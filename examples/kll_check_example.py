"""Assert on a KLL quantile sketch inside a verification run
(reference `examples/KLLCheckExample.scala`)."""

from deequ_tpu import Check, CheckLevel, CheckStatus, Dataset, VerificationSuite
from deequ_tpu.analyzers import KLLParameters
from deequ_tpu.constraints import ConstraintStatus

from .example_utils import SAMPLE_ITEMS, items_as_dataset


def main():
    data = items_as_dataset(*SAMPLE_ITEMS)
    # the reference casts numViews to double first
    new_data = Dataset.from_dict(
        {"numViews": [float(i.num_views) for i in SAMPLE_ITEMS]}
    )

    verification_result = (
        VerificationSuite.on_data(new_data)
        .add_check(
            Check(CheckLevel.ERROR, "integrity checks")
            # we expect 5 records
            .has_size(lambda size: size == 5)
            # we expect the maximum of views to be not more than 10
            .has_max("numViews", lambda v: v <= 10)
            # we expect the sketch size to be at least 16
            .kll_sketch_satisfies(
                "numViews",
                lambda dist: dist.parameters[1] >= 16,
                kll_parameters=KLLParameters(2, 0.64, 2),
            )
        )
        .run()
    )

    if verification_result.status == CheckStatus.SUCCESS:
        print("The data passed the test, everything is fine!")
    else:
        print("We found errors in the data, the following constraints were not satisfied:\n")
        for check_result in verification_result.check_results.values():
            for result in check_result.constraint_results:
                if result.status != ConstraintStatus.SUCCESS:
                    print(f"{result.constraint} failed: {result.message}")

    return verification_result


if __name__ == "__main__":
    main()
