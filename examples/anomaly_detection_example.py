"""Detect anomalous changes in metrics over time: yesterday's Size is stored
in a repository, today's more-than-doubled Size trips the anomaly check
(reference `examples/AnomalyDetectionExample.scala`)."""

import time

from deequ_tpu import (
    CheckStatus,
    InMemoryMetricsRepository,
    ResultKey,
    VerificationSuite,
)
from deequ_tpu.analyzers import Size
from deequ_tpu.anomalydetection import RelativeRateOfChangeStrategy

from .example_utils import SAMPLE_ITEMS, items_as_dataset


def main():
    # anomaly detection operates on metrics stored in a metric repository
    metrics_repository = InMemoryMetricsRepository()
    now_ms = int(time.time() * 1000)

    # yesterday, the data had only two rows
    yesterdays_key = ResultKey(now_ms - 24 * 60 * 1000)
    yesterdays_dataset = items_as_dataset(*SAMPLE_ITEMS[:2])

    (
        VerificationSuite.on_data(yesterdays_dataset)
        .use_repository(metrics_repository)
        .save_or_append_result(yesterdays_key)
        .add_anomaly_check(
            RelativeRateOfChangeStrategy(max_rate_increase=2.0), Size()
        )
        .run()
    )

    # today's data has five rows: the size more than doubled
    todays_key = ResultKey(now_ms)
    todays_dataset = items_as_dataset(*SAMPLE_ITEMS)

    verification_result = (
        VerificationSuite.on_data(todays_dataset)
        .use_repository(metrics_repository)
        .save_or_append_result(todays_key)
        .add_anomaly_check(
            RelativeRateOfChangeStrategy(max_rate_increase=2.0), Size()
        )
        .run()
    )

    if verification_result.status != CheckStatus.SUCCESS:
        print("Anomaly detected in the Size() metric!")
        frame = (
            metrics_repository.load()
            .for_analyzers([Size()])
            .get_success_metrics_as_data_frame()
        )
        print(frame)

    return verification_result


if __name__ == "__main__":
    main()
