"""Store computed metrics in a repository and query the history by key, time
window and tags (reference `examples/MetricsRepositoryExample.scala`)."""

import tempfile
import time
from pathlib import Path

from deequ_tpu import (
    Check,
    CheckLevel,
    FileSystemMetricsRepository,
    ResultKey,
    VerificationSuite,
)
from deequ_tpu.analyzers import Completeness

from .example_utils import SAMPLE_ITEMS, items_as_dataset


def main():
    data = items_as_dataset(*SAMPLE_ITEMS)

    # a json file in which the computed metrics will be stored
    metrics_file = str(Path(tempfile.mkdtemp()) / "metrics.json")
    repository = FileSystemMetricsRepository(metrics_file)

    # the key under which results are stored: a timestamp plus arbitrary tags
    now_ms = int(time.time() * 1000)
    result_key = ResultKey(now_ms, {"tag": "repositoryExample"})

    (
        VerificationSuite.on_data(data)
        .add_check(
            Check(CheckLevel.ERROR, "integrity checks")
            .has_size(lambda size: size == 5)
            .is_complete("id")
            .is_complete("productName")
            .is_contained_in("priority", ["high", "low"])
            .is_non_negative("numViews")
        )
        .use_repository(repository)
        .save_or_append_result(result_key)
        .run()
    )

    # load the metric for a particular analyzer stored under our result key
    completeness_of_product_name = (
        repository.load_by_key(result_key).metric(Completeness("productName")).value.get()
    )
    print(f"The completeness of the productName column is: {completeness_of_product_name}")

    # query all metrics from the last 10 minutes as json
    json_metrics = (
        repository.load().after(now_ms - 10 * 60 * 1000).get_success_metrics_as_json()
    )
    print(f"Metrics from the last 10 minutes:\n{json_metrics}")

    # query by tag value, result as a dataframe
    frame = (
        repository.load()
        .with_tag_values({"tag": "repositoryExample"})
        .get_success_metrics_as_data_frame()
    )
    print(frame)
    return frame


if __name__ == "__main__":
    main()
