"""Scale a verification run over a device mesh — the TPU-native capability
the Scala reference delegates to the Spark cluster (SURVEY.md §2.9: data
parallelism over row partitions with algebraic state merge).

Three equivalent ways to use many chips, all built on the same semigroup
state algebra (`analyzers/Analyzer.scala:34-53` in the reference):

1. **Sharded streaming scan** — hand the engine a `jax.sharding.Mesh`; the
   fused per-batch program row-shards the feature arrays and XLA inserts the
   cross-device partial-reduce collectives (Spark's partial agg + shuffle,
   compiled, riding ICI).
2. **Independent shard scans + collective merge** — run one engine per data
   shard (e.g. one per host in a pod), then butterfly-merge the per-shard
   states with `collective_merge_states` (the `rdd.treeReduce` analog,
   reference `analyzers/runners/KLLRunner.scala:104-112`).
3. **Persisted states + `run_on_aggregated_states`** — no collective at
   all: shard states round-trip through a StateProvider (local or
   object-store URI) and merge offline, exactly like the reference's
   partitioned-table refresh (`AnalysisRunner.scala:385-460`).

This example runs all three on whatever devices the process sees (the test
conftest provides an 8-virtual-device CPU mesh; on a TPU pod slice the same
code uses the real chips) and asserts they produce identical metrics.
"""

from __future__ import annotations

import numpy as np


def main():
    import jax

    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        KLLParameters,
        KLLSketch,
        Mean,
        Size,
        StandardDeviation,
    )
    from deequ_tpu.data import Dataset
    from deequ_tpu.parallel import collective_merge_states, make_mesh
    from deequ_tpu.runners import AnalysisRunner
    from deequ_tpu.runners.engine import ScanEngine

    n_devices = min(len(jax.devices()), 8)
    mesh = make_mesh(n_devices)
    analyzers = [
        Size(),
        Completeness("latency_ms"),
        Mean("latency_ms"),
        StandardDeviation("latency_ms"),
        ApproxCountDistinct("endpoint"),
        KLLSketch("latency_ms", KLLParameters(256, 0.64, 10)),
    ]

    rng = np.random.default_rng(0)
    rows = 4096 * n_devices
    latency = rng.gamma(2.0, 30.0, rows)
    endpoint = rng.integers(0, 200, rows)
    data = Dataset.from_dict({"latency_ms": latency, "endpoint": endpoint})

    # 1) sharded streaming scan: ONE engine over the whole mesh
    ctx_sharded = AnalysisRunner.do_analysis_run(
        data, analyzers, batch_size=rows, sharding=mesh, placement="device"
    )

    # 2) per-shard scans + explicit collective merge (ONE engine reused:
    #    identical analyzers/shapes share the same compiled program)
    shard_rows = rows // n_devices
    shard_engine = ScanEngine(analyzers, placement="device")
    per_shard_states = []
    for d in range(n_devices):
        shard = Dataset.from_dict(
            {
                "latency_ms": latency[d * shard_rows : (d + 1) * shard_rows],
                "endpoint": endpoint[d * shard_rows : (d + 1) * shard_rows],
            }
        )
        states, _ = shard_engine.run(shard)
        per_shard_states.append(states)
    stacked = tuple(
        jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]),
            *[s[i] for s in per_shard_states],
        )
        for i in range(len(analyzers))
    )
    # scalar metrics only: the KLL quantile sketch is compared via its own
    # rank-error tests, not exact equality. Filtering happens BEFORE the
    # value is computed, so excluded metrics are never evaluated.
    def scalar_metrics(pairs, value_of):
        return {
            a.name: value_of(a, x) for a, x in pairs if a.name != "KLLSketch"
        }

    merged = collective_merge_states(analyzers, mesh, stacked)
    metrics_merged = scalar_metrics(
        zip(analyzers, merged),
        lambda a, m: a.compute_metric_from(
            jax.tree_util.tree_map(np.asarray, jax.device_get(m))
        ).value.get(),
    )

    # 3) offline: persist per-shard states, refresh metrics with no rescan
    from deequ_tpu.analyzers.state_provider import InMemoryStateProvider

    providers = []
    for d, states in enumerate(per_shard_states):
        provider = InMemoryStateProvider()
        for a, s in zip(analyzers, states):
            provider.persist(a, jax.tree_util.tree_map(np.asarray, s))
        providers.append(provider)
    ctx_offline = AnalysisRunner.run_on_aggregated_states(
        data.schema, analyzers, providers
    )

    get_value = lambda a, m: m.value.get()  # noqa: E731
    metrics_sharded = scalar_metrics(ctx_sharded.metric_map.items(), get_value)
    metrics_offline = scalar_metrics(ctx_offline.metric_map.items(), get_value)
    for name, want in metrics_sharded.items():
        for variant, got_map in (("merged", metrics_merged), ("offline", metrics_offline)):
            got = got_map[name]
            assert abs(got - want) <= 1e-9 * max(1.0, abs(want)), (
                name, variant, got, want,
            )

    print(f"mesh: {n_devices} devices; all three distribution modes agree:")
    for name, value in sorted(metrics_sharded.items()):
        print(f"  {name}: {value:.6g}")
    return metrics_sharded, metrics_merged, metrics_offline


if __name__ == "__main__":
    main()
