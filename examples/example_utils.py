"""Shared fixtures for the examples (reference `examples/ExampleUtils.scala`
and `examples/entities.scala`)."""

from dataclasses import dataclass
from typing import Optional

import pyarrow as pa

from deequ_tpu import Dataset


@dataclass
class Item:
    id: int
    product_name: Optional[str]
    description: Optional[str]
    priority: Optional[str]
    num_views: int


@dataclass
class Manufacturer:
    id: int
    manufacturer_name: Optional[str]
    country_code: str


@dataclass
class RawData:
    """Raw, mostly-string records, e.g. from a csv file (reference
    `examples/DataProfilingExample.scala` RawData)."""

    product_name: str
    total_number: Optional[str]
    status: str
    valuable: Optional[str]


def items_as_dataset(*items: Item) -> Dataset:
    # explicit types, like the reference's typed Item case class: an
    # all-null partition must still be a STRING column, not a null column
    return Dataset.from_arrow(
        pa.table(
            {
                "id": pa.array([i.id for i in items], type=pa.int64()),
                "productName": pa.array([i.product_name for i in items], type=pa.string()),
                "description": pa.array([i.description for i in items], type=pa.string()),
                "priority": pa.array([i.priority for i in items], type=pa.string()),
                "numViews": pa.array([i.num_views for i in items], type=pa.int64()),
            }
        )
    )


def manufacturers_as_dataset(*manufacturers: Manufacturer) -> Dataset:
    return Dataset.from_arrow(
        pa.table(
            {
                "id": pa.array([m.id for m in manufacturers], type=pa.int64()),
                "manufacturerName": pa.array(
                    [m.manufacturer_name for m in manufacturers], type=pa.string()
                ),
                "countryCode": pa.array(
                    [m.country_code for m in manufacturers], type=pa.string()
                ),
            }
        )
    )


def raw_data_as_dataset(*rows: RawData) -> Dataset:
    return Dataset.from_arrow(
        pa.table(
            {
                "productName": pa.array([r.product_name for r in rows], type=pa.string()),
                "totalNumber": pa.array([r.total_number for r in rows], type=pa.string()),
                "status": pa.array([r.status for r in rows], type=pa.string()),
                "valuable": pa.array([r.valuable for r in rows], type=pa.string()),
            }
        )
    )


SAMPLE_ITEMS = (
    Item(1, "Thingy A", "awesome thing.", "high", 0),
    Item(2, "Thingy B", "available at http://thingb.com", None, 0),
    Item(3, None, None, "low", 5),
    Item(4, "Thingy D", "checkout https://thingd.ca", "low", 10),
    Item(5, "Thingy E", None, "high", 12),
)

SAMPLE_RAW_DATA = (
    RawData("thingA", "13.0", "IN_TRANSIT", "true"),
    RawData("thingA", "5", "DELAYED", "false"),
    RawData("thingB", None, "DELAYED", None),
    RawData("thingC", None, "IN_TRANSIT", "false"),
    RawData("thingD", "1.0", "DELAYED", "true"),
    RawData("thingC", "7.0", "UNKNOWN", None),
    RawData("thingC", "20", "UNKNOWN", None),
    RawData("thingE", "20", "DELAYED", "false"),
)
