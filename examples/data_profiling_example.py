"""Profile raw (mostly string) data: completeness, approximate distinct
counts, inferred types, numeric statistics after casting, and full value
distributions for low-cardinality columns
(reference `examples/DataProfilingExample.scala`)."""

from deequ_tpu.profiles import ColumnProfilerRunner, NumericColumnProfile

from .example_utils import SAMPLE_RAW_DATA, raw_data_as_dataset


def main():
    raw_data = raw_data_as_dataset(*SAMPLE_RAW_DATA)

    # three passes over the data, no shuffles
    result = ColumnProfilerRunner.on_data(raw_data).run()

    # a profile for each column: completeness, approx distinct count,
    # inferred datatype
    for product_name, profile in result.profiles.items():
        print(
            f"Column '{product_name}':\n"
            f"\tcompleteness: {profile.completeness}\n"
            f"\tapproximate number of distinct values: "
            f"{profile.approximate_num_distinct_values}\n"
            f"\tdatatype: {profile.data_type}\n"
        )

    # numeric columns get descriptive statistics ('totalNumber' is a string
    # column whose values are numeric, so the profiler casts it)
    total_number_profile = result.profiles["totalNumber"]
    assert isinstance(total_number_profile, NumericColumnProfile)
    print(
        "Statistics of 'totalNumber':\n"
        f"\tminimum: {total_number_profile.minimum}\n"
        f"\tmaximum: {total_number_profile.maximum}\n"
        f"\tmean: {total_number_profile.mean}\n"
        f"\tstandard deviation: {total_number_profile.std_dev}\n"
    )

    # low-cardinality columns get the full value distribution
    status_profile = result.profiles["status"]
    print("Value distribution in 'status':")
    if status_profile.histogram is not None:
        for key, entry in status_profile.histogram.values.items():
            print(f"\t{key} occurred {entry.absolute} times (ratio is {entry.ratio})")

    return result


if __name__ == "__main__":
    main()
