"""Incrementally update metrics on a growing dataset from persisted states —
no rescan of the old data (reference `examples/IncrementalMetricsExample.scala`;
the algebra is `analyzers/Analyzer.scala:107-128` aggregateWith)."""

from deequ_tpu.analyzers import ApproxCountDistinct, Completeness, Size
from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.builder import Analysis

from .example_utils import Item, items_as_dataset


def main():
    data = items_as_dataset(
        Item(1, "Thingy A", "awesome thing.", "high", 0),
        Item(2, "Thingy B", "available tomorrow", "low", 0),
        Item(3, "Thing C", None, None, 5),
    )
    more_data = items_as_dataset(
        Item(4, "Thingy D", None, "low", 10),
        Item(5, "Thingy E", None, "high", 12),
    )

    analysis = (
        Analysis()
        .add_analyzer(Size())
        .add_analyzer(ApproxCountDistinct("id"))
        .add_analyzer(Completeness("productName"))
        .add_analyzer(Completeness("description"))
    )

    state_store = InMemoryStateProvider()

    # persist the internal state of the computation
    metrics_for_data = analysis.run(data, save_states_with=state_store)

    # continue from the stored states WITHOUT touching the previous data
    metrics_after_adding_more_data = analysis.run(more_data, aggregate_with=state_store)

    print("Metrics for the first 3 records:\n")
    for analyzer, metric in metrics_for_data.metric_map.items():
        print(f"\t{analyzer}: {metric.value.get()}")

    print("\nMetrics after adding 2 more records:\n")
    for analyzer, metric in metrics_after_adding_more_data.metric_map.items():
        print(f"\t{analyzer}: {metric.value.get()}")

    return metrics_for_data, metrics_after_adding_more_data


if __name__ == "__main__":
    main()
