"""Profile with explicit KLL sketch parameters and inspect the resulting
quantile sketch: buckets, parameters, raw compactor buffers
(reference `examples/KLLExample.scala`)."""

from deequ_tpu.analyzers import KLLParameters
from deequ_tpu.profiles import NumericColumnProfile
from deequ_tpu.suggestions import ConstraintSuggestionRunner, Rules

from .example_utils import SAMPLE_ITEMS, items_as_dataset


def main():
    df = items_as_dataset(*SAMPLE_ITEMS)

    suggestion_result = (
        ConstraintSuggestionRunner.on_data(df)
        .add_constraint_rules(Rules.DEFAULT)
        .set_kll_parameters(KLLParameters(2, 0.64, 2))
        .run()
    )

    column_profiles = suggestion_result.column_profiles

    print("Observed statistics:")
    for name, profile in column_profiles.items():
        print(f"Feature '{name}': ")
        if isinstance(profile, NumericColumnProfile):
            print(
                f"\tminimum: {profile.minimum}\n"
                f"\tmaximum: {profile.maximum}\n"
                f"\tmean: {profile.mean}\n"
                f"\tstandard deviation: {profile.std_dev}"
            )
            kll_metric = profile.kll
            if kll_metric is not None:
                print("\tKLL buckets:")
                for item in kll_metric.buckets:
                    print(
                        f"\t\tlow_value: {item.low_value} "
                        f"high_value: {item.high_value} count: {item.count}"
                    )
                print(
                    f"\tparameters: c: {kll_metric.parameters[0]}, "
                    f"k: {kll_metric.parameters[1]}"
                )
                print(f"\tcompactor buffers: {kll_metric.data}")
        elif profile.histogram is not None:
            for key, entry in profile.histogram.values.items():
                print(f"\t{key} occurred {entry.absolute} times (ratio is {entry.ratio})")

    return suggestion_result


if __name__ == "__main__":
    main()
