"""Runnable examples, ported from the reference's examples suite
(`/root/reference/src/main/scala/com/amazon/deequ/examples/`). Each module
exposes ``main()`` so the examples double as end-to-end tests
(tests/test_examples.py — the `ExamplesTest.scala` analog)."""
