"""Continuous verification service: multiple tenants sharing one scheduler,
streaming micro-batch sessions with checks evaluated on every merge, a
deliberately injected transient failure that retries to success, admission
control shedding a burst, and the Prometheus/JSON export plane.

The one-shot examples call ``VerificationSuite.run()`` directly; a
production deployment instead keeps ONE `VerificationService` per process
and routes every tenant's work through it — warm compiled programs are
shared, cold compiles stay off the queue, and an operator scrapes
``/metrics``.
"""

from __future__ import annotations

import numpy as np

from deequ_tpu import Check, CheckLevel, CheckStatus, Dataset, VerificationSuite
from deequ_tpu.service import (
    Priority,
    ServiceOverloaded,
    TransientFailure,
    VerificationService,
)


def clickstream_batch(rows: int, seed: int, null_fraction: float = 0.0) -> Dataset:
    rng = np.random.default_rng(seed)
    ids = np.arange(rows) + seed * 1_000_000
    latency = rng.lognormal(3.0, 0.3, rows)
    if null_fraction:
        # genuine Arrow NULLs (NaN would count as present for Completeness)
        drop = rng.random(rows) < null_fraction
        latency = [None if d else float(v) for d, v in zip(drop, latency)]
    return Dataset.from_dict(
        {
            "event_id": ids,
            "latency_ms": latency,
            "country": rng.choice(["US", "DE", "JP"], rows),
        }
    )


def main():
    service = VerificationService(workers=2, max_queue_depth=4)

    # -- tenant A: a streaming session over a growing clickstream ----------
    checks = [
        Check(CheckLevel.ERROR, "clickstream integrity")
        .is_complete("event_id")
        .is_unique("event_id"),
        Check(CheckLevel.WARNING, "latency quality").has_completeness(
            "latency_ms", lambda c: c > 0.95
        ),
    ]
    session = service.session("tenant-a", "clickstream", checks)
    stream_statuses = []
    for batch_no in range(3):
        # batch 2 arrives with 20% nulls: the WARNING surfaces on THAT
        # merge, mid-stream, not at end-of-day
        batch = clickstream_batch(
            500, seed=batch_no, null_fraction=0.2 if batch_no == 2 else 0.0
        )
        result = session.ingest(batch)
        stream_statuses.append(result.status)
        print(f"[tenant-a] batch {batch_no}: {result.status.value}")

    # -- tenant B: one-shot jobs, one of which fails transiently -----------
    orders = Dataset.from_dict(
        {"order_id": [1, 2, 3, 4, 5], "amount": [10.0, 20.5, 7.0, 99.0, 3.2]}
    )
    order_check = Check(CheckLevel.ERROR, "orders").is_complete(
        "order_id"
    ).is_non_negative("amount")
    ok_handle = service.submit_verification(
        orders, [order_check], tenant="tenant-b", priority=Priority.HIGH
    )

    # injected fault: the first attempt dies with a TransientFailure (a
    # flaky feed link, say); the scheduler retries with backoff and the
    # second attempt verifies for real
    attempts = []

    def flaky_verification(ctx):
        attempts.append(ctx.attempt)
        if ctx.attempt == 1:
            raise TransientFailure("injected: feed link reset mid-run")
        return VerificationSuite.do_verification_run(
            orders, [order_check], monitor=ctx.monitor, placement=ctx.placement
        )

    flaky_handle = service.scheduler.submit(
        flaky_verification, tenant="tenant-b", max_retries=2, retry_backoff_s=0.02
    )

    ok_result = ok_handle.result(timeout=300)
    flaky_result = flaky_handle.result(timeout=300)
    print(f"[tenant-b] one-shot: {ok_result.status.value}")
    print(
        f"[tenant-b] flaky job: {flaky_result.status.value} after "
        f"{flaky_handle.attempts} attempts (injected failure retried)"
    )

    # -- admission control: a burst beyond the queue bound is SHED ---------
    import threading

    gate = threading.Event()
    for _ in range(2):  # occupy both workers so the queue actually fills
        service.scheduler.submit(lambda ctx: gate.wait(60))
    shed = 0
    for _ in range(12):
        try:
            service.scheduler.submit(lambda ctx: None, tenant="burst")
        except ServiceOverloaded:
            shed += 1
    gate.set()
    print(f"[burst] {shed} of 12 burst jobs shed with ServiceOverloaded")

    snapshot = service.json_snapshot()
    prom = service.prometheus_text()
    print("\n--- /metrics (excerpt) ---")
    for line in prom.splitlines():
        if "jobs_" in line or "queue_depth" in line or "stream_" in line:
            print(line)

    service.close()
    return stream_statuses, flaky_handle, shed, snapshot


if __name__ == "__main__":
    statuses, handle, shed, _ = main()
    assert statuses[2] == CheckStatus.WARNING, "mid-stream anomaly must surface"
    assert handle.attempts == 2 and shed > 0
