"""Declare checks on a small Item table and verify them — the canonical
entry-point walkthrough (reference `examples/BasicExample.scala`)."""

from deequ_tpu import Check, CheckLevel, CheckStatus, VerificationSuite
from deequ_tpu.constraints import ConstraintStatus

from .example_utils import SAMPLE_ITEMS, items_as_dataset


def main():
    data = items_as_dataset(*SAMPLE_ITEMS)

    verification_result = (
        VerificationSuite.on_data(data)
        .add_check(
            Check(CheckLevel.ERROR, "integrity checks")
            # we expect 5 records
            .has_size(lambda size: size == 5)
            # 'id' should never be NULL
            .is_complete("id")
            # 'id' should not contain duplicates
            .is_unique("id")
            # 'productName' should never be NULL
            .is_complete("productName")
            # 'priority' should only contain the values "high" and "low"
            .is_contained_in("priority", ["high", "low"])
            # 'numViews' should not contain negative values
            .is_non_negative("numViews")
        )
        .add_check(
            Check(CheckLevel.WARNING, "distribution checks")
            # at least half of the 'description's should contain a url
            .contains_url("description", lambda ratio: ratio >= 0.5)
            # half of the items should have less than 10 'numViews'
            .has_approx_quantile("numViews", 0.5, lambda median: median <= 10)
        )
        .run()
    )

    if verification_result.status == CheckStatus.SUCCESS:
        print("The data passed the test, everything is fine!")
    else:
        print("We found errors in the data, the following constraints were not satisfied:\n")
        for check_result in verification_result.check_results.values():
            for result in check_result.constraint_results:
                if result.status != ConstraintStatus.SUCCESS:
                    print(f"{result.constraint} failed: {result.message}")

    return verification_result


if __name__ == "__main__":
    main()
