"""Maintain table-level metrics over partitioned data: compute state per
partition, derive metrics from merged states, refresh one partition without
touching the others (reference
`examples/UpdateMetricsOnPartitionedDataExample.scala:60-92`, engine path
`AnalysisRunner.runOnAggregatedStates`, `AnalysisRunner.scala:385-460`)."""

from deequ_tpu import Check, CheckLevel
from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.builder import Analysis

from .example_utils import Manufacturer, manufacturers_as_dataset


def main():
    # a table of manufacturers stored/processed partitioned by country code
    de_manufacturers = manufacturers_as_dataset(
        Manufacturer(1, "ManufacturerA", "DE"),
        Manufacturer(2, "ManufacturerB", "DE"),
    )
    us_manufacturers = manufacturers_as_dataset(
        Manufacturer(3, "ManufacturerD", "US"),
        Manufacturer(4, "ManufacturerE", "US"),
        Manufacturer(5, "ManufacturerF", "US"),
    )
    cn_manufacturers = manufacturers_as_dataset(
        Manufacturer(6, "ManufacturerG", "CN"),
        Manufacturer(7, "ManufacturerH", "CN"),
    )

    # constraints on the table as a whole
    check = (
        Check(CheckLevel.WARNING, "a check")
        .is_complete("manufacturerName")
        .contains_url("manufacturerName", lambda ratio: ratio == 0.0)
        .is_contained_in("countryCode", ["DE", "US", "CN"])
    )
    analysis = Analysis(check.required_analyzers())

    # compute and store the state of the metrics per partition
    de_states = InMemoryStateProvider()
    us_states = InMemoryStateProvider()
    cn_states = InMemoryStateProvider()
    analysis.run(de_manufacturers, save_states_with=de_states)
    analysis.run(us_manufacturers, save_states_with=us_states)
    analysis.run(cn_manufacturers, save_states_with=cn_states)

    # metrics for the whole table from the partition states alone —
    # the data is not touched again
    table_metrics = AnalysisRunner.run_on_aggregated_states(
        de_manufacturers.schema, analysis.analyzers, [de_states, us_states, cn_states]
    )
    print("Metrics for the whole table:\n")
    for analyzer, metric in table_metrics.metric_map.items():
        print(f"\t{analyzer}: {metric.value.get()}")

    # a single partition changes: recompute ONLY its state
    updated_us = manufacturers_as_dataset(
        Manufacturer(3, "ManufacturerDNew", "US"),
        Manufacturer(4, None, "US"),
        Manufacturer(5, "ManufacturerFNew http://clickme.com", "US"),
    )
    updated_us_states = InMemoryStateProvider()
    analysis.run(updated_us, save_states_with=updated_us_states)

    updated_table_metrics = AnalysisRunner.run_on_aggregated_states(
        de_manufacturers.schema,
        analysis.analyzers,
        [de_states, updated_us_states, cn_states],
    )
    print("Metrics for the whole table after updating the US partition:\n")
    for analyzer, metric in updated_table_metrics.metric_map.items():
        print(f"\t{analyzer}: {metric.value.get()}")

    return table_metrics, updated_table_metrics


if __name__ == "__main__":
    main()
