"""Let the library suggest constraints from a data profile: the rules are
heuristics, so review the suggestions before applying them in production
(reference `examples/ConstraintSuggestionExample.scala`)."""

from deequ_tpu.suggestions import ConstraintSuggestionRunner, Rules

from .example_utils import SAMPLE_RAW_DATA, RawData, raw_data_as_dataset


def main():
    # twice the raw-data rows, with a little numeric variation
    data = raw_data_as_dataset(
        *SAMPLE_RAW_DATA,
        RawData("thingA", "13.0", "IN_TRANSIT", "true"),
        RawData("thingA", "5", "DELAYED", "false"),
        RawData("thingB", None, "DELAYED", None),
        RawData("thingC", None, "IN_TRANSIT", "false"),
        RawData("thingD", "1.0", "DELAYED", "true"),
        RawData("thingC", "17.0", "UNKNOWN", None),
        RawData("thingC", "22", "UNKNOWN", None),
        RawData("thingE", "23", "DELAYED", "false"),
    )

    # profile the data, then apply the default rule set to suggest constraints
    suggestion_result = (
        ConstraintSuggestionRunner.on_data(data)
        .add_constraint_rules(Rules.DEFAULT)
        .run()
    )

    # each suggestion comes with a textual description and runnable code
    for column, suggestions in suggestion_result.constraint_suggestions.items():
        for suggestion in suggestions:
            print(
                f"Constraint suggestion for '{column}':\t{suggestion.description}\n"
                f"The corresponding code is {suggestion.code_for_constraint}\n"
            )

    return suggestion_result


if __name__ == "__main__":
    main()
