"""PackedScanProgram: the packed-carry fused scan (engine.py).

Pins the round-4 fusion-root redesign: all scalar state leaves ride ONE
stacked float vector + ONE stacked int vector through the per-batch device
program (XLA fuses sibling reductions only when they share an output root —
with per-analyzer scalar carries each reduction recomputed a full pass over
the batch). These tests freeze the contract the speedup rests on:

- pack/unpack is a lossless bijection for every state type in the battery;
- the packed chain computes bit-identical states to folding each analyzer's
  ``update`` directly;
- int counters round-trip exactly through the int vector even at magnitudes
  where a float slot would corrupt them.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Correlation,
    DataType,
    KLLParameters,
    KLLSketch,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners.engine import PackedScanProgram, _fused_program, ScanEngine


def battery():
    return (
        Size(),
        Completeness("x"),
        Mean("x"),
        Sum("x"),
        Minimum("x"),
        Maximum("x"),
        StandardDeviation("x"),
        Correlation("x", "y"),
        DataType("s"),
        ApproxCountDistinct("y"),
        KLLSketch("x", KLLParameters(256, 0.64, 10)),
    )


def make_features(engine, rows=512, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, rows)
    x[rng.random(rows) < 0.1] = np.nan
    data = Dataset.from_dict(
        {
            "x": x,
            "y": rng.integers(0, 100, rows),
            "s": np.array(
                [["12", "ab", "3.5", "true", ""][i % 5] for i in range(rows)],
                dtype=object,
            ),
        }
    )
    batch = next(iter(data.batches(rows, columns=engine.required_columns())))
    return engine._prepare(batch)


class TestPackedScanProgram:
    def test_init_carry_unpacks_to_init_states(self):
        analyzers = battery()
        prog = _fused_program(analyzers, None)
        states = jax.tree_util.tree_map(np.asarray, prog.unpack(prog.init_carry()))
        for a, s in zip(analyzers, states):
            ref = a.init_state()
            for got, want in zip(
                jax.tree_util.tree_leaves(s), jax.tree_util.tree_leaves(ref)
            ):
                got, want = np.asarray(got), np.asarray(want)
                assert got.dtype == want.dtype, (a.name, got.dtype, want.dtype)
                assert got.shape == want.shape, (a.name, got.shape, want.shape)
                np.testing.assert_array_equal(got, want, err_msg=a.name)

    def test_packed_chain_equals_direct_update_fold(self):
        analyzers = battery()
        prog = PackedScanProgram(analyzers, None)
        engine = ScanEngine(list(analyzers), placement="device")

        carry = prog.init_carry()
        direct = tuple(a.init_state() for a in analyzers)
        direct_step = jax.jit(
            lambda sts, f: tuple(
                a.update(s, f) for a, s in zip(analyzers, sts)
            )
        )
        for seed in range(3):
            features = make_features(engine, seed=seed)
            carry = prog(carry, features)
            direct = direct_step(direct, features)
        packed_states = jax.tree_util.tree_map(np.asarray, prog.unpack(carry))
        direct_states = jax.tree_util.tree_map(np.asarray, direct)
        for a, ps, ds in zip(analyzers, packed_states, direct_states):
            for got, want in zip(
                jax.tree_util.tree_leaves(ps), jax.tree_util.tree_leaves(ds)
            ):
                np.testing.assert_array_equal(
                    np.asarray(got), np.asarray(want), err_msg=a.name
                )

    def test_int_counters_round_trip_exactly_at_large_magnitudes(self):
        # 2^40 + 3 is representable in int64/f64 but NOT in f32 — a float
        # slot in 32-bit mode would corrupt it; the int vector must not
        analyzers = (Size(),)
        prog = PackedScanProgram(analyzers, None)
        big = np.int64((1 << 40) + 3)
        state = analyzers[0].init_state().__class__(
            jnp.asarray(big, dtype=jnp.int64)
        )
        carry = prog._pack((state,))
        (roundtrip,) = jax.tree_util.tree_map(np.asarray, prog._unpack(carry))
        assert int(jax.tree_util.tree_leaves(roundtrip)[0]) == int(big)

    def test_program_cache_returns_same_packed_program(self):
        analyzers = battery()
        assert _fused_program(analyzers, None) is _fused_program(analyzers, None)
