"""Multi-device tests on the 8-device CPU mesh (conftest forces
--xla_force_host_platform_device_count=8): sharded scan == single-device
scan, collective state merges == sequential merges — the analog of the
reference forcing 2 shuffle partitions (`SparkContextSpec.scala:75-84`)."""

import jax
import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    KLLSketch,
    KLLParameters,
    Mean,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.data import Dataset
from deequ_tpu.parallel import collective_merge_states, make_mesh
from deequ_tpu.runners import AnalysisRunner


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh(8)


@pytest.fixture(scope="module")
def big_data():
    import pyarrow as pa

    rng = np.random.default_rng(0)
    n = 40000
    x = rng.normal(5, 2, n)
    null_mask = rng.random(n) < 0.1  # genuine nulls, not NaN values
    return Dataset.from_arrow(
        pa.table(
            {
                "x": pa.array(x, mask=null_mask),
                "y": pa.array(rng.integers(0, 500, n)),
            }
        )
    )


ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    Sum("x"),
    StandardDeviation("x"),
    ApproxCountDistinct("y"),
    KLLSketch("x", KLLParameters(512, 0.64, 10)),
]


class TestShardedScan:
    def test_sharded_equals_single_device(self, mesh, big_data):
        plain = AnalysisRunner.do_analysis_run(big_data, ANALYZERS, batch_size=8192)
        sharded = AnalysisRunner.do_analysis_run(
            big_data, ANALYZERS, batch_size=8192, sharding=mesh
        )
        for a in ANALYZERS[:-1]:
            pv = plain.metric(a).value.get()
            sv = sharded.metric(a).value.get()
            assert pv == pytest.approx(sv, rel=1e-12), a
        # KLL: distributed sort changes nothing semantically; bucket counts
        # must still sum to the count and quantiles stay within error bounds
        pk = plain.metric(ANALYZERS[-1]).value.get()
        sk = sharded.metric(ANALYZERS[-1]).value.get()
        assert sum(b.count for b in sk.buckets) == sum(b.count for b in pk.buckets)

    def test_odd_batch_sizes_pad_to_mesh(self, mesh):
        data = Dataset.from_dict({"x": np.arange(1000, dtype=np.float64)})
        ctx = AnalysisRunner.do_analysis_run(
            data, [Size(), Mean("x")], batch_size=333, sharding=mesh
        )
        assert ctx.metric(Size()).value.get() == 1000.0
        assert ctx.metric(Mean("x")).value.get() == pytest.approx(499.5)


class TestCollectiveMerge:
    def test_matches_sequential_merge(self, mesh):
        rng = np.random.default_rng(1)
        analyzers = [Mean("x"), StandardDeviation("x"), ApproxCountDistinct("y")]
        # build 8 per-device states by folding 8 different row shards
        from deequ_tpu.runners.engine import ScanEngine

        per_analyzer_states = []
        all_states = []
        for d in range(8):
            data = Dataset.from_dict(
                {
                    "x": rng.normal(d, 1, 1000),
                    "y": rng.integers(0, 100, 1000),
                }
            )
            engine = ScanEngine(analyzers)
            states, _ = engine.run(data)
            all_states.append(states)
        # stack: per analyzer, leaves get leading device dim
        stacked = tuple(
            jax.tree_util.tree_map(lambda *xs: np.stack(xs), *[s[i] for s in all_states])
            for i in range(len(analyzers))
        )
        merged = collective_merge_states(analyzers, mesh, stacked)
        for i, a in enumerate(analyzers):
            seq = all_states[0][i]
            for d in range(1, 8):
                seq = a.merge(seq, all_states[d][i])
            m_collective = a.compute_metric_from(
                jax.tree_util.tree_map(np.asarray, merged[i])
            )
            m_seq = a.compute_metric_from(seq)
            assert m_collective.value.get() == pytest.approx(
                m_seq.value.get(), rel=1e-12
            )


class TestCollectiveMergeNonPow2:
    def test_three_device_mesh_gather_path(self):
        """Non-power-of-two meshes take the all_gather + local-fold path and
        must still fold every shard exactly once."""
        from deequ_tpu.analyzers import Size
        from deequ_tpu.runners.engine import ScanEngine

        analyzers = [Size(), Mean("x")]
        shard_states = []
        for d in range(5):
            data = Dataset.from_dict({"x": np.full(10 * (d + 1), float(d))})
            states, _ = ScanEngine(analyzers).run(data)
            shard_states.append(states)
        stacked = tuple(
            jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *[s[i] for s in shard_states]
            )
            for i in range(len(analyzers))
        )
        mesh3 = make_mesh(3)
        merged = collective_merge_states(analyzers, mesh3, stacked)
        assert int(np.asarray(merged[0].num_matches)) == 10 + 20 + 30 + 40 + 50
        expected_mean = sum(10 * (d + 1) * d for d in range(5)) / 150
        got = float(np.asarray(merged[1].total) / np.asarray(merged[1].count))
        assert got == pytest.approx(expected_mean)


class TestReviewRegressions:
    def test_merge_more_shards_than_devices(self, mesh):
        """8 persisted shard states on any mesh must fold ALL shards."""
        from deequ_tpu.analyzers import Size
        from deequ_tpu.runners.engine import ScanEngine

        analyzers = [Size()]
        shard_states = []
        for d in range(8):
            data = Dataset.from_dict({"x": np.arange(100, dtype=np.float64)})
            states, _ = ScanEngine(analyzers).run(data)
            shard_states.append(states)
        stacked = (
            jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *[s[0] for s in shard_states]
            ),
        )
        small_mesh = make_mesh(4)
        merged = collective_merge_states(analyzers, small_mesh, stacked)
        assert int(np.asarray(merged[0].num_matches)) == 800

    def test_two_device_mesh_hll(self):
        """(2, B) HLL pairs must shard on the batch axis, not the pair axis."""
        from deequ_tpu.analyzers import ApproxCountDistinct

        mesh2 = make_mesh(2)
        data = Dataset.from_dict({"y": np.arange(4000) % 137})
        a = ApproxCountDistinct("y")
        plain = AnalysisRunner.do_analysis_run(data, [a])
        sharded = AnalysisRunner.do_analysis_run(data, [a], sharding=mesh2)
        pv = plain.metric(a).value.get()
        sv = sharded.metric(a).value.get()
        assert pv == sv  # identical registers either way
        assert abs(pv - 137.0) <= 7  # within the sketch error envelope

    def test_anomaly_check_save_after_evaluate(self, tmp_path):
        """The current run's metric must NOT be in the anomaly history it is
        judged against (reference saves after evaluation)."""
        from deequ_tpu import CheckStatus, VerificationSuite
        from deequ_tpu.analyzers import Size
        from deequ_tpu.anomalydetection import AbsoluteChangeStrategy
        from deequ_tpu.repository import InMemoryMetricsRepository, ResultKey
        from deequ_tpu.runners import AnalysisRunner

        repo = InMemoryMetricsRepository()
        big = Dataset.from_dict({"x": np.arange(100, dtype=np.float64)})
        repo.save(ResultKey(1), AnalysisRunner.do_analysis_run(big, [Size()]))

        tiny = Dataset.from_dict({"x": np.arange(2, dtype=np.float64)})
        result = (
            VerificationSuite.on_data(tiny)
            .use_repository(repo)
            .save_or_append_result(ResultKey(2))
            .add_anomaly_check(
                AbsoluteChangeStrategy(max_rate_decrease=-10.0, max_rate_increase=10.0),
                Size(),
            )
            .run()
        )
        # size dropped 100 -> 2: must be flagged even though the run also
        # saves its own result under key 2
        assert result.status == CheckStatus.WARNING
        # and the save still happened (after evaluation)
        assert repo.load_by_key(ResultKey(2)) is not None


class TestKLLF32Saturation:
    def test_huge_magnitude_values_saturate(self):
        from deequ_tpu.ops.kll import kll_init, kll_update
        from deequ_tpu.ops.kll_host import HostKLL
        import jax.numpy as jnp

        vals = np.array([1.0, 2.0, 1e39, 3.0])
        state = kll_update(
            kll_init(64), jnp.asarray(vals), jnp.ones(4, dtype=bool)
        )
        assert int(state.count) == 4
        assert float(state.g_max) == 1e39  # exact in ACC dtype
        sketch = HostKLL.from_state(state)
        assert np.isfinite(sketch.quantile(1.0))  # saturated, not inf
        assert sketch.total_weight == 4


class TestMeshHostTierComposition:
    """Mesh x host ingest tier (VERDICT round-2 item 4): host partials are
    computed next to the data and the chunk folds shard over the mesh, so a
    slow feed link and a mesh no longer cancel each other."""

    def test_host_placement_on_mesh_matches_device(self, mesh, big_data):
        from deequ_tpu.runners.engine import RunMonitor

        mon = RunMonitor()
        host = AnalysisRunner.do_analysis_run(
            big_data, ANALYZERS, batch_size=4096, sharding=mesh,
            placement="host", monitor=mon,
        )
        assert mon.placement == "host"
        dev = AnalysisRunner.do_analysis_run(
            big_data, ANALYZERS, batch_size=4096, placement="device"
        )
        for a in ANALYZERS:
            hv, dv = host.metric(a).value, dev.metric(a).value
            assert hv.is_success == dv.is_success, a
            if hv.is_success and isinstance(hv.get(), float):
                assert hv.get() == pytest.approx(dv.get(), rel=1e-9), a

    def test_mesh_auto_placement_no_longer_forces_device(self, mesh, big_data):
        from deequ_tpu.runners import engine as engine_mod
        from deequ_tpu.runners.engine import RunMonitor, ScanEngine

        eng = ScanEngine(ANALYZERS, monitor=RunMonitor(), sharding=mesh, placement="auto")
        # simulate a slow probed link: auto must pick the host tier even
        # under a mesh (previously hard-forced "device")
        saved = engine_mod._FEED_BANDWIDTH_MBPS
        engine_mod._FEED_BANDWIDTH_MBPS = 1.0
        try:
            assert eng._resolve_placement() == "host"
        finally:
            engine_mod._FEED_BANDWIDTH_MBPS = saved
