"""Sketch analyzer tests: HLL++ accuracy envelopes, merge algebra, packed
serde round-trips — the analog of the reference
`analyzers/AnalyzerTests.scala` ApproxCountDistinct cases."""

import numpy as np
import pytest

from deequ_tpu.analyzers import ApproxCountDistinct, ApproxCountDistinctState
from deequ_tpu.data import Dataset
from deequ_tpu.ops import hll
from deequ_tpu.runners import AnalysisRunner


def run(data, *analyzers, **kwargs):
    return AnalysisRunner.do_analysis_run(data, list(analyzers), **kwargs)


def value_of(context, analyzer):
    metric = context.metric(analyzer)
    assert metric is not None, f"no metric for {analyzer}"
    assert metric.value.is_success, f"failure: {metric.value}"
    return metric.value.get()


class TestApproxCountDistinct:
    def test_small_exactish(self, df_full):
        # 4 rows, 2 distinct att1 values; at tiny cardinality linear counting
        # is essentially exact
        a = ApproxCountDistinct("att1")
        assert value_of(run(df_full, a), a) == 2.0

    def test_with_nulls(self, df_missing):
        a = ApproxCountDistinct("att1")
        assert value_of(run(df_missing, a), a) == 2.0

    def test_with_where(self, df_numeric):
        a = ApproxCountDistinct("att1", where="att1 <= 3")
        assert value_of(run(df_numeric, a), a) == 3.0

    def test_error_envelope_strings(self):
        n = 20000
        values = np.array([f"value-{i}" for i in range(n)], dtype=object)
        data = Dataset.from_dict({"col": list(values)})
        a = ApproxCountDistinct("col")
        est = value_of(run(data, a), a)
        # relativeSD = 0.05; allow 3 sigma
        assert abs(est - n) / n < 0.15

    def test_midrange_uses_bias_corrected_estimator(self):
        # cardinality between the linear-counting threshold (400 for p=9) and
        # 5m: must go through the bias-corrected raw estimator, not linear
        # counting
        n = 1000
        data = Dataset.from_dict({"col": [f"v{i}" for i in range(n)]})
        a = ApproxCountDistinct("col")
        est = value_of(run(data, a), a)
        assert abs(est - n) / n < 0.15

    def test_error_envelope_ints(self):
        rng = np.random.default_rng(7)
        vals = rng.integers(0, 50000, size=200000)
        data = Dataset.from_dict({"col": vals})
        exact = len(np.unique(vals))
        a = ApproxCountDistinct("col")
        est = value_of(run(data, a), a)
        assert abs(est - exact) / exact < 0.15

    def test_batched_equals_single_pass(self):
        rng = np.random.default_rng(3)
        vals = rng.integers(0, 5000, size=30000)
        data = Dataset.from_dict({"col": vals})
        a = ApproxCountDistinct("col")
        full = value_of(run(data, a), a)
        batched = value_of(run(data, a, batch_size=1024), a)
        assert full == batched

    def test_merge_algebra(self):
        rng = np.random.default_rng(11)
        vals = rng.integers(0, 3000, size=10000)
        d_all = Dataset.from_dict({"col": vals})
        d1 = Dataset.from_dict({"col": vals[:4000]})
        d2 = Dataset.from_dict({"col": vals[4000:]})
        a = ApproxCountDistinct("col")

        from deequ_tpu.analyzers import InMemoryStateProvider

        s1, s2 = InMemoryStateProvider(), InMemoryStateProvider()
        run(d1, a, save_states_with=s1)
        run(d2, a, save_states_with=s2)
        merged = a.merge_states(s1.load(a), s2.load(a))
        assert a.compute_metric_from(merged).value.get() == value_of(run(d_all, a), a)

    def test_empty_is_zero(self):
        data = Dataset.from_dict({"col": np.array([], dtype=np.int64)})
        a = ApproxCountDistinct("col")
        assert value_of(run(data, a), a) == 0.0


class TestHLLInternals:
    def test_clz64(self):
        xs = np.array([1, 2, 1 << 63, (1 << 64) - 1, 256, 1 << 32], dtype=np.uint64)
        expected = [63, 62, 0, 0, 55, 31]
        assert list(hll._clz64(xs)) == expected

    def test_word_packing_roundtrip(self):
        rng = np.random.default_rng(0)
        regs = rng.integers(0, 56, size=hll.M).astype(np.int32)
        words = hll.registers_to_words(regs)
        assert words.shape == (hll.NUM_WORDS,)
        back = hll.words_to_registers(words)
        np.testing.assert_array_equal(regs, back)

    def test_feature_math_matches_reference_semantics(self):
        # idx = top 9 bits; pw = clz((x << 9) | 256) + 1
        h = np.array([0, (1 << 64) - 1, 1 << 55], dtype=np.uint64)
        pairs = hll.hll_features(h)
        idx, pw = pairs[0], pairs[1]
        assert list(idx) == [0, 511, 1]
        # x=0: w = 256 -> clz = 55 -> pw = 56
        assert pw[0] == 56
        # all ones: w starts with 1 -> clz = 0 -> pw = 1
        assert pw[1] == 1

    def test_estimate_zero(self):
        assert hll.estimate_cardinality(np.zeros(hll.M, dtype=np.int32)) == 0.0


class TestKLLParameterValidation:
    def test_non_positive_sketch_size_is_failure_metric_not_hang(self):
        """A sketch_size of 0 must become a precondition failure metric, and
        the native sampler guards the stride loop regardless (regression: an
        unguarded k<=0 loop hung the process in native code)."""
        import numpy as np

        from deequ_tpu.analyzers import KLLParameters, KLLSketch
        from deequ_tpu.data import Dataset
        from deequ_tpu.exceptions import IllegalAnalyzerParameterException
        from deequ_tpu.runners import AnalysisRunner

        data = Dataset.from_dict({"x": np.arange(100.0)})
        a = KLLSketch("x", KLLParameters(sketch_size=0))
        ctx = AnalysisRunner.do_analysis_run(data, [a])
        value = ctx.metric(a).value
        assert value.is_failure
        assert isinstance(value.exception, IllegalAnalyzerParameterException)

    def test_native_kernels_guard_non_positive_k(self):
        import numpy as np

        import pytest

        from deequ_tpu.native import native_block_kll_pick, native_block_kll_sample

        if native_block_kll_sample is None:
            pytest.skip("native lib not built")
        v = np.arange(1000.0)
        # k clamps to 1; the denser stride policy may pick up to 4*k items
        items, m, h, nv, mn, mx = native_block_kll_sample(v, None, 0, 0)
        assert nv == 1000 and m <= 4
        items, m, h = native_block_kll_pick(v, None, 0, 0, 1000)
        assert m <= 4


class TestDictHLLRegisterFold:
    def _regs(self, values, take_rows):
        """Registers from ApproxCountDistinct.host_partial over a batch
        containing the first take_rows rows of a dictionary column."""
        import pyarrow as pa

        from deequ_tpu.analyzers import ApproxCountDistinct
        from deequ_tpu.analyzers.base import HostBatchContext
        from deequ_tpu.data import Dataset

        arr = pa.array(values).dictionary_encode()
        data = Dataset.from_arrow(pa.table({"c": arr}))
        batch = next(iter(data.batches(take_rows)))
        ctx = HostBatchContext(batch, batch_index=0)
        return np.asarray(ApproxCountDistinct("c").host_partial(ctx).registers)

    def _oracle(self, values, take_rows):
        """The original scatter formulation over the same batch subset."""
        import pyarrow as pa

        from deequ_tpu.data import Dataset
        from deequ_tpu.ops.hll import M, hll_features
        from deequ_tpu.runners.features import dict_entry_hashes

        arr = pa.array(values).dictionary_encode()
        data = Dataset.from_arrow(pa.table({"c": arr}))
        batch = next(iter(data.batches(take_rows)))
        col = batch.column("c")
        pairs = hll_features(dict_entry_hashes(col))
        mask = batch.row_mask & col.mask
        counts = np.bincount(
            col.codes[mask], minlength=col.num_categories + 1
        )[: col.num_categories]
        present = counts > 0
        regs = np.zeros(M, dtype=np.int32)
        if col.num_categories:
            np.maximum.at(
                regs,
                pairs[0][: col.num_categories][present],
                pairs[1][: col.num_categories][present],
            )
        return regs

    def test_partial_batch_fold_matches_scatter_fuzz(self):
        """Pin the reduceat fold (incl. the trailing-empty-register segment
        bug: clamping the reduceat starts dropped the LAST sorted pair out
        of the topmost occupied register whenever higher registers were
        empty) against the scatter oracle across random partial batches."""
        rng = np.random.default_rng(7)
        for trial in range(20):
            num_vals = int(rng.integers(50, 400))
            values = [f"v{int(v)}" for v in rng.integers(0, 10_000, num_vals)]
            take = int(rng.integers(1, num_vals + 1))
            got = self._regs(values, take)
            want = self._oracle(values, take)
            np.testing.assert_array_equal(got, want, err_msg=f"trial {trial}")
