"""Regression tests for advisor findings (ADVICE.md round 5).

- `utils.py`: BoundedLRU.keys()/__len__ must hold the lock (concurrent
  get()'s move_to_end could blow up the unlocked iteration).
- `data/__init__.py`: derived Datasets (select, casts, splits) skip the
  64k-row dictionary-encoding probes their parent already ran.
- `runners/engine.py`: _DeviceFeatureCache evicts whole per-table entry
  groups LRU when the budget is exhausted, dropping the Arrow-table pin,
  and logs when admission stops.

(The fourth finding — SQL function names shadowing column identifiers —
is pinned in tests/test_sql_predicates.py::TestFunctionNamesAsColumns.)
"""

import threading

import numpy as np
import pytest

from deequ_tpu.utils import BoundedLRU


class TestBoundedLRUThreadSafety:
    def test_keys_and_len_locked_under_concurrent_mutation(self):
        lru = BoundedLRU(64)
        stop = threading.Event()
        errors = []

        def writer(base):
            i = 0
            while not stop.is_set():
                lru[(base, i % 200)] = i
                lru.get((base, (i * 7) % 200))
                i += 1

        def reader():
            while not stop.is_set():
                try:
                    lru.keys()
                    len(lru)
                except RuntimeError as exc:  # pragma: no cover - the bug
                    errors.append(exc)
                    return

        threads = [threading.Thread(target=writer, args=(b,)) for b in range(3)]
        threads += [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        import time

        time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join(10)
        assert not errors
        assert len(lru) <= 64

    def test_plain_semantics_still_hold(self):
        lru = BoundedLRU(2)
        lru["a"] = 1
        lru["b"] = 2
        assert sorted(lru.keys()) == ["a", "b"]
        lru.get("a")  # touch: "b" becomes LRU
        lru["c"] = 3
        assert sorted(lru.keys()) == ["a", "c"]
        assert len(lru) == 2


class TestDerivedDatasetsSkipProbe:
    def _counting(self, monkeypatch):
        import deequ_tpu.data as dmod

        calls = []
        orig = dmod._maybe_dictionary_encode

        def counting(table):
            calls.append(table.schema.names)
            return orig(table)

        monkeypatch.setattr(dmod, "_maybe_dictionary_encode", counting)
        return calls

    def test_select_cast_split_do_not_reprobe(self, monkeypatch):
        from deequ_tpu.data import Dataset

        calls = self._counting(monkeypatch)
        ds = Dataset.from_dict(
            {
                "s": np.array(["x", "y", "z"] * 200),
                "num_str": np.array(["1.5", "2.5"] * 300),
                "v": np.arange(600, dtype=np.float64),
            }
        )
        assert len(calls) == 1  # the root construction probes once
        ds.select(["s", "v"])
        ds.with_column_cast_to_f64("num_str")
        ds.random_split(0.5, seed=1)
        ds.with_columns_dictionary_encoded(["v"])
        assert len(calls) == 1, "derived views must not re-run the probes"

    def test_fresh_roots_still_probe(self, monkeypatch):
        from deequ_tpu.data import Dataset

        calls = self._counting(monkeypatch)
        Dataset.from_dict({"s": ["a", "b"] * 50})
        Dataset.from_dict({"s": ["c", "d"] * 50})
        assert len(calls) == 2

    def test_derived_dataset_keeps_parent_encoding(self):
        from deequ_tpu.data import Dataset

        ds = Dataset.from_dict({"s": ["a", "b"] * 400, "v": list(range(800))})
        assert ds.dictionary_size("s") == 2  # probe encoded the root
        view = ds.select(["s"])
        assert view.dictionary_size("s") == 2  # encoding rode the slice


class TestDeviceFeatureCacheEviction:
    def _cache(self, budget):
        from deequ_tpu.runners.engine import _DeviceFeatureCache

        return _DeviceFeatureCache(budget)

    def test_lru_group_eviction_drops_table_pin(self):
        cache = self._cache(budget=100)
        t1, t2, t3 = object(), object(), object()
        for i in range(2):
            assert cache.admit((id(t1), i), t1, {"f": i}, 20)
        assert cache.admit((id(t2), 0), t2, {"f": 0}, 40)
        assert cache.bytes == 80 and set(cache.tables) == {id(t1), id(t2)}
        # t1 is LRU -> its WHOLE group (both entries) goes, pin included
        assert cache.admit((id(t3), 0), t3, {"f": 0}, 60)
        assert id(t1) not in cache.tables
        assert cache.get((id(t1), 0)) is None and cache.get((id(t1), 1)) is None
        assert cache.get((id(t2), 0)) is not None
        assert cache.bytes == 100 and cache.evictions == 1

    def test_get_refreshes_group_recency(self):
        cache = self._cache(budget=100)
        t1, t2, t3 = object(), object(), object()
        cache.admit((id(t1), 0), t1, {}, 40)
        cache.admit((id(t2), 0), t2, {}, 40)
        cache.get((id(t1), 0))  # t1 is now MRU; t2 becomes the victim
        cache.admit((id(t3), 0), t3, {}, 40)
        assert id(t1) in cache.tables and id(t2) not in cache.tables

    def test_own_group_never_evicted_for_itself(self, caplog):
        import logging

        cache = self._cache(budget=50)
        t1 = object()
        assert cache.admit((id(t1), 0), t1, {}, 40)
        with caplog.at_level(logging.WARNING, logger="deequ_tpu.runners.engine"):
            # the same table's next batch does not fit: admission stops
            # (evicting batch 0 to admit batch 1 would thrash every pass)
            assert not cache.admit((id(t1), 1), t1, {}, 40)
        assert cache.get((id(t1), 0)) is not None
        assert any(
            "stopped admitting" in rec.message for rec in caplog.records
        ), "refused admission must be logged"
        # ... and logged ONCE, not per batch
        with caplog.at_level(logging.WARNING, logger="deequ_tpu.runners.engine"):
            assert not cache.admit((id(t1), 2), t1, {}, 40)
        stops = [r for r in caplog.records if "stopped admitting" in r.message]
        assert len(stops) == 1

    def test_oversize_entry_rejected_without_flushing_warm_groups(self):
        """An entry larger than the whole budget can never fit; trying to
        evict for it would flush every warm group for nothing."""
        cache = self._cache(budget=100)
        t1, t2 = object(), object()
        assert cache.admit((id(t1), 0), t1, {"f": 0}, 60)
        assert not cache.admit((id(t2), 0), t2, {"f": 0}, 150)
        assert cache.get((id(t1), 0)) is not None, "warm group must survive"
        assert cache.evictions == 0

    def test_unfittable_entry_counts_own_group_before_evicting_others(self):
        """budget 100: table A holds 80, B holds 15; a new 30-byte A batch
        can never fit (A's own group is unevictable for it) — B's warm
        group must survive the refused admission."""
        cache = self._cache(budget=100)
        ta, tb = object(), object()
        assert cache.admit((id(ta), 0), ta, {"f": 0}, 80)
        assert cache.admit((id(tb), 0), tb, {"f": 0}, 15)
        assert not cache.admit((id(ta), 1), ta, {"f": 1}, 30)
        assert cache.get((id(tb), 0)) is not None, "B flushed for nothing"
        assert cache.evictions == 0

    def test_program_cache_is_bounded(self):
        from deequ_tpu.runners.engine import _PROGRAM_CACHE
        from deequ_tpu.utils import BoundedLRU

        assert isinstance(_PROGRAM_CACHE, BoundedLRU)
        assert _PROGRAM_CACHE.max_size >= 64  # generous but finite

    def test_duplicate_admit_is_idempotent(self):
        """Two workers preparing the same batch concurrently both admit the
        same key: bytes must not double-count and the group bookkeeping
        must stay consistent (a duplicate group key broke eviction)."""
        cache = self._cache(budget=100)
        t1, t2 = object(), object()
        assert cache.admit((id(t1), 0), t1, {"f": 0}, 40)
        assert cache.admit((id(t1), 0), t1, {"f": 0}, 40)  # the race loser
        assert cache.bytes == 40
        cache.admit((id(t2), 0), t2, {"f": 0}, 80)  # forces t1's eviction
        assert id(t1) not in cache.tables and cache.bytes == 80

    def test_clear_resets_everything(self):
        cache = self._cache(budget=100)
        t1 = object()
        cache.admit((id(t1), 0), t1, {}, 60)
        cache.clear()
        assert cache.bytes == 0 and not cache.tables and not cache.store
        assert cache.admit((id(t1), 0), t1, {}, 60)

    def test_engine_round_trip_with_tiny_budget(self, monkeypatch):
        """End to end: a warm re-run over the same dataset hits the cache,
        and a second dataset evicts the first instead of overflowing."""
        import deequ_tpu.runners.engine as eng
        from deequ_tpu.analyzers import Mean
        from deequ_tpu.data import Dataset
        from deequ_tpu.runners import AnalysisRunner

        # 12KB budget: one 1024-row f64 feature set (~9KB) fits, two don't
        monkeypatch.setenv(eng.DEVICE_FEATURE_CACHE_ENV, "0.000012")
        eng.clear_device_feature_cache()
        try:
            d1 = Dataset.from_dict({"x": np.arange(1024, dtype=np.float64)})
            d2 = Dataset.from_dict(
                {"x": np.arange(1024, 2048, dtype=np.float64)}
            )
            AnalysisRunner.do_analysis_run(d1, [Mean("x")], placement="device")
            cache = eng.device_feature_cache()
            assert cache is not None and id(d1.arrow) in cache.tables
            AnalysisRunner.do_analysis_run(d2, [Mean("x")], placement="device")
            assert id(d1.arrow) not in cache.tables, "LRU table evicted"
            assert id(d2.arrow) in cache.tables
            ctx = AnalysisRunner.do_analysis_run(d2, [Mean("x")], placement="device")
            assert ctx.metric(Mean("x")).value.get() == pytest.approx(1535.5)
        finally:
            eng.clear_device_feature_cache()
