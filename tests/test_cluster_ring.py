"""Cluster tier: consistent-hash ring + heartbeat membership.

The routing and liveness primitives under the front tier (ISSUE 16
tentpole): stable cross-process hashing, bounded key movement on
membership changes, TTL-declared host loss, and the chaos probes
(``ring_rebalance``, ``host_heartbeat``) that let drills fail them on
purpose."""

import time

import pytest

from deequ_tpu.cluster import (
    HashRing,
    HeartbeatMembership,
    HostLossError,
    ring_vnodes,
)
from deequ_tpu.reliability.faults import FaultSpec, inject

pytestmark = pytest.mark.cluster


KEYS = [f"tenant-{i % 7}/stream-{i}" for i in range(400)]


class TestHashRing:
    def test_routing_is_deterministic_across_instances(self):
        """Every front-tier replica must route identically: the ring is
        a pure function of (host set, vnodes) — no process salt."""
        a = HashRing(["w0", "w1", "w2"], vnodes=64)
        b = HashRing(["w2", "w0", "w1"], vnodes=64)  # order must not matter
        assert [a.route(k) for k in KEYS] == [b.route(k) for k in KEYS]

    def test_distribution_is_roughly_balanced(self):
        ring = HashRing(["w0", "w1", "w2", "w3"], vnodes=64)
        counts = {h: 0 for h in ring.hosts}
        for k in KEYS:
            counts[ring.route(k)] += 1
        share = len(KEYS) / len(counts)
        for host, n in counts.items():
            assert 0.4 * share <= n <= 1.8 * share, (host, counts)

    def test_add_host_moves_only_a_fraction(self):
        """THE consistent-hashing contract: adding one host re-homes
        ~1/N of keys, and every moved key lands ON the new host."""
        before = HashRing(["w0", "w1", "w2"], vnodes=64)
        after = before.snapshot()
        after.add_host("w3")
        moved = after.moved_keys(KEYS, before)
        assert 0 < len(moved) < len(KEYS) // 2
        assert all(dst == "w3" for _src, dst in moved.values())

    def test_remove_host_moves_only_its_keys(self):
        before = HashRing(["w0", "w1", "w2"], vnodes=64)
        after = before.snapshot()
        after.remove_host("w1")
        moved = after.moved_keys(KEYS, before)
        assert moved, "w1 owned some of 400 keys"
        for key, (src, dst) in moved.items():
            assert src == "w1" and dst != "w1", (key, src, dst)
        # unmoved keys still route where they did
        unmoved = [k for k in KEYS if k not in moved]
        assert all(after.route(k) == before.route(k) for k in unmoved)

    def test_empty_ring_raises_lookup_error(self):
        with pytest.raises(LookupError):
            HashRing().route("t/d")

    def test_vnodes_env_knob(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_CLUSTER_VNODES", "8")
        assert ring_vnodes() == 8
        assert HashRing(["w0"]).vnodes == 8
        monkeypatch.setenv("DEEQU_TPU_CLUSTER_VNODES", "not-a-number")
        assert ring_vnodes() == 64  # warn-once keep-default parser

    def test_ring_rebalance_fault_site_is_live(self):
        """Chaos plans can fail the re-hash mid-membership-change."""
        ring = HashRing(["w0"])
        with inject(FaultSpec(site="ring_rebalance", kind="host_loss",
                              at=1)):
            with pytest.raises(HostLossError):
                ring.add_host("w1")


class TestHeartbeatMembership:
    def test_beat_then_scan_alive(self, tmp_path):
        mem = HeartbeatMembership(str(tmp_path), host_id="w0", ttl_s=5.0)
        mem.beat()
        alive, lost = HeartbeatMembership(str(tmp_path), ttl_s=5.0).scan()
        assert alive == ["w0"] and lost == []

    def test_ttl_expiry_declares_lost_and_retire_clears(self, tmp_path):
        mem = HeartbeatMembership(str(tmp_path), host_id="w0", ttl_s=0.1)
        mem.beat()
        time.sleep(0.25)
        reader = HeartbeatMembership(str(tmp_path), ttl_s=0.1)
        alive, lost = reader.scan()
        assert alive == [] and lost == ["w0"]
        reader.retire("w0")
        assert reader.scan() == ([], [])

    def test_background_beater_keeps_host_alive(self, tmp_path):
        mem = HeartbeatMembership(
            str(tmp_path), host_id="w0",
            heartbeat_period_s=0.05, ttl_s=0.3,
        )
        mem.start()
        try:
            time.sleep(0.5)  # several TTLs: only the beater keeps it alive
            alive, lost = HeartbeatMembership(str(tmp_path), ttl_s=0.3).scan()
            assert alive == ["w0"] and lost == []
        finally:
            mem.stop()

    def test_host_heartbeat_fault_declares_host_lost(self, tmp_path):
        """An injected host_loss fault at the heartbeat probe declares a
        LIVE host dead — the drills' loss path without killing anything."""
        for host in ("w0", "w1"):
            HeartbeatMembership(str(tmp_path), host_id=host,
                                ttl_s=30.0).beat()
        reader = HeartbeatMembership(str(tmp_path), ttl_s=30.0)
        with inject(FaultSpec(site="host_heartbeat", kind="host_loss",
                              match="w1")):
            alive, lost = reader.scan()
        assert alive == ["w0"] and lost == ["w1"]

    def test_torn_beat_files_are_skipped(self, tmp_path):
        (tmp_path / "host-evil.json").write_text("{not json")
        mem = HeartbeatMembership(str(tmp_path), host_id="w0", ttl_s=5.0)
        mem.beat()
        assert list(mem.members()) == ["w0"]
