"""Scan-watchdog drills (ISSUE 4 acceptance): a stalled (injected) scan is
cancelled within 2x its deadline and fails over instead of hanging the
worker; escaped stalls are requeued by the scheduler; deadlines derive
from measured per-batch rates with the env override on top."""

import time

import numpy as np
import pytest

from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import ScanStallError
from deequ_tpu.reliability import (
    SCAN_DEADLINE_ENV,
    FaultSpec,
    RateTracker,
    classify_failure,
    inject,
    rate_tracker,
    run_with_deadline,
    scan_deadline_s,
)
from deequ_tpu.runners.engine import RunMonitor


@pytest.fixture(autouse=True)
def _clean_rates(monkeypatch):
    """Each test starts with no learned rates and no env deadline, and
    leaks neither into the rest of the suite."""
    monkeypatch.delenv(SCAN_DEADLINE_ENV, raising=False)
    rate_tracker().clear()
    yield
    rate_tracker().clear()


class TestRunWithDeadline:
    def test_value_and_error_pass_through(self):
        monitor = RunMonitor()
        assert run_with_deadline(lambda: 42, 5.0, monitor, "t") == 42
        with pytest.raises(KeyError):
            run_with_deadline(
                lambda: (_ for _ in ()).throw(KeyError("x")), 5.0, monitor, "t"
            )
        assert monitor.stalls == 0

    def test_deadline_cancels_with_typed_error(self):
        monitor = RunMonitor()
        t0 = time.perf_counter()
        with pytest.raises(ScanStallError) as err:
            run_with_deadline(lambda: time.sleep(10), 0.2, monitor, "device")
        elapsed = time.perf_counter() - t0
        assert elapsed < 2 * 0.2 + 0.5  # cancelled ~at the deadline
        assert monitor.stalls == 1
        assert err.value.deadline_s == 0.2
        assert classify_failure(err.value) == "device"  # tier-failover path


class TestDeadlineDerivation:
    def test_env_override_wins(self, monkeypatch):
        monkeypatch.setenv(SCAN_DEADLINE_ENV, "7.5")
        assert scan_deadline_s(100, "device") == 7.5

    def test_env_zero_or_negative_disables(self, monkeypatch):
        monkeypatch.setenv(SCAN_DEADLINE_ENV, "0")
        assert scan_deadline_s(100, "device") is None
        monkeypatch.setenv(SCAN_DEADLINE_ENV, "-3")
        assert scan_deadline_s(100, "device") is None

    def test_garbage_env_falls_back_to_derived_not_silent_off(
        self, monkeypatch
    ):
        """An operator who typo'd "60s" believes hang detection is armed;
        the unparseable value must not silently disable BOTH paths."""
        monkeypatch.setenv(SCAN_DEADLINE_ENV, "60s")
        assert scan_deadline_s(100, "device") is None  # no rate yet either
        rate_tracker().observe("device", rows=10, seconds=10.0)
        assert scan_deadline_s(100, "device") == pytest.approx(1000.0)

    def test_cold_process_runs_unguarded(self):
        assert scan_deadline_s(100, "device") is None

    def test_derived_from_measured_rate_with_floor(self):
        tracker = rate_tracker()
        tracker.observe("device", rows=1000, seconds=1.0)  # 1ms/row
        # 10x multiple: 2000 rows -> 20s, under the 30s floor
        assert scan_deadline_s(2000, "device") == 30.0
        # 10000 rows -> 100s, over the floor
        assert scan_deadline_s(10_000, "device") == pytest.approx(100.0)
        # rates are per tier: host has no measurement yet
        assert scan_deadline_s(10_000, "host") is None

    def test_rate_is_per_row_not_per_batch(self):
        """One tier serves 512-row micro-batches AND 1M-row batches; a
        per-batch rate learned from the small ones would derive deadlines
        no healthy large-batch pass can meet (review finding). Per-row,
        the same observation covers both."""
        from deequ_tpu.reliability.watchdog import DEADLINE_RATE_MULTIPLE

        tracker = RateTracker()
        # micro-batch pass: 10 batches x 512 rows in 0.2s
        tracker.observe("host", rows=5120, seconds=0.2)
        per_row = tracker.per_row_s("host")
        # a 32M-row pass's deadline scales with ROWS, not batch count
        expected = max(30.0, DEADLINE_RATE_MULTIPLE * per_row * 32_000_000)
        assert expected > 1000  # minutes of slack, no false stall

    def test_ewma_blends_observations(self):
        tracker = RateTracker()
        tracker.observe("device", 1, 1.0)
        tracker.observe("device", 1, 2.0)
        assert tracker.per_row_s("device") == pytest.approx(
            0.3 * 2.0 + 0.7 * 1.0
        )

    def test_engine_pass_feeds_tracker(self):
        from deequ_tpu.runners.analysis_runner import AnalysisRunner
        from deequ_tpu.analyzers import Mean

        data = Dataset.from_dict({"x": np.arange(2048, dtype=np.float64)})
        AnalysisRunner.do_analysis_run(data, [Mean("x")], batch_size=1024)
        assert rate_tracker().per_row_s("device") is not None


@pytest.mark.chaos
class TestStallDrills:
    def _data(self, rows=4096):
        rng = np.random.default_rng(0)
        return Dataset.from_dict({"x": rng.normal(size=rows)})

    def _check(self):
        return (
            Check(CheckLevel.ERROR, "stall battery")
            .has_mean("x", lambda m: abs(m) < 1)
            .is_complete("x")
        )

    def test_injected_stall_cancelled_within_2x_deadline_and_fails_over(
        self, monkeypatch
    ):
        """ISSUE acceptance: a stalled (injected) scan is cancelled by the
        watchdog within 2x its deadline and fails over instead of hanging
        the worker."""
        from deequ_tpu.verification import VerificationSuite

        # warm BOTH tiers' programs first: a pinned 1s deadline applies to
        # every pass, and a cold host-tier compile would legitimately trip
        # it (the derived-deadline path never has this problem — it only
        # arms after a completed pass measured the tier's rate)
        for placement in ("device", "host"):
            (
                VerificationSuite.on_data(self._data())
                .add_check(self._check())
                .with_placement(placement)
                .run()
            )
        monkeypatch.setenv(SCAN_DEADLINE_ENV, "1.0")
        monitor = RunMonitor()
        with inject(FaultSpec("device_update", "stall", at=1, delay_s=30.0)):
            t0 = time.perf_counter()
            result = (
                VerificationSuite.on_data(self._data())
                .add_check(self._check())
                .with_monitor(monitor)
                .with_placement("device")
                .run()
            )
            elapsed = time.perf_counter() - t0
        # the device pass was cancelled at ~1s (not the 30s sleep) and the
        # host-tier re-run finished the battery
        assert elapsed < 2 * 1.0 + 5.0
        assert monitor.stalls == 1
        assert monitor.device_failovers == 1
        assert result.status == CheckStatus.SUCCESS
        for metric in result.metrics.values():
            assert metric.value.is_success

    def test_scheduler_requeues_escaped_stall(self):
        """A stall that escapes the engine's failover must requeue the job
        (worker freed), not hang or insta-fail it."""
        from deequ_tpu.service import VerificationService

        attempts = []

        def flaky(ctx):
            attempts.append(ctx.attempt)
            if len(attempts) == 1:
                raise ScanStallError("device", 1.0, 1.2)
            return "done"

        with VerificationService(workers=1, background_warm=False) as svc:
            handle = svc.scheduler.submit(
                flaky, tenant="t", max_retries=1, retry_backoff_s=0.01
            )
            assert handle.result(timeout=30) == "done"
        assert attempts == [1, 2]

    def test_stall_counts_on_export_plane_and_probation(self):
        """A job whose monitor recorded stalls teaches the placement
        router (probation) and the export plane counter."""
        from deequ_tpu.service import VerificationService

        def stalled_then_done(ctx):
            ctx.monitor.bump("stalls")
            ctx.monitor.bump("device_stalls")  # the stall was device-tier
            ctx.monitor.placement = "host"
            return "ok"

        with VerificationService(workers=1, background_warm=False) as svc:
            handle = svc.scheduler.submit(
                stalled_then_done, tenant="t", signature=("sig",)
            )
            assert handle.result(timeout=30) == "ok"
            counters = svc.json_snapshot()["counters"]
            assert (
                counters["deequ_service_scan_stalls_total"]["tenant=t"] == 1.0
            )
            # probation: the router now refuses the device tier for this
            # battery signature
            assert svc.router.decide(("sig",), None) == "host"

    def test_host_tier_stall_does_not_probation_device(self):
        """A HOST-tier hang must not pin the battery to the tier that
        hung: monitor.stalls without device_stalls counts on the export
        plane but leaves placement routing alone."""
        from deequ_tpu.service import VerificationService

        def host_stalled(ctx):
            ctx.monitor.bump("stalls")  # tier was host: no device_stalls
            ctx.monitor.placement = "device"
            return "ok"

        with VerificationService(workers=1, background_warm=False) as svc:
            handle = svc.scheduler.submit(
                host_stalled, tenant="t", signature=("hsig",)
            )
            assert handle.result(timeout=30) == "ok"
            counters = svc.json_snapshot()["counters"]
            assert (
                counters["deequ_service_scan_stalls_total"]["tenant=t"] == 1.0
            )
            assert ("hsig",) not in svc.router._device_suspect
