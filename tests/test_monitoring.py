"""Compile-count invariants and phase timers (VERDICT round-2 item 8).

The jit-compile counter is the codegen-cache analog of the reference's
Spark-job-count asserts (`AnalysisRunnerTests.scala:50-74`): re-running the
SAME battery must hit the cached XLA programs, never re-trace — a recompile
regression multiplies run latency by the ~20-40s compile cost."""

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    KLLParameters,
    KLLSketch,
    Maximum,
    Mean,
    Minimum,
    StandardDeviation,
    Sum,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor

BATTERY = [
    Completeness("x"),
    Mean("x"),
    Sum("x"),
    Minimum("x"),
    Maximum("x"),
    StandardDeviation("x"),
    ApproxCountDistinct("x"),
    KLLSketch("x", KLLParameters(256, 0.64, 10)),
]


def _data(seed: int, n: int = 20_000) -> Dataset:
    rng = np.random.default_rng(seed)
    return Dataset.from_dict({"x": rng.normal(size=n)})


class TestCompileCountInvariants:
    @pytest.mark.parametrize("placement", ["device", "host"])
    def test_no_recompiles_across_identical_runs(self, placement):
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(
            _data(0), BATTERY, batch_size=4096, monitor=mon, placement=placement
        )
        warm = mon.jit_compiles
        for seed in (1, 2):
            mon2 = RunMonitor()
            AnalysisRunner.do_analysis_run(
                _data(seed), BATTERY, batch_size=4096, monitor=mon2,
                placement=placement,
            )
            assert mon2.jit_compiles == warm, (
                f"recompile regression: warmup={warm}, rerun={mon2.jit_compiles}"
            )

    def test_different_row_counts_share_programs(self):
        """Batch padding keeps program shapes static: a run with a ragged
        final batch must not compile new programs."""
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(
            _data(0, 8192), BATTERY, batch_size=4096, monitor=mon, placement="device"
        )
        warm = mon.jit_compiles
        mon2 = RunMonitor()
        AnalysisRunner.do_analysis_run(
            _data(1, 10_000), BATTERY, batch_size=4096, monitor=mon2, placement="device"
        )
        assert mon2.jit_compiles == warm


class TestPhaseTimers:
    def test_device_path_phases_recorded(self):
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(
            _data(0), BATTERY, batch_size=4096, monitor=mon, placement="device"
        )
        assert {"feature_build", "device_feed", "device_dispatch", "state_fetch"} <= set(
            mon.phase_seconds
        )
        assert all(v >= 0 for v in mon.phase_seconds.values())

    def test_host_path_phases_recorded(self):
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(
            _data(0), BATTERY, batch_size=4096, monitor=mon, placement="host"
        )
        assert {"host_partials", "ingest_fold", "state_fetch"} <= set(mon.phase_seconds)

    def test_reset_clears_phases(self):
        mon = RunMonitor()
        mon.add_phase_time("x", 1.0)
        mon.reset()
        assert mon.phase_seconds == {}
