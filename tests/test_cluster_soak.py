"""Multi-process cluster soak, as tests (ISSUE 16 tentpole acceptance).

Drives ``python -m tools.cluster_soak`` through the shared spawn harness
(``tests/cluster_harness.py``): real worker PROCESSES, each a whole
service plane with an HTTP ingest endpoint, routed by the real front
tier over one shared partition store. Marked slow (spawns several
processes); skips cleanly where the environment cannot run them."""

import pytest

from cluster_harness import run_tool_json, skip_if_skipped

pytestmark = [pytest.mark.slow, pytest.mark.cluster]


def test_two_process_soak_bit_exact_parity():
    """Aggregate throughput across 2 worker processes with the parity
    gate: every session's final Sum/Size equals the closed-form oracle
    EXACTLY (integer-valued sums are fold-order independent)."""
    rc, report = run_tool_json(
        "tools.cluster_soak",
        ["--procs", "2", "--sessions", "6", "--batches", "6",
         "--rows", "2048"],
        timeout=420,
    )
    skip_if_skipped(rc, report)
    assert rc == 0, report
    assert report["ok"], report
    assert report["parity_failures"] == []
    assert report["sessions_per_s"] > 0
    assert report["counters"]["deequ_service_cluster_routes_total"] > 0
    # the observability verdict: per-host journals merged into ONE
    # Perfetto trace, with front-tier ingest spans and worker spans
    # sharing a trace_id across the process boundary, and a live
    # worker's /statusz covering every plane schema-clean
    obs = report["observability"]
    assert obs["ok"], obs
    # front + at least one worker journal (the ring may hash every
    # session onto one host at small session counts)
    assert obs["journals"] >= 2
    assert obs["cross_process_ingest_traces"] >= 1
    assert obs["statusz_problems"] == []
    for plane in ("scheduler", "tuning", "cluster", "catalog",
                  "fleetwatch", "partition_store"):
        assert plane in obs["statusz_planes"]


def test_kill_one_worker_recovers_with_typed_counters():
    """The SIGKILL drill: one worker dies mid-stream; the verdict
    asserts the ring re-hashed its sessions to the survivor, each was
    adopted from its last flushed partition and its journaled folds
    replayed (exact parity — no lost, no double-committed folds), and
    the typed deequ_service_cluster_* counters prove recovery ran."""
    rc, report = run_tool_json(
        "tools.cluster_soak",
        ["--drill", "kill-one", "--sessions", "4", "--batches", "4",
         "--rows", "1024"],
        timeout=420,
    )
    skip_if_skipped(rc, report)
    assert rc == 0, report
    assert report["ok"], report
    assert report["parity_failures"] == []
    assert report["recovered_hosts"] == [report["victim"]]
    for src, dst in report["rehomed"].values():
        assert src == report["victim"] and dst != src
    counters = report["counters"]
    assert counters["deequ_service_cluster_host_losses_total"] >= 1
    assert counters["deequ_service_cluster_sessions_recovered_total"] >= 1
    assert counters["deequ_service_cluster_replayed_folds_total"] >= 1
    # satellite 1: the SIGKILLed victim's line-buffered span journal
    # survives as its flight dump — worker-side spans for the folds it
    # finished before dying
    assert report["victim_journal_spans"] >= 1
    obs = report["observability"]
    assert obs["ok"], obs
    assert obs["statusz_problems"] == []
