"""RowLevelSchemaValidator + Applicability tests — the analog of
`schema/RowLevelSchemaValidatorTest.scala` and
`analyzers/applicability/ApplicabilityTest.scala`."""

import numpy as np
import pytest

from deequ_tpu.applicability import Applicability, generate_random_data
from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.data import ColumnKind, ColumnSchema, Dataset, Schema
from deequ_tpu.schema import (
    RowLevelSchema,
    RowLevelSchemaValidator,
)


class TestRowLevelSchemaValidator:
    def test_int_validation_and_cast(self):
        data = Dataset.from_dict(
            {"id": ["1", "2", "not-a-number", "4", None], "name": list("abcde")}
        )
        schema = RowLevelSchema().with_int_column("id", is_nullable=False)
        result = RowLevelSchemaValidator.validate(data, schema)
        assert result.num_valid_rows == 3
        assert result.num_invalid_rows == 2
        valid = result.valid_rows.to_pandas()
        assert list(valid["id"]) == [1, 2, 4]
        assert result.valid_rows.schema["id"].kind == ColumnKind.INTEGRAL
        invalid = result.invalid_rows.to_pandas()
        assert set(invalid["name"]) == {"c", "e"}

    def test_int_bounds(self):
        data = Dataset.from_dict({"v": ["5", "15", "25"]})
        schema = RowLevelSchema().with_int_column("v", min_value=10, max_value=20)
        result = RowLevelSchemaValidator.validate(data, schema)
        assert result.num_valid_rows == 1
        assert list(result.valid_rows.to_pandas()["v"]) == [15]

    def test_string_constraints(self):
        data = Dataset.from_dict({"code": ["AB", "ABC", "ABCD", "xy", None]})
        schema = RowLevelSchema().with_string_column(
            "code", min_length=2, max_length=3, matches="^[A-Z]+$"
        )
        result = RowLevelSchemaValidator.validate(data, schema)
        # AB, ABC pass; ABCD too long; xy lowercase; null allowed (nullable)
        assert result.num_valid_rows == 3

    def test_decimal(self):
        data = Dataset.from_dict({"d": ["12.34", "123456.7", "abc"]})
        schema = RowLevelSchema().with_decimal_column("d", precision=6, scale=2)
        result = RowLevelSchemaValidator.validate(data, schema)
        assert result.num_valid_rows == 1
        assert list(result.valid_rows.to_pandas()["d"]) == [12.34]

    def test_timestamp(self):
        data = Dataset.from_dict(
            {"ts": ["2024-01-31 10:30:00", "not a date", "2024-13-99 99:99:99"]}
        )
        schema = RowLevelSchema().with_timestamp_column("ts", mask="yyyy-MM-dd HH:mm:ss")
        result = RowLevelSchemaValidator.validate(data, schema)
        assert result.num_valid_rows == 1
        assert result.valid_rows.schema["ts"].kind == ColumnKind.TIMESTAMP

    def test_non_nullable(self):
        data = Dataset.from_dict({"x": ["a", None, "b"]})
        schema = RowLevelSchema().with_string_column("x", is_nullable=False)
        result = RowLevelSchemaValidator.validate(data, schema)
        assert result.num_valid_rows == 2

    def test_multi_column_cnf(self):
        data = Dataset.from_dict(
            {
                "id": ["1", "2", "x"],
                "name": ["alice", "bob", "carol"],
            }
        )
        schema = (
            RowLevelSchema()
            .with_int_column("id", is_nullable=False)
            .with_string_column("name", max_length=5)
        )
        result = RowLevelSchemaValidator.validate(data, schema)
        assert result.num_valid_rows == 2


class TestApplicability:
    def _schema(self):
        return Schema(
            [
                ColumnSchema("num", ColumnKind.FRACTIONAL),
                ColumnSchema("count", ColumnKind.INTEGRAL),
                ColumnSchema("name", ColumnKind.STRING),
                ColumnSchema("flag", ColumnKind.BOOLEAN),
            ]
        )

    def test_generate_random_data(self):
        data = generate_random_data(self._schema(), 500)
        assert data.num_rows == 500
        assert data.schema["num"].kind == ColumnKind.FRACTIONAL
        assert data.schema["name"].kind == ColumnKind.STRING

    def test_applicable_check(self):
        check = (
            Check(CheckLevel.ERROR, "ok")
            .has_size(lambda v: True)
            .has_mean("num", lambda v: True)
            .is_complete("name")
        )
        result = Applicability.is_applicable_check(check, self._schema())
        assert result.is_applicable
        assert all(result.constraint_applicabilities.values())

    def test_inapplicable_check(self):
        check = (
            Check(CheckLevel.ERROR, "bad")
            .has_mean("name", lambda v: True)  # mean over a string column
            .has_mean("missing_col", lambda v: True)
        )
        result = Applicability.is_applicable_check(check, self._schema())
        assert not result.is_applicable
        assert len(result.failures) == 2
        inapplicable = [
            c for c, ok in result.constraint_applicabilities.items() if not ok
        ]
        assert len(inapplicable) == 2

    def test_analyzers_applicability(self):
        from deequ_tpu.analyzers import Completeness, Mean

        result = Applicability.is_applicable_analyzers(
            [Mean("num"), Completeness("name")], self._schema()
        )
        assert result.is_applicable
        bad = Applicability.is_applicable_analyzers([Mean("name")], self._schema())
        assert not bad.is_applicable
