"""Round-5 host-tier fast paths: ingest-time adaptive dictionary encoding,
the per-pass HLL seen-entry skip, the int64 KLL pick kernel, the Histogram
dictionary-code path and the small-range integer bincount — each pinned
against the slow path / an oracle so the optimizations cannot drift
(VERDICT r4 #1b)."""

import numpy as np
import pandas as pd
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    CountDistinct,
    Histogram,
    KLLSketch,
    Uniqueness,
)
from deequ_tpu.data import ADAPTIVE_DICT_ENCODE_ENV, Dataset
from deequ_tpu.runners import AnalysisRunner


def lowcard_table(rows=20_000, seed=3):
    rng = np.random.default_rng(seed)
    return {
        "flag": np.array(["A", "N", "R"])[rng.integers(0, 3, rows)],
        "mode": np.array([f"m{i}" for i in range(40)])[rng.integers(0, 40, rows)],
        "num": rng.integers(1, 8, rows),
    }


class TestAdaptiveDictionaryEncoding:
    def test_low_cardinality_strings_are_encoded(self):
        data = Dataset.from_dict(lowcard_table())
        assert data.dictionary_size("flag") == 3
        assert data.dictionary_size("mode") == 40
        assert data.dictionary_size("num") is None  # integers stay plain

    def test_high_cardinality_strings_stay_plain(self):
        rows = 100_000
        uniq = np.array([f"u{i:06d}" for i in range(rows)])
        data = Dataset.from_dict({"u": uniq})
        assert data.dictionary_size("u") is None

    def test_env_disables_encoding(self, monkeypatch):
        monkeypatch.setenv(ADAPTIVE_DICT_ENCODE_ENV, "0")
        data = Dataset.from_dict(lowcard_table())
        assert data.dictionary_size("flag") is None

    def test_metrics_identical_encoded_vs_plain(self, monkeypatch):
        cols = lowcard_table(rows=5000)
        analyzers = [
            ApproxCountDistinct("flag"),
            Uniqueness(["mode"]),
            CountDistinct(["mode"]),
            Histogram("flag"),
        ]
        encoded = AnalysisRunner.do_analysis_run(
            Dataset.from_dict(cols), analyzers, batch_size=1024
        )
        monkeypatch.setenv(ADAPTIVE_DICT_ENCODE_ENV, "0")
        plain = AnalysisRunner.do_analysis_run(
            Dataset.from_dict(cols), analyzers, batch_size=1024
        )
        for a in analyzers[:-1]:
            assert encoded.metric(a).value.get() == plain.metric(a).value.get(), a
        he = encoded.metric(Histogram("flag")).value.get()
        hp = plain.metric(Histogram("flag")).value.get()
        assert {k: v.absolute for k, v in he.values.items()} == {
            k: v.absolute for k, v in hp.values.items()
        }


class TestHllSeenSkip:
    def _estimate(self, data, column, **kwargs):
        ctx = AnalysisRunner.do_analysis_run(
            data, [ApproxCountDistinct(column)], **kwargs
        )
        return ctx.metric(ApproxCountDistinct(column)).value.get()

    def test_multi_batch_equals_single_batch(self):
        cols = lowcard_table(rows=30_000)
        data = Dataset.from_dict(cols)
        one = self._estimate(data, "mode", placement="host", batch_size=30_000)
        many = self._estimate(data, "mode", placement="host", batch_size=1024)
        assert one == many  # batching must not change the registers
        assert abs(one - 40.0) <= 0.05 * 40  # published error envelope

    def test_second_run_over_same_dataset_is_correct(self):
        # the seen-set is keyed to the PASS: a second streamed run over the
        # SAME Dataset must not inherit the first run's saturation (which
        # would fold only identity partials -> estimate 0)
        data = Dataset.from_dict(lowcard_table(rows=30_000))
        first = self._estimate(data, "flag", placement="host", batch_size=2048)
        second = self._estimate(data, "flag", placement="host", batch_size=2048)
        assert first == second == 3.0

    def test_large_dictionary_row_path(self):
        rng = np.random.default_rng(9)
        rows = 300_000
        pool = np.array([f"val{i:07d}" for i in range(80_000)])
        import pyarrow as pa

        codes = pa.array(rng.integers(0, len(pool), rows).astype(np.int32))
        table = pa.table(
            {"big": pa.DictionaryArray.from_arrays(codes, pa.array(pool))}
        )
        data = Dataset.from_arrow(table)
        true = len(np.unique(np.asarray(codes)))
        streamed = self._estimate(data, "big", placement="host", batch_size=65_536)
        single = self._estimate(data, "big", placement="host", batch_size=rows)
        assert streamed == single
        assert abs(streamed - true) / true < 0.10  # published 5% envelope + slack

    def test_seen_skip_with_where_filter_disabled_and_correct(self):
        cols = lowcard_table(rows=20_000)
        data = Dataset.from_dict(cols)
        a = ApproxCountDistinct("mode", where="num > 3")
        ctx = AnalysisRunner.do_analysis_run(
            data, [a], placement="host", batch_size=1024
        )
        one = AnalysisRunner.do_analysis_run(
            data, [a], placement="host", batch_size=20_000
        )
        assert ctx.metric(a).value.get() == one.metric(a).value.get()


class TestKllIntPick:
    def test_int64_pick_matches_numpy_sampler(self):
        from deequ_tpu.analyzers.sketches import _np_kll_sample
        from deequ_tpu.native import native_block_kll_pick

        if native_block_kll_pick is None:
            pytest.skip("native kernels unavailable")
        rng = np.random.default_rng(4)
        vals = rng.integers(-(10**12), 10**12, 100_000)
        for mask in (
            np.ones(len(vals), dtype=bool),
            rng.random(len(vals)) < 0.7,
        ):
            nv = int(mask.sum())
            items, m, h = native_block_kll_pick(vals, mask, 512, 11, nv)
            ref_items, rm, rh, rnv, _, _ = _np_kll_sample(
                vals.astype(np.float64), mask, 512, 11
            )
            assert (m, h) == (rm, rh)
            assert np.array_equal(items[:m], ref_items[:rm])

    def test_streamed_int_column_quantiles(self):
        rng = np.random.default_rng(5)
        vals = rng.integers(0, 1000, 200_000)
        data = Dataset.from_dict({"x": vals})
        a = KLLSketch("x")
        ctx = AnalysisRunner.do_analysis_run(
            data, [a], placement="host", batch_size=8192
        )
        dist = ctx.metric(a).value.get()
        total = sum(b.count for b in dist.buckets)
        assert total == len(vals)


class TestHistogramFastPaths:
    def test_dictionary_histogram_matches_pandas(self):
        cols = lowcard_table(rows=15_000)
        data = Dataset.from_dict(cols)
        assert data.dictionary_size("mode") == 40  # fast path engaged
        ctx = AnalysisRunner.do_analysis_run(
            data, [Histogram("mode")], batch_size=2048
        )
        dist = ctx.metric(Histogram("mode")).value.get()
        vc = pd.Series(cols["mode"]).value_counts()
        assert {k: v.absolute for k, v in dist.values.items()} == vc.to_dict()

    def test_dictionary_histogram_null_bin(self):
        import pyarrow as pa

        vals = ["a", "b", None, "a", None, "c", "a"]
        table = pa.table({"c": pa.array(vals).dictionary_encode()})
        ctx = AnalysisRunner.do_analysis_run(
            Dataset.from_arrow(table), [Histogram("c")], batch_size=3
        )
        dist = ctx.metric(Histogram("c")).value.get()
        got = {k: v.absolute for k, v in dist.values.items()}
        assert got == {"a": 3, "b": 1, "c": 1, "NullValue": 2}

    def test_small_range_integer_bincount_matches_unique(self):
        rng = np.random.default_rng(6)
        vals = rng.integers(-3, 9, 25_000)
        data = Dataset.from_dict({"i": vals})
        ctx = AnalysisRunner.do_analysis_run(
            data, [Histogram("i"), CountDistinct(["i"])], batch_size=4096
        )
        dist = ctx.metric(Histogram("i")).value.get()
        vc = pd.Series(vals).value_counts()
        assert {k: v.absolute for k, v in dist.values.items()} == {
            str(k): v for k, v in vc.items()
        }
        assert ctx.metric(CountDistinct(["i"])).value.get() == float(
            len(np.unique(vals))
        )

    def test_uint64_above_int63_bincount(self):
        # uint64 values past 2^63: widening to int64 would overflow, so the
        # unsigned path subtracts in-dtype (exact — the range is tiny)
        base = np.uint64(2**63)
        vals = np.array([base + 1, base + 5, base + 1, base + 3], dtype=np.uint64)
        data = Dataset.from_dict({"u": vals})
        ctx = AnalysisRunner.do_analysis_run(
            data, [CountDistinct(["u"]), Histogram("u")], batch_size=4
        )
        assert ctx.metric(CountDistinct(["u"])).value.get() == 3.0
        dist = ctx.metric(Histogram("u")).value.get()
        assert {k: v.absolute for k, v in dist.values.items()} == {
            str(int(base) + 1): 2, str(int(base) + 3): 1, str(int(base) + 5): 1
        }

    def test_narrow_int_dtype_full_range_bincount(self):
        # int8 spanning [-128, 127]: the offset subtraction must widen
        # first, or it wraps and np.bincount rejects the negatives
        vals = np.array([-128, 127, 0, -128, 127, 5], dtype=np.int8)
        data = Dataset.from_dict({"i": vals})
        ctx = AnalysisRunner.do_analysis_run(
            data, [CountDistinct(["i"]), Histogram("i")], batch_size=6
        )
        assert ctx.metric(CountDistinct(["i"])).value.get() == 4.0
        dist = ctx.metric(Histogram("i")).value.get()
        assert {k: v.absolute for k, v in dist.values.items()} == {
            "-128": 2, "127": 2, "0": 1, "5": 1
        }


class TestIngestProgramReuse:
    def test_programs_shared_across_columns_and_datasets(self):
        # VERDICT r4 #2: sub-programs are keyed by analyzer SIGNATURE
        # (class + state shapes), so a second battery over different
        # columns/datasets compiles NOTHING new
        from deequ_tpu.analyzers import Maximum, Mean, Minimum
        from deequ_tpu.runners import engine

        rng = np.random.default_rng(8)
        d1 = Dataset.from_dict({"a": rng.normal(size=5000), "b": rng.normal(size=5000)})
        battery1 = [Mean("a"), Minimum("a"), Maximum("b"), ApproxCountDistinct("b")]
        AnalysisRunner.do_analysis_run(d1, battery1, placement="host", batch_size=1024)
        n_programs = len(engine._INGEST_CACHE)
        d2 = Dataset.from_dict({"x": rng.normal(size=3000), "y": rng.normal(size=3000)})
        battery2 = [Mean("x"), Minimum("y"), Maximum("x"), ApproxCountDistinct("y")]
        ctx = AnalysisRunner.do_analysis_run(
            d2, battery2, placement="host", batch_size=512
        )
        assert len(engine._INGEST_CACHE) == n_programs
        assert ctx.metric(Mean("x")).value.is_success

    def test_tail_padded_bundle_results_exact(self):
        # 9 same-signature analyzers -> one full bundle + a padded tail;
        # results must equal the pandas oracle exactly
        from deequ_tpu.analyzers import Mean

        rng = np.random.default_rng(9)
        cols = {f"m{i}": rng.normal(size=20_000) for i in range(9)}
        data = Dataset.from_dict(cols)
        battery = [Mean(f"m{i}") for i in range(9)]
        ctx = AnalysisRunner.do_analysis_run(
            data, battery, placement="host", batch_size=2048
        )
        for i in range(9):
            got = ctx.metric(Mean(f"m{i}")).value.get()
            assert abs(got - cols[f"m{i}"].mean()) < 1e-9


class TestEncodeGuards:
    def test_clustered_high_cardinality_column_reverts(self):
        # head probe sees 1 distinct value, tail is ~all-unique: the
        # post-encode dictionary-size guard must leave the column plain
        rows = 400_000
        head = np.full(70_000, "constant", dtype=object)
        tail = np.array([f"u{i:07d}" for i in range(rows - 70_000)], dtype=object)
        data = Dataset.from_dict({"c": np.concatenate([head, tail])})
        assert data.dictionary_size("c") is None
