"""Signature-bundled device scan programs: parity and sharing contracts.

The bundled path (engine.BundledScanProgram) must be OBSERVATIONALLY
IDENTICAL to the monolithic one-program-per-battery design it replaces
(``DEEQU_TPU_SCAN_BUNDLE=0``): same metrics bit-for-bit, same states, on a
single device and under the 8-virtual-device mesh the conftest forces.
These tests pin that contract plus the slim-fetch protocol riding on it:

- bundled vs monolithic metrics are bit-identical (the acceptance bar);
- template-program reuse across columns is REAL (two batteries share one
  PackedScanProgram object) and the remapped features compute the right
  numbers, not the template column's;
- the slim fetch returns metrics identical to the full fetch, while runs
  that persist states still fetch FULL states (parity/ticks intact);
- battery-level warmth introspection stays conservative: shared bundle
  programs never make a never-dispatched battery read as warm.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Correlation,
    DataType,
    KLLParameters,
    KLLSketch,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor


@pytest.fixture
def scan_data():
    rng = np.random.default_rng(11)
    n = 8192
    x = rng.normal(size=n)
    x[rng.random(n) < 0.07] = np.nan
    return Dataset.from_dict(
        {
            "x": x,
            "y": rng.normal(size=n),
            "ints": rng.integers(0, 1000, n),
            "s": np.array(
                [["12", "ab", "3.5", "true", ""][i % 5] for i in range(n)],
                dtype=object,
            ),
        }
    )


def mixed_battery():
    return [
        Size(),
        Completeness("x"),
        Mean("x"),
        Sum("x"),
        Minimum("x"),
        Maximum("x"),
        StandardDeviation("x"),
        Mean("y"),
        Sum("y"),
        Correlation("x", "y"),
        DataType("s"),
        ApproxCountDistinct("ints"),
        KLLSketch("x", KLLParameters(256, 0.64, 10)),
    ]


def run_metrics(data, battery, *, bundle: str, slim: str, batch_size=2048):
    prior_bundle = os.environ.get("DEEQU_TPU_SCAN_BUNDLE")
    prior_slim = os.environ.get("DEEQU_TPU_SLIM_FETCH")
    os.environ["DEEQU_TPU_SCAN_BUNDLE"] = bundle
    os.environ["DEEQU_TPU_SLIM_FETCH"] = slim
    try:
        return AnalysisRunner.do_analysis_run(
            data, battery, batch_size=batch_size, placement="device"
        )
    finally:
        for var, prior in (
            ("DEEQU_TPU_SCAN_BUNDLE", prior_bundle),
            ("DEEQU_TPU_SLIM_FETCH", prior_slim),
        ):
            if prior is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = prior


def assert_contexts_identical(ctx_a, ctx_b):
    assert set(ctx_a.metric_map) == set(ctx_b.metric_map)
    for a in ctx_a.metric_map:
        va, vb = ctx_a.metric_map[a].value, ctx_b.metric_map[a].value
        assert va.is_success == vb.is_success, a
        if not va.is_success:
            continue
        ga, gb = va.get(), vb.get()
        if isinstance(ga, float):
            assert ga == gb or (np.isnan(ga) and np.isnan(gb)), (a, ga, gb)
        elif hasattr(ga, "buckets"):  # KLL BucketDistribution
            ba = [(b.low_value, b.high_value, b.count) for b in ga.buckets]
            bb = [(b.low_value, b.high_value, b.count) for b in gb.buckets]
            assert ba == bb, a
        else:
            assert str(ga) == str(gb), a


class TestBundledVsMonolithicParity:
    def test_metrics_bit_identical_single_device(self, scan_data):
        battery = mixed_battery()
        bundled = run_metrics(scan_data, battery, bundle="8", slim="1")
        mono = run_metrics(scan_data, battery, bundle="0", slim="1")
        assert_contexts_identical(bundled, mono)

    def test_metrics_bit_identical_on_8_device_mesh(self, scan_data):
        import jax

        from deequ_tpu.parallel import make_mesh

        assert len(jax.devices()) == 8  # the conftest's virtual-device mesh
        mesh = make_mesh()
        battery = mixed_battery()

        def run(bundle):
            prior = os.environ.get("DEEQU_TPU_SCAN_BUNDLE")
            os.environ["DEEQU_TPU_SCAN_BUNDLE"] = bundle
            try:
                return AnalysisRunner.do_analysis_run(
                    scan_data, battery, batch_size=2048, sharding=mesh,
                    placement="device",
                )
            finally:
                if prior is None:
                    os.environ.pop("DEEQU_TPU_SCAN_BUNDLE", None)
                else:
                    os.environ["DEEQU_TPU_SCAN_BUNDLE"] = prior

        assert_contexts_identical(run("8"), run("0"))

    def test_slim_fetch_metrics_equal_full_fetch(self, scan_data):
        battery = mixed_battery()
        slim = run_metrics(scan_data, battery, bundle="8", slim="1")
        full = run_metrics(scan_data, battery, bundle="8", slim="0")
        assert_contexts_identical(slim, full)


class TestProgramSharing:
    def test_two_batteries_share_one_program_object(self):
        from deequ_tpu.runners.engine import _fused_program

        prog_a = _fused_program((Mean("share_col_a"),), None)
        prog_b = _fused_program((Mean("share_col_b"),), None)
        assert prog_a is not prog_b  # battery-level orchestrators differ
        assert prog_a._programs[0] is prog_b._programs[0]  # compiled unit shared

    def test_remapped_columns_compute_their_own_values(self):
        # the shared template program must see each battery's OWN feature
        # arrays: if remapping broke, col_b would get col_a's numbers
        rng = np.random.default_rng(23)
        a_vals = rng.normal(10, 1, 2048)
        b_vals = rng.normal(-50, 5, 2048)
        data = Dataset.from_dict({"remap_a": a_vals, "remap_b": b_vals})
        ctx = AnalysisRunner.do_analysis_run(
            data, [Mean("remap_a"), Mean("remap_b")], placement="device"
        )
        got_a = ctx.metric(Mean("remap_a")).value.get()
        got_b = ctx.metric(Mean("remap_b")).value.get()
        assert got_a == pytest.approx(a_vals.mean(), rel=1e-12)
        assert got_b == pytest.approx(b_vals.mean(), rel=1e-12)

    def test_shared_programs_do_not_fake_battery_warmth(self):
        from deequ_tpu.runners.engine import (
            _fused_program,
            fused_program_is_cached,
        )

        warm_battery = (Mean("warmth_src_col"),)
        data = Dataset.from_dict(
            {"warmth_src_col": np.arange(128, dtype=np.float64)}
        )
        AnalysisRunner.do_analysis_run(
            data, list(warm_battery), placement="device"
        )
        assert fused_program_is_cached(warm_battery)
        # same signature, never dispatched: its bundle program is warm but
        # the BATTERY must not read as warm (placement keys on batteries)
        cold_battery = (Mean("warmth_never_ran_col"),)
        _fused_program(cold_battery, None)
        assert not fused_program_is_cached(cold_battery)


class TestSlimFetchStateContract:
    def test_persisting_runs_fetch_full_states(self, scan_data):
        from deequ_tpu.analyzers.state_provider import InMemoryStateProvider

        kll = KLLSketch("x", KLLParameters(256, 0.64, 10))
        sp = InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(
            scan_data, [kll], batch_size=2048, save_states_with=sp,
            placement="device",
        )
        state = sp.load(kll)
        # ticks drive future folds; the slim fetch drops them, so a
        # persisted state carrying real ticks proves the run fetched full
        assert int(np.asarray(state.ticks)) > 0
        assert np.asarray(state.parity).shape == np.asarray(state.sizes).shape

    def test_metric_leaves_contract_kll(self):
        # the indices KLL declares metric-bearing must match the state's
        # flatten order: items, sizes, count, g_min, g_max kept
        import jax

        kll = KLLSketch("contract_col", KLLParameters(64, 0.64, 5))
        state = kll.init_state()
        leaves = jax.tree_util.tree_leaves(state)
        kept = kll.metric_leaves()
        assert len(leaves) == 7
        dropped = [j for j in range(7) if j not in set(kept)]
        # dropped leaves are exactly parity (vector of level offsets) and
        # ticks (scalar update counter)
        shapes = [tuple(np.asarray(leaves[j]).shape) for j in dropped]
        assert sorted(shapes) == sorted(
            [tuple(np.asarray(state.parity).shape), ()]
        )
