"""Compaction lease/fence protocol (ISSUE 16 satellite 4).

The multi-writer partition store's single-compactor election: concurrent
compactors refuse, stale leases take over after the TTL with a bumped
epoch, a holder that loses the lease mid-merge aborts with every loose
entry readable, and a real two-process write/compact interleaving loses
no entry."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from deequ_tpu.analyzers import Size
from deequ_tpu.data import Dataset
from deequ_tpu.repository import (
    PartitionedMetricsRepository,
    ResultKey,
)
from deequ_tpu.repository.lease import FileLease
from deequ_tpu.runners import AnalysisRunner

pytestmark = pytest.mark.cluster

DAY_MS = 86_400_000
BASE_MS = 1_735_689_600_000  # 2025-01-01T00:00Z


@pytest.fixture(scope="module")
def ctx():
    data = Dataset.from_dict(
        {"x": np.random.default_rng(0).normal(10, 2, 32)}
    )
    return AnalysisRunner.do_analysis_run(data, [Size()])


def populate(repo, n, ctx, offset=0):
    for d in range(n):
        repo.save(ResultKey(BASE_MS + (offset + d) * DAY_MS), ctx)


class TestLeaseProtocol:
    def test_concurrent_compactor_refused(self, tmp_path, ctx):
        """While one process's compactor holds the lease, another
        repository's compact() is REFUSED (-1) and every entry stays
        loose and readable — refusal is never data loss."""
        root = str(tmp_path / "hist")
        a = PartitionedMetricsRepository(root, compact_threshold=10_000)
        b = PartitionedMetricsRepository(root, compact_threshold=10_000)
        b.lease.owner = "other-host:999"  # distinct owner, same lease file
        populate(a, 6, ctx)
        assert a.lease.acquire()
        try:
            assert b.compact("2025-01") == -1
            assert b.lease.refusals >= 1
            assert len(b.load().get()) == 6  # loose entries still serve
        finally:
            a.lease.release()
        # with the lease free, the refused compactor succeeds
        assert b.compact("2025-01") == 6
        assert len(b.load().get()) == 6

    def test_stale_lease_takeover_after_ttl(self, tmp_path):
        """A crashed holder's lease expires; the next contender takes
        over by atomic rename with a BUMPED epoch, and the old holder's
        fence checks fail from then on."""
        path = str(tmp_path / "x.lease")
        dead = FileLease(path, owner="dead:1", ttl_s=0.15)
        live = FileLease(path, owner="live:2", ttl_s=30.0)
        assert dead.acquire()
        assert not live.acquire()  # still fresh: refused
        assert live.refusals == 1
        time.sleep(0.3)  # the holder "crashed"; its TTL lapses
        assert live.acquire()
        assert live.takeovers == 1
        assert live.epoch == dead.epoch + 1  # the fence moved forward
        assert not dead.held()
        assert not dead.renew()  # the old holder can never fence again

    def test_lease_lost_mid_merge_leaves_loose_entries(
        self, tmp_path, ctx, monkeypatch
    ):
        """The FENCE: a compactor that stalls past its TTL and loses the
        lease mid-merge must abort BEFORE the destructive rewrite —
        every loose entry file survives and reads still merge them."""
        root = str(tmp_path / "hist")
        repo = PartitionedMetricsRepository(root, compact_threshold=10_000)
        populate(repo, 5, ctx)
        bucket_dir = tmp_path / "hist" / "2025-01"
        loose_before = sorted(
            f for f in os.listdir(bucket_dir) if f.startswith("e-")
        )
        assert len(loose_before) == 5
        monkeypatch.setattr(
            repo.lease, "renew", lambda: False
        )  # the takeover happened while we merged
        assert repo.compact("2025-01") == -1
        loose_after = sorted(
            f for f in os.listdir(bucket_dir) if f.startswith("e-")
        )
        assert loose_after == loose_before  # nothing deleted
        assert not (bucket_dir / "compacted.json").exists()
        assert len(repo.load().get()) == 5

    def test_crash_mid_compaction_recovers_by_takeover(self, tmp_path, ctx):
        """A lease file left behind by a crashed compactor defers
        compaction at most one TTL: refused while fresh, taken over
        once stale, and the data was readable throughout."""
        root = str(tmp_path / "hist")
        repo = PartitionedMetricsRepository(root, compact_threshold=10_000)
        populate(repo, 4, ctx)
        # simulate the crash: a foreign holder's lease file, never released
        crashed = FileLease(repo.lease.path, owner="crashed:7", ttl_s=0.2)
        assert crashed.acquire()
        assert repo.compact("2025-01") == -1  # fresh foreign lease: refused
        assert len(repo.load().get()) == 4
        time.sleep(0.4)
        assert repo.compact("2025-01") == 4  # stale: takeover + compact
        assert repo.lease.takeovers == 1
        assert len(repo.load().get()) == 4


WRITER_SCRIPT = """
import sys
import numpy as np
from deequ_tpu.analyzers import Size
from deequ_tpu.data import Dataset
from deequ_tpu.repository import PartitionedMetricsRepository, ResultKey
from deequ_tpu.runners import AnalysisRunner

root, n, offset = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
DAY_MS = 86_400_000
BASE_MS = 1_735_689_600_000
data = Dataset.from_dict({"x": np.random.default_rng(0).normal(10, 2, 32)})
ctx = AnalysisRunner.do_analysis_run(data, [Size()])
repo = PartitionedMetricsRepository(root, compact_threshold=10_000)
for d in range(n):
    repo.save(ResultKey(BASE_MS + (offset + d) * DAY_MS), ctx)
    repo.compact("2025-01")
print("done", flush=True)
"""


@pytest.mark.slow
class TestTwoProcessInterleaving:
    def test_interleaved_write_compact_loses_no_entry(self, tmp_path, ctx):
        """Two PROCESSES interleave appends and compactions on one store
        root under the lease: every entry either survives loose or lands
        in compacted.json — none is dropped by a racing rewrite."""
        root = str(tmp_path / "hist")
        n_child = 12
        child_offset = 12  # days 12-23: SAME 2025-01 bucket as the parent
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        child = subprocess.Popen(
            [sys.executable, "-c", WRITER_SCRIPT, root, str(n_child),
             str(child_offset)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        repo = PartitionedMetricsRepository(root, compact_threshold=10_000)
        n_parent = 12
        written = 0
        deadline = time.monotonic() + 240
        while written < n_parent or child.poll() is None:
            if time.monotonic() > deadline:
                child.kill()
                pytest.fail("interleaving run timed out")
            if written < n_parent:
                repo.save(
                    ResultKey(BASE_MS + written * DAY_MS), ctx
                )
                written += 1
                repo.compact("2025-01")
            else:
                time.sleep(0.05)
        out, err = child.communicate(timeout=30)
        assert child.returncode == 0, err.decode()[-500:]
        assert b"done" in out
        # every key from both writers present exactly once (distinct
        # timestamps; last-wins merge never collapses distinct keys)
        final = PartitionedMetricsRepository(root)
        stamps = sorted(e.result_key.data_set_date for e in
                        final.load().get())
        want = sorted(
            [BASE_MS + d * DAY_MS for d in range(n_parent)]
            + [BASE_MS + (child_offset + d) * DAY_MS
               for d in range(n_child)]
        )
        assert stamps == want
        # and a final elected compaction folds them all into one file
        assert final.compact("2025-01") == n_parent + n_child
        assert len(final.load().get()) == n_parent + n_child
