"""Predicate expression null-semantics tests (SQL 3-valued logic collapsed
to False, matching deequ's Catalyst predicate behavior)."""

import numpy as np
import pytest

from deequ_tpu.expr import evaluate_predicate


def cols():
    return {
        "s": np.array(["a", "b", None, None], dtype=object),
        "x": np.array([1.0, 2.0, np.nan, 4.0]),
    }


def test_neq_excludes_nulls():
    mask = evaluate_predicate("s != 'a'", cols(), 4)
    assert mask.tolist() == [False, True, False, False]


def test_eq_excludes_nulls():
    mask = evaluate_predicate("s == 'a'", cols(), 4)
    assert mask.tolist() == [True, False, False, False]


def test_length_null_is_false_under_comparison():
    mask = evaluate_predicate("length(s) < 3", cols(), 4)
    assert mask.tolist() == [True, True, False, False]
    mask = evaluate_predicate("length(s) >= 1", cols(), 4)
    assert mask.tolist() == [True, True, False, False]


def test_numeric_nan_comparisons_false():
    mask = evaluate_predicate("x > 0", cols(), 4)
    assert mask.tolist() == [True, True, False, True]
    mask = evaluate_predicate("x != 2", cols(), 4)
    assert mask.tolist() == [True, False, False, True]


def test_in_and_not_in():
    mask = evaluate_predicate("s in ('a', 'b')", cols(), 4)
    assert mask.tolist() == [True, True, False, False]
    mask = evaluate_predicate("s not in ('a',)", cols(), 4)
    assert mask.tolist() == [False, True, False, False]


def test_is_null_checks():
    mask = evaluate_predicate("s is None", cols(), 4)
    assert mask.tolist() == [False, False, True, True]
    mask = evaluate_predicate("s is not None", cols(), 4)
    assert mask.tolist() == [True, True, False, False]


def test_boolean_combinators():
    mask = evaluate_predicate("x >= 2 and s == 'b'", cols(), 4)
    assert mask.tolist() == [False, True, False, False]
    mask = evaluate_predicate("not (x > 1)", cols(), 4)
    # NaN > 1 is False, so `not` flips it to True: null-row caveat documented
    assert mask.tolist() == [True, False, True, False]


def test_inf_and_nan_pass_through_features():
    """Valid inf/NaN values must reach the device untouched (only nulls are
    zeroed)."""
    import pyarrow as pa

    from deequ_tpu.analyzers import Maximum, Mean
    from deequ_tpu.data import Dataset
    from deequ_tpu.runners import AnalysisRunner

    data = Dataset.from_arrow(pa.table({"x": pa.array([1.0, float("inf")])}))
    ctx = AnalysisRunner.do_analysis_run(data, [Maximum("x")])
    assert ctx.metric(Maximum("x")).value.get() == float("inf")


class TestImplicitCoercion:
    def test_string_column_numeric_comparisons(self):
        from deequ_tpu.expr import evaluate_predicate

        cols = {"s": np.array(["5", "7", "x", None], dtype=object)}
        assert list(evaluate_predicate("s >= 5", cols, 4)) == [True, True, False, False]
        assert list(evaluate_predicate("s == 5", cols, 4)) == [True, False, False, False]
        assert list(evaluate_predicate("s == 7", cols, 4)) == [False, True, False, False]

    def test_neq_uncastable_is_null(self):
        from deequ_tpu.expr import evaluate_predicate

        cols = {"s": np.array(["x", "5", None], dtype=object)}
        assert list(evaluate_predicate("s != 5", cols, 3)) == [False, False, False]
        assert list(evaluate_predicate("s != 7", cols, 3)) == [False, True, False]
