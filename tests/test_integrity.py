"""Data-plane integrity drills (ISSUE 4 acceptance): flipping one byte in
a persisted state blob, an FS repository entry, or a checkpoint payload
yields a typed ``CorruptStateError``, quarantine (not crash), and a
bit-exact resume/recompute — on both the device and host tiers. Plus the
checksum construction's pinned behavior and the chaos-marked injection
variants for the ``state_load`` / ``repository_load`` sites."""

import glob
import json
import os

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Histogram,
    KLLSketch,
    Mean,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers.state_provider import (
    FileSystemStateProvider,
    InMemoryStateProvider,
)
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import CorruptStateError
from deequ_tpu.reliability import FaultSpec, IngestCheckpointer, inject
from deequ_tpu.repository import ResultKey
from deequ_tpu.repository.fs import FileSystemMetricsRepository
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor


def _data(rows=4096, seed=0):
    rng = np.random.default_rng(seed)
    return Dataset.from_dict(
        {
            "x": rng.normal(size=rows),
            "c": [f"v{i % 37}" for i in range(rows)],
        }
    )


def _flip_byte(path, offset_fraction=0.5):
    blob = bytearray(open(path, "rb").read())
    blob[int(len(blob) * offset_fraction)] ^= 0xFF
    open(path, "wb").write(bytes(blob))


class TestChecksumConstruction:
    def test_small_and_large_paths_are_deterministic(self):
        from deequ_tpu.integrity import checksum_bytes

        small = b"meta record"
        big = np.random.default_rng(1).bytes(1 << 20)
        assert checksum_bytes(small) == checksum_bytes(small)
        assert checksum_bytes(big) == checksum_bytes(bytes(big))
        assert len(checksum_bytes(small)) == 16
        assert len(checksum_bytes(big)) == 16

    def test_single_byte_flip_always_detected(self):
        """Flip one byte at several positions incl. the un-word-aligned
        tail: the digest must change every time."""
        from deequ_tpu.integrity import checksum_bytes

        payload = bytearray(np.random.default_rng(2).bytes((1 << 16) + 5))
        base = checksum_bytes(bytes(payload))
        for pos in (0, 7, 8, 1 << 12, len(payload) - 3, len(payload) - 1):
            flipped = bytearray(payload)
            flipped[pos] ^= 0x01
            assert checksum_bytes(bytes(flipped)) != base, pos

    def test_transposed_regions_detected(self):
        """The position tag makes word swaps visible (a plain XOR of
        per-word hashes would not see them)."""
        from deequ_tpu.integrity import checksum_bytes

        payload = bytearray(np.random.default_rng(3).bytes(1 << 14))
        base = checksum_bytes(bytes(payload))
        swapped = bytearray(payload)
        swapped[0:8], swapped[8:16] = payload[8:16], payload[0:8]
        assert checksum_bytes(bytes(swapped)) != base

    def test_length_extension_detected(self):
        from deequ_tpu.integrity import checksum_bytes

        payload = np.random.default_rng(4).bytes(1 << 12)
        assert checksum_bytes(payload) != checksum_bytes(payload + b"\x00")


class TestStateBlobCorruption:
    """Byte-flip drills on the FileSystemStateProvider's two blob
    families, plus recompute parity through the verification engine."""

    def test_npz_flip_raises_typed(self, tmp_path):
        data = _data()
        sp = FileSystemStateProvider(str(tmp_path))
        AnalysisRunner.do_analysis_run(data, [Mean("x")], save_states_with=sp)
        for path in glob.glob(str(tmp_path / "*-state.npz")):
            _flip_byte(path)
        with pytest.raises(CorruptStateError):
            sp.load(Mean("x"))

    def test_parquet_flip_raises_typed(self, tmp_path):
        data = _data()
        sp = FileSystemStateProvider(str(tmp_path))
        AnalysisRunner.do_analysis_run(
            data, [Histogram("c")], save_states_with=sp
        )
        for path in glob.glob(str(tmp_path / "*-frequencies.parquet")):
            _flip_byte(path)
        with pytest.raises(CorruptStateError):
            sp.load(Histogram("c"))

    @pytest.mark.parametrize("placement", ["device", "host"])
    def test_corrupt_aggregate_state_degrades_only_its_analyzer(
        self, tmp_path, placement
    ):
        """A corrupt persisted state under ``aggregate_with`` degrades
        exactly the analyzer that needed it to a typed Failure metric —
        the rest of the battery completes with clean-run values, on BOTH
        tiers; a recompute without the corrupt store is bit-exact."""
        import shutil

        data = _data()
        battery = [Mean("x"), Sum("x"), Completeness("x")]
        store = tmp_path / "store"
        sp = FileSystemStateProvider(str(store))
        AnalysisRunner.do_analysis_run(
            data, battery, save_states_with=sp, placement=placement
        )
        pristine = tmp_path / "pristine"
        shutil.copytree(store, pristine)
        # corrupt ONLY Mean's blob (keyed file name starts with the
        # analyzer name)
        mean_key = sp._key(Mean("x"))
        _flip_byte(str(store / f"{mean_key}-state.npz"))
        ctx = AnalysisRunner.do_analysis_run(
            data, battery, aggregate_with=sp, placement=placement
        )
        assert ctx.metric_map[Mean("x")].value.is_failure
        assert isinstance(
            ctx.metric_map[Mean("x")].value.exception, CorruptStateError
        )
        # the rest of the battery's AGGREGATED values equal a run over an
        # uncorrupted copy of the same store (bit-exact recompute)
        clean = AnalysisRunner.do_analysis_run(
            data, battery,
            aggregate_with=FileSystemStateProvider(str(pristine)),
            placement=placement,
        )
        for a in (Sum("x"), Completeness("x")):
            assert (
                ctx.metric_map[a].value.get()
                == clean.metric_map[a].value.get()
            )

    def test_legacy_unchecksummed_blob_still_loads(self, tmp_path, caplog):
        """A v2 blob WITHOUT the __checksum__ member (pre-integrity build)
        loads unverified with a warn-once."""
        import logging

        sp = FileSystemStateProvider(str(tmp_path))
        a = Mean("x")
        base = str(tmp_path / sp._key(a))
        np.savez(
            base + "-state.npz",
            __format_version__=np.int64(2),
            __state_type__=np.str_("MeanState"),
            __static__=np.str_("{}"),
            leaf0=np.float64(45.0),
            leaf1=np.int64(10),
        )
        with caplog.at_level(logging.WARNING, logger="deequ_tpu.integrity"):
            state = sp.load(a)
        assert float(state.total) == 45.0 and int(state.count) == 10


class TestRepositoryQuarantine:
    def _saved_repo(self, tmp_path, monitor=None):
        data = _data()
        path = str(tmp_path / "history.json")
        repo = FileSystemMetricsRepository(path, monitor=monitor)
        ctx = AnalysisRunner.do_analysis_run(data, [Mean("x"), Sum("x")])
        repo.save(ResultKey(1, {"run": "a"}), ctx)
        repo.save(ResultKey(2, {"run": "b"}), ctx)
        return repo, path

    def test_entry_flip_quarantines_only_that_entry(self, tmp_path):
        monitor = RunMonitor()
        repo, path = self._saved_repo(tmp_path, monitor)
        raw = open(path).read()
        i = raw.index("Mean") + 1
        open(path, "w").write(
            raw[:i] + ("X" if raw[i] != "X" else "Y") + raw[i + 1:]
        )
        results = repo._read_all()
        assert len(results) == 1  # the clean entry keeps serving
        assert monitor.corrupt_quarantined == 1
        sidecars = os.listdir(path + ".quarantine")
        assert len(sidecars) == 1 and sidecars[0].startswith("entry-")
        # the preserved payload is the corrupt entry's JSON, forensically
        # intact
        preserved = json.load(
            open(os.path.join(path + ".quarantine", sidecars[0]))
        )
        assert "checksum" in preserved

    def test_structural_flip_quarantines_whole_file_and_recovers(
        self, tmp_path
    ):
        repo, path = self._saved_repo(tmp_path)
        raw = open(path).read()
        open(path, "w").write(raw.replace("[", "", 1))  # torn JSON
        assert repo._read_all() == []  # QUERIES: quarantined, not crashed
        assert os.path.isdir(path + ".quarantine")
        # a SAVE over the torn file refuses typed — rewriting would erase
        # whatever valid entries the torn payload still holds
        data = _data()
        ctx = AnalysisRunner.do_analysis_run(data, [Mean("x")])
        with pytest.raises(CorruptStateError, match="metrics-repository file"):
            repo.save(ResultKey(3), ctx)
        assert open(path).read() == raw.replace("[", "", 1)  # untouched
        # the operator restores/clears the file (bytes live in the
        # quarantine sidecar); saves work again
        os.unlink(path)
        repo.save(ResultKey(3), ctx)
        assert len(repo._read_all()) == 1

    def test_requarantine_is_idempotent(self, tmp_path):
        """Content-addressed sidecars: re-reading the same unrepaired
        corruption for weeks keeps ONE quarantine file, not one per read."""
        repo, path = self._saved_repo(tmp_path)
        raw = open(path).read()
        i = raw.index("Mean") + 1
        open(path, "w").write(
            raw[:i] + ("X" if raw[i] != "X" else "Y") + raw[i + 1:]
        )
        for _ in range(5):
            assert len(repo._read_all()) == 1
        assert len(os.listdir(path + ".quarantine")) == 1

    def test_loader_queries_survive_corruption(self, tmp_path):
        repo, path = self._saved_repo(tmp_path)
        raw = open(path).read()
        i = raw.index("Sum") + 1
        open(path, "w").write(
            raw[:i] + ("X" if raw[i] != "X" else "Y") + raw[i + 1:]
        )
        frames = repo.load().get_success_metrics_as_data_frame()
        assert len(frames) > 0  # the surviving entry's metrics

    def test_legacy_unchecksummed_entry_loads(self, tmp_path):
        """History written by a pre-checksum build (no per-entry checksum)
        still deserializes."""
        repo, path = self._saved_repo(tmp_path)
        entries = json.load(open(path))
        for e in entries:
            e.pop("checksum")
        open(path, "w").write(json.dumps(entries))
        assert len(repo._read_all()) == 2


class TestCheckpointCorruption:
    def _run(self, data, analyzers, ckpt=None, monitor=None, placement=None):
        return AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=1024, checkpointer=ckpt,
            monitor=monitor, placement=placement,
        )

    @pytest.mark.parametrize("placement", [None, "host"])
    def test_corrupt_checkpoint_falls_back_to_fresh_bitexact_fold(
        self, tmp_path, placement, monkeypatch
    ):
        """ISSUE acceptance: a flipped byte in a checkpoint state blob
        discards the resume point (typed, counted) and the fold restarts
        from batch 0 — recomputed metrics EQUAL the uninterrupted run's,
        on both tiers."""
        if placement == "host":
            from deequ_tpu.runners.engine import HOST_TIER_WORKERS_ENV

            monkeypatch.setenv(HOST_TIER_WORKERS_ENV, "2")
        # 80 batches: the host tier checkpoints on 32-batch chunk
        # boundaries, so the interrupt must land well past one chunk
        data = _data(rows=80 * 1024)
        analyzers = [Completeness("x"), Mean("x"), Sum("x"), KLLSketch("x")]
        uninterrupted = self._run(data, analyzers, placement=placement)
        provider_dir = tmp_path / (placement or "device")
        ckpt = IngestCheckpointer(
            FileSystemStateProvider(str(provider_dir)), every=4
        )
        site, at = (
            ("host_partial", 75) if placement == "host"
            else ("device_update", 11)
        )
        with inject(FaultSpec(site, "interrupt", at=at)):
            with pytest.raises(KeyboardInterrupt):
                self._run(data, analyzers, ckpt=ckpt, placement=placement)
        assert ckpt.saves  # a resume point exists
        for path in glob.glob(str(provider_dir / "*-state.npz")):
            _flip_byte(path)
        monitor = RunMonitor()
        resumed = self._run(
            data, analyzers, ckpt=ckpt, monitor=monitor, placement=placement
        )
        assert monitor.resumed_at_batch is None  # fresh fold, not resume
        assert ckpt.corrupt_discards >= 1
        assert monitor.corrupt_quarantined >= 1
        for a, metric in uninterrupted.metric_map.items():
            got = resumed.metric_map[a]
            if a.name == "KLLSketch":
                assert repr(got.value.get().buckets) == repr(
                    metric.value.get().buckets
                )
            else:
                assert got.value.get() == metric.value.get(), a

    def test_epoch_fence_refuses_stale_saves_and_completes(self, tmp_path):
        """The watchdog-abandoned-zombie defense: a pass fenced by a newer
        one (begin_run) can neither save a checkpoint nor clear the active
        pass's meta — its writes no-op, counted."""
        data = _data(rows=4 * 1024)
        analyzers = [Mean("x"), Sum("x")]
        ckpt = IngestCheckpointer(
            FileSystemStateProvider(str(tmp_path)), every=2
        )
        stale = ckpt.begin_run()
        current = ckpt.begin_run()  # fences `stale`
        sp = FileSystemStateProvider(str(tmp_path / "src"))
        AnalysisRunner.do_analysis_run(
            data, analyzers, save_states_with=sp, batch_size=1024
        )
        real_states = [sp.load(a) for a in analyzers]
        ckpt.save(2, 1024, 4096, analyzers, real_states, {}, epoch=stale)
        assert ckpt.saves == [] and ckpt.fenced_saves == 1
        ckpt.save(2, 1024, 4096, analyzers, real_states, {}, epoch=current)
        assert ckpt.saves == [(2, 2)]
        ckpt.complete(stale)  # must NOT clear the current resume point
        assert ckpt.fenced_saves == 2
        assert ckpt.load(1024, 4096, analyzers, []) is not None
        ckpt.complete(current)
        assert ckpt.load(1024, 4096, analyzers, []) is None

    def test_engine_passes_fence_each_other(self, tmp_path):
        """Each engine pass over a shared checkpointer bumps the epoch, so
        a save issued with a pre-pass token is refused."""
        data = _data(rows=8 * 1024)
        analyzers = [Mean("x"), Sum("x")]
        ckpt = IngestCheckpointer(
            FileSystemStateProvider(str(tmp_path)), every=2
        )
        zombie_epoch = ckpt.begin_run()
        AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=1024, checkpointer=ckpt
        )
        sp = FileSystemStateProvider(str(tmp_path / "src"))
        AnalysisRunner.do_analysis_run(
            data, analyzers, save_states_with=sp, batch_size=1024
        )
        ckpt.save(
            6, 1024, 8192, analyzers, [sp.load(a) for a in analyzers], {},
            epoch=zombie_epoch,
        )
        assert ckpt.fenced_saves == 1
        # the completed run cleared its meta; the zombie could not
        # resurrect a resume point
        assert ckpt.load(1024, 8192, analyzers, []) is None

    def test_tampered_meta_record_is_discarded(self, tmp_path):
        data = _data(rows=8 * 1024)
        analyzers = [Mean("x"), Sum("x")]
        ckpt = IngestCheckpointer(
            FileSystemStateProvider(str(tmp_path)), every=2
        )
        with inject(FaultSpec("device_update", "interrupt", at=5)):
            with pytest.raises(KeyboardInterrupt):
                self._run(data, analyzers, ckpt=ckpt)
        meta_path = str(tmp_path / "ingest-checkpoint-meta.json")
        meta = open(meta_path).read()
        assert '"checksum"' in meta
        # an off-by-one batch index would double-fold 2 batches on resume;
        # the checksum catches the tamper and the fold starts fresh
        tampered = meta.replace('"batch_index": 4', '"batch_index": 2')
        assert tampered != meta
        open(meta_path, "w").write(tampered)
        monitor = RunMonitor()
        result = self._run(data, analyzers, ckpt=ckpt, monitor=monitor)
        assert monitor.resumed_at_batch is None
        clean = AnalysisRunner.do_analysis_run(data, analyzers, batch_size=1024)
        for a, metric in clean.metric_map.items():
            assert result.metric_map[a].value.get() == metric.value.get()


@pytest.mark.chaos
class TestInjectedCorruption:
    """The seeded `corrupt` fault kind at the load sites: the recovery
    paths fire without any real bytes rotting."""

    def test_state_load_corrupt_degrades_analyzer(self, tmp_path):
        data = _data()
        sp = FileSystemStateProvider(str(tmp_path))
        battery = [Mean("x"), Sum("x")]
        AnalysisRunner.do_analysis_run(data, battery, save_states_with=sp)
        with inject(
            FaultSpec("state_load", "corrupt", match="Mean")
        ) as inj:
            ctx = AnalysisRunner.do_analysis_run(
                data, battery, aggregate_with=sp
            )
        assert inj.fired
        assert ctx.metric_map[Mean("x")].value.is_failure
        assert ctx.metric_map[Sum("x")].value.is_success

    def test_repository_load_corrupt_quarantines_whole_file(self, tmp_path):
        data = _data()
        path = str(tmp_path / "history.json")
        repo = FileSystemMetricsRepository(path)
        ctx = AnalysisRunner.do_analysis_run(data, [Mean("x")])
        repo.save(ResultKey(1), ctx)
        with inject(FaultSpec("repository_load", "corrupt", at=1)) as inj:
            assert repo._read_all() == []  # quarantined for THIS read
        assert inj.fired
        assert os.path.isdir(path + ".quarantine")
        # the source file was preserved: the next (uninjected) read serves
        assert len(repo._read_all()) == 1

    def test_checkpoint_state_corrupt_resumes_fresh(self):
        data = _data(rows=8 * 1024)
        analyzers = [Mean("x"), Sum("x")]
        provider = InMemoryStateProvider()
        ckpt = IngestCheckpointer(provider, every=2)
        with inject(FaultSpec("device_update", "interrupt", at=5)):
            with pytest.raises(KeyboardInterrupt):
                AnalysisRunner.do_analysis_run(
                    data, analyzers, batch_size=1024, checkpointer=ckpt
                )
        assert ckpt.saves
        # in-memory providers have no checksums (objects never serialize);
        # the corrupt kind injected at state_load covers the FS ones above
        monitor = RunMonitor()
        resumed = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=1024, checkpointer=ckpt,
            monitor=monitor,
        )
        assert monitor.resumed_at_batch == 4
        clean = AnalysisRunner.do_analysis_run(data, analyzers, batch_size=1024)
        for a, metric in clean.metric_map.items():
            assert resumed.metric_map[a].value.get() == metric.value.get()
