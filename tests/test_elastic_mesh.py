"""Elastic mesh fault tolerance (ISSUE 7): the multi-chip scan survives
shard loss.

The acceptance contract this file pins:

- an injected loss of one device mid-pass completes the battery with
  metrics equal to the uninterrupted run (salvage + re-shard + replay),
  with the loss visible as ONE connected trace (shard_loss -> salvage ->
  mesh_reshard -> completion) and counted on the export plane;
- a second loss walks the ladder down, ultimately landing on the host
  tier WITHOUT losing folded state;
- a checkpoint taken under one mesh shape resumes under a smaller one
  (8->4 and 4->1), equal to the uninterrupted run;
- a shard loss on the GSPMD device path re-shards at the pass level
  (classify_failure routes "mesh" to re-shard-before-host-failover);
- the DEEQU_TPU_MESH_LADDER / DEEQU_TPU_SHARD_HEARTBEAT_S knobs follow
  the warn-and-fallback convention.
"""

import numpy as np
import pytest

import jax

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    KLLParameters,
    KLLSketch,
    Maximum,
    Mean,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import ShardLossError, ShardStallError
from deequ_tpu.parallel import make_mesh
from deequ_tpu.reliability import FaultSpec, inject
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor

pytestmark = pytest.mark.mesh

ROWS = 24_000
BATCH = 512

ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    Sum("x"),
    StandardDeviation("x"),
    Maximum("x"),
    ApproxCountDistinct("y"),
    KLLSketch("x", KLLParameters(256, 0.64, 10)),
]


@pytest.fixture(scope="module")
def data():
    import pyarrow as pa

    rng = np.random.default_rng(17)
    x = rng.normal(5, 2, ROWS)
    return Dataset.from_arrow(
        pa.table(
            {
                "x": pa.array(x, mask=rng.random(ROWS) < 0.1),
                "y": pa.array(rng.integers(0, 700, ROWS)),
            }
        )
    )


@pytest.fixture(scope="module")
def clean(data):
    """The uninterrupted-run oracle (mesh-free host tier)."""
    return AnalysisRunner.do_analysis_run(
        data, ANALYZERS, batch_size=BATCH, placement="host"
    )


def assert_parity(clean_ctx, lossy_ctx, rel=1e-9):
    for a in ANALYZERS:
        cv = clean_ctx.metric(a).value
        lv = lossy_ctx.metric(a).value
        assert cv.is_success and lv.is_success, a
        if isinstance(a, KLLSketch):
            ck = sum(b.count for b in cv.get().buckets)
            lk = sum(b.count for b in lv.get().buckets)
            assert ck == lk, a
        else:
            assert lv.get() == pytest.approx(cv.get(), rel=rel), a


class TestShardLossRecovery:
    def test_single_loss_salvage_reshard_parity(self, data, clean):
        """One shard dies mid-fold: surviving states salvage, the mesh
        rebuilds 8->4, the lost shard's batches replay, metrics match."""
        mon = RunMonitor()
        with inject(
            FaultSpec("sharded_fold", "mesh_loss", at=2, shard=5)
        ) as inj:
            lossy = AnalysisRunner.do_analysis_run(
                data, ANALYZERS, batch_size=BATCH, sharding=make_mesh(8),
                placement="host", monitor=mon,
            )
        assert inj.fired == ["sharded_fold::mesh_loss"]
        assert mon.shard_losses == 1
        assert mon.mesh_reshards == 1
        assert mon.salvaged_states == 7
        assert "mesh:8->4" in mon.degraded
        assert_parity(clean, lossy)

    def test_loss_during_collective_merge(self, data, clean):
        """The final butterfly merge is a loss site too: the merge itself
        recovers (salvage + re-shard + re-merge)."""
        mon = RunMonitor()
        with inject(
            FaultSpec("collective_merge", "mesh_loss", at=1, shard=2)
        ) as inj:
            lossy = AnalysisRunner.do_analysis_run(
                data, ANALYZERS, batch_size=BATCH, sharding=make_mesh(8),
                placement="host", monitor=mon,
            )
        assert inj.fired
        assert mon.shard_losses == 1
        assert mon.mesh_reshards == 1
        assert_parity(clean, lossy)

    def test_second_loss_walks_ladder_to_host(self, data, clean, monkeypatch):
        """Two losses with a truncated ladder: 8->4, then 4 loses a shard
        with no rung left -> the fold lands on the HOST tier with the
        salvaged canonical states (folded work kept, run completes)."""
        from deequ_tpu.parallel import elastic

        monkeypatch.setenv(elastic.MESH_LADDER_ENV, "8,4")
        mon = RunMonitor()
        with inject(
            FaultSpec("sharded_fold", "mesh_loss", at=1, shard=7),
            FaultSpec("sharded_fold", "mesh_loss", at=2, shard=0),
        ) as inj:
            lossy = AnalysisRunner.do_analysis_run(
                data, ANALYZERS, batch_size=BATCH, sharding=make_mesh(8),
                placement="host", monitor=mon,
            )
        assert len(inj.fired) == 2
        assert mon.shard_losses == 2
        assert mon.mesh_reshards == 2
        assert "mesh:8->4" in mon.degraded
        assert "mesh:host" in mon.degraded
        assert_parity(clean, lossy)

    def test_shard_stall_kind_recovers_like_loss(self, data, clean):
        """shard_stall (heartbeat-declared wedge) takes the same salvage
        path as a thrown loss — ShardStallError IS a ShardLossError."""
        assert issubclass(ShardStallError, ShardLossError)
        mon = RunMonitor()
        with inject(
            FaultSpec("sharded_fold", "shard_stall", at=2, shard=3)
        ):
            lossy = AnalysisRunner.do_analysis_run(
                data, ANALYZERS, batch_size=BATCH, sharding=make_mesh(8),
                placement="host", monitor=mon,
            )
        assert mon.shard_losses == 1 and mon.mesh_reshards == 1
        assert_parity(clean, lossy)

    def test_pass_level_reshard_on_device_path(self, data, clean):
        """A loss on the GSPMD device path (replicated states, no per-shard
        salvage site) escapes the engine and re-shards at the PASS level:
        classify_failure routes "mesh" to re-shard-before-host-failover."""
        from deequ_tpu.reliability import classify_failure

        assert classify_failure(ShardLossError([3], "x")) == "mesh"
        mon = RunMonitor()
        with inject(
            FaultSpec("device_update", "mesh_loss", at=2, shard=3)
        ) as inj:
            lossy = AnalysisRunner.do_analysis_run(
                data, ANALYZERS, batch_size=BATCH, sharding=make_mesh(8),
                monitor=mon,
            )
        assert inj.fired
        assert mon.mesh_reshards == 1
        assert "mesh:pass_reshard" in mon.degraded
        # the re-run stayed on a (smaller) mesh, not the host tier
        assert mon.device_failovers == 0
        assert_parity(clean, lossy)


class TestConnectedTrace:
    def test_loss_is_one_connected_trace(self, data, clean):
        """Acceptance: shard_loss -> salvage -> mesh_reshard -> completion
        all ride ONE trace_id, with the typed failure event recorded."""
        from deequ_tpu.observability.recorder import recorder

        recorder().clear()
        mon = RunMonitor()
        with inject(FaultSpec("sharded_fold", "mesh_loss", at=2, shard=5)):
            lossy = AnalysisRunner.do_analysis_run(
                data, ANALYZERS, batch_size=BATCH, sharding=make_mesh(8),
                placement="host", monitor=mon,
            )
        assert_parity(clean, lossy)
        spans = recorder().spans()
        assert spans and len({s.trace_id for s in spans}) == 1
        events = [ev["name"] for s in spans for ev in s.events]
        for expected in ("shard_loss", "salvage", "mesh_reshard",
                         "mesh_replay"):
            assert expected in events, (expected, events)
        failures = [
            ev for s in spans for ev in s.events if ev["name"] == "failure"
        ]
        assert any(
            ev["attrs"]["type"] == "ShardLossError" for ev in failures
        )
        passes = [s for s in spans if s.name == "engine_pass"]
        assert passes and passes[-1].status == "ok"
        recorder().clear()


class TestExportPlane:
    def test_mesh_counters_reach_prometheus(self, data):
        """A service job absorbing a shard loss surfaces
        deequ_service_{shard_losses,mesh_reshards,salvaged_states}_total."""
        from deequ_tpu.checks import Check, CheckLevel
        from deequ_tpu.service import VerificationService

        check = (
            Check(CheckLevel.ERROR, "mesh battery")
            .has_size(lambda n: n == ROWS)
            .has_mean("x", lambda m: 4 < m < 6)
        )
        with inject(FaultSpec("sharded_fold", "mesh_loss", at=2, shard=5)):
            with VerificationService(
                workers=1, mesh=make_mesh(8), background_warm=False,
            ) as svc:
                # a cold battery routes to the host tier, which on a mesh
                # service IS the sharded elastic fold path
                result = svc.verify(data, [check], timeout=300, batch_size=BATCH)
                text = svc.prometheus_text()
                counters = svc.json_snapshot()["counters"]
        from deequ_tpu.checks import CheckStatus

        assert result.status == CheckStatus.SUCCESS
        assert "deequ_service_shard_losses_total" in text

        def total(name: str) -> float:
            out = 0.0
            for k, v in counters.items():
                if k.startswith(name):
                    out += sum(v.values()) if isinstance(v, dict) else v
            return out

        assert total("deequ_service_shard_losses_total") >= 1
        assert total("deequ_service_mesh_reshards_total") >= 1
        assert total("deequ_service_salvaged_states_total") >= 1


class TestCrossShapeCheckpoint:
    @pytest.mark.parametrize("big,small", [(8, 4), (4, 1)])
    def test_checkpoint_resumes_on_smaller_mesh(self, data, clean, big, small):
        """A checkpoint taken under one mesh shape resumes under a smaller
        one: states checkpoint in CANONICAL merged form and the batch-size
        quantum keeps batch boundaries put across the ladder."""
        from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
        from deequ_tpu.reliability import IngestCheckpointer

        ckpt = IngestCheckpointer(InMemoryStateProvider(), every=8)
        with pytest.raises(KeyboardInterrupt):
            with inject(FaultSpec("ingest_fold", "interrupt", at=2)):
                AnalysisRunner.do_analysis_run(
                    data, ANALYZERS, batch_size=BATCH,
                    sharding=make_mesh(big), placement="host",
                    checkpointer=ckpt,
                )
        assert ckpt.saves, "the interrupted run must have checkpointed"
        mon = RunMonitor()
        resumed = AnalysisRunner.do_analysis_run(
            data, ANALYZERS, batch_size=BATCH, sharding=make_mesh(small),
            placement="host", checkpointer=ckpt, monitor=mon,
        )
        assert mon.resumed_at_batch == ckpt.saves[-1][0]
        assert mon.resumed_at_batch > 0
        assert_parity(clean, resumed)

    def test_mesh_and_plain_host_checkpoints_interchange(self, data, clean):
        """The canonical form is tier-independent too: a mesh checkpoint
        resumes on the PLAIN (mesh-free) host tier."""
        from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
        from deequ_tpu.reliability import IngestCheckpointer

        ckpt = IngestCheckpointer(InMemoryStateProvider(), every=8)
        with pytest.raises(KeyboardInterrupt):
            with inject(FaultSpec("ingest_fold", "interrupt", at=2)):
                AnalysisRunner.do_analysis_run(
                    data, ANALYZERS, batch_size=BATCH,
                    sharding=make_mesh(8), placement="host",
                    checkpointer=ckpt,
                )
        mon = RunMonitor()
        resumed = AnalysisRunner.do_analysis_run(
            data, ANALYZERS, batch_size=BATCH, placement="host",
            checkpointer=ckpt, monitor=mon,
        )
        assert mon.resumed_at_batch and mon.resumed_at_batch > 0
        assert_parity(clean, resumed)

    def test_non_quantum_batch_size_resumes_across_tiers(self, data, clean):
        """A nominal batch size that is NOT a ladder-quantum multiple must
        still resume mesh->plain-host: checkpointed runs round to the
        quantum on BOTH sides, so the meta's batch_size matches."""
        from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
        from deequ_tpu.reliability import IngestCheckpointer

        ckpt = IngestCheckpointer(InMemoryStateProvider(), every=8)
        with pytest.raises(KeyboardInterrupt):
            with inject(FaultSpec("ingest_fold", "interrupt", at=2)):
                AnalysisRunner.do_analysis_run(
                    data, ANALYZERS, batch_size=500,
                    sharding=make_mesh(8), placement="host",
                    checkpointer=ckpt,
                )
        mon = RunMonitor()
        resumed = AnalysisRunner.do_analysis_run(
            data, ANALYZERS, batch_size=500, placement="host",
            checkpointer=ckpt, monitor=mon,
        )
        assert mon.resumed_at_batch and mon.resumed_at_batch > 0
        assert_parity(clean, resumed)


class TestHealthProbe:
    def test_probe_reports_injected_dead_shard(self):
        from deequ_tpu.parallel import probe_shards

        mesh = make_mesh(4)
        assert probe_shards(mesh) == []
        with inject(
            FaultSpec("shard_probe", "mesh_loss", at=3, shard=2)
        ):
            # at=3: the probe of position 2 (1-based hit numbering)
            assert probe_shards(mesh) == [2]

    def test_heartbeat_gate_is_time_gated(self):
        from deequ_tpu.parallel.health import HeartbeatGate

        gate = HeartbeatGate(interval_s=3600.0)
        assert not gate.due()  # just constructed
        gate._last -= 7200.0
        assert gate.due()
        assert gate.check(make_mesh(2)) == []
        assert not gate.due()  # check() re-arms the timer

    def test_disabled_heartbeat_never_due(self, monkeypatch):
        from deequ_tpu.parallel import health

        monkeypatch.setenv(health.HEARTBEAT_ENV, "0")
        assert health.shard_heartbeat_s() is None
        gate = health.HeartbeatGate()
        gate._last -= 7200.0
        assert not gate.due()


class TestEnvKnobs:
    def test_mesh_ladder_parses(self, monkeypatch):
        from deequ_tpu.parallel import elastic

        monkeypatch.setenv(elastic.MESH_LADDER_ENV, "4,2")
        assert elastic.mesh_ladder() == (4, 2)

    def test_mesh_ladder_warns_and_falls_back(self, monkeypatch, caplog):
        import logging

        from deequ_tpu.parallel import elastic

        monkeypatch.setenv(elastic.MESH_LADDER_ENV, "eight,four")
        monkeypatch.setattr(elastic, "_ENV_WARNED", False)
        with caplog.at_level(logging.WARNING, logger=elastic.__name__):
            assert elastic.mesh_ladder() == elastic.DEFAULT_MESH_LADDER
        assert any("DEEQU_TPU_MESH_LADDER" in r.message for r in caplog.records)

    def test_heartbeat_warns_and_falls_back(self, monkeypatch, caplog):
        import logging

        from deequ_tpu import utils
        from deequ_tpu.parallel import health

        monkeypatch.setenv(health.HEARTBEAT_ENV, "5s")
        # the heartbeat knob now rides the SHARED utils.env_number parser
        # (ISSUE 14's env-knob convention): reset its warn-once latch
        monkeypatch.setattr(utils, "_ENV_WARNED", set())
        with caplog.at_level(logging.WARNING, logger=utils.__name__):
            assert health.shard_heartbeat_s() == health.DEFAULT_HEARTBEAT_S
        assert any(
            "DEEQU_TPU_SHARD_HEARTBEAT_S" in r.message for r in caplog.records
        )

    def test_batch_quantum_is_ladder_shape_independent(self):
        from deequ_tpu.parallel import mesh_batch_quantum

        # every rung of the default ladder rounds to the same quantum, so
        # batch boundaries (and checkpoint meta) survive a re-shard
        assert len({mesh_batch_quantum(n) for n in (1, 2, 4, 8)}) == 1


class TestElasticUnits:
    def test_salvage_drops_exactly_the_lost_shards(self):
        from deequ_tpu.parallel import salvage_stacked_states
        from deequ_tpu.runners.engine import ScanEngine

        analyzers = [Size(), Mean("x")]
        per_shard = []
        for d in range(4):
            states, _ = ScanEngine(analyzers).run(
                Dataset.from_dict({"x": np.full(10 * (d + 1), float(d))})
            )
            per_shard.append(states)
        stacked = tuple(
            jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *[s[i] for s in per_shard],
            )
            for i in range(len(analyzers))
        )
        shard_states, salvaged = salvage_stacked_states(
            analyzers, stacked, lost=[1]
        )
        assert salvaged == [0, 2, 3]
        sizes = [int(np.asarray(s[0].num_matches)) for s in shard_states]
        assert sizes == [10, 30, 40]

    def test_host_merge_equals_collective_merge(self):
        from deequ_tpu.parallel import (
            collective_merge_states,
            host_merge_states,
        )
        from deequ_tpu.runners.engine import ScanEngine

        rng = np.random.default_rng(3)
        analyzers = [Size(), Mean("x"), StandardDeviation("x"), Sum("x")]
        per_shard = []
        for d in range(5):
            states, _ = ScanEngine(analyzers).run(
                Dataset.from_dict({"x": rng.normal(d, 1, 500)})
            )
            per_shard.append(states)
        stacked = tuple(
            jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *[s[i] for s in per_shard],
            )
            for i in range(len(analyzers))
        )
        collective = collective_merge_states(analyzers, make_mesh(4), stacked)
        salvage = host_merge_states(analyzers, per_shard)
        for i, a in enumerate(analyzers):
            mc = a.compute_metric_from(
                jax.tree_util.tree_map(np.asarray, collective[i])
            )
            ms = a.compute_metric_from(salvage[i])
            assert ms.value.get() == pytest.approx(
                mc.value.get(), rel=1e-12
            ), a

    def test_stack_canonical_roundtrip(self):
        from deequ_tpu.parallel import (
            host_merge_states,
            stack_canonical_states,
        )
        from deequ_tpu.runners.engine import ScanEngine

        analyzers = [Size(), Sum("x")]
        states, _ = ScanEngine(analyzers).run(
            Dataset.from_dict({"x": np.arange(100, dtype=np.float64)})
        )
        canonical = tuple(
            jax.tree_util.tree_map(np.asarray, s) for s in states
        )
        stacked = stack_canonical_states(analyzers, canonical, 4)
        shard_states = [
            tuple(
                jax.tree_util.tree_map(lambda x, _d=d: np.asarray(x[_d]), t)
                for t in stacked
            )
            for d in range(4)
        ]
        merged = host_merge_states(analyzers, shard_states)
        assert int(np.asarray(merged[0].num_matches)) == 100
        assert float(np.asarray(merged[1].total)) == pytest.approx(4950.0)

    def test_next_rung(self):
        from deequ_tpu.parallel import next_rung

        assert next_rung((8, 4, 2, 1), 7) == 4
        assert next_rung((8, 4, 2, 1), 4) == 4
        assert next_rung((8, 4, 2, 1), 1) == 1
        assert next_rung((8, 4), 3) is None
        assert next_rung((8, 4, 2, 1), 0) is None


class TestDictionaryMemoReplay:
    """Replayed batches must RE-CONTRIBUTE dictionary-memo work: the HLL
    dictionary skip credits an entry to the first batch that saw it, and
    when that batch's shard dies the replay must not skip the entry
    (pre-fix it did — a silent ApproxCountDistinct undercount under
    shard loss; ISSUE 12 review find)."""

    def test_replayed_batches_recontribute_dictionary_entries(self):
        import pyarrow as pa

        from deequ_tpu.analyzers import ApproxCountDistinct
        from deequ_tpu.parallel import make_mesh
        from deequ_tpu.reliability import FaultSpec, inject

        rows, batch = 24_000, 512
        # canary values live ONLY in batches 20-23 — exactly shard 5's
        # slice of the first 32-batch chunk fold (local_chunk=4), so a
        # loss of shard 5 at the SECOND fold makes those batches replay
        values = []
        for i in range(rows):
            b = i // batch
            if 20 <= b <= 23:
                values.append(f"canary{i % 200}")
            else:
                values.append(f"base{i % 300}")
        data = Dataset.from_arrow(
            pa.table({"d": pa.array(values).dictionary_encode()})
        )
        analyzers = [ApproxCountDistinct("d")]
        clean = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=batch, sharding=make_mesh(8),
            placement="host",
        )
        mon = RunMonitor()
        with inject(
            FaultSpec("sharded_fold", "mesh_loss", at=2, shard=5)
        ) as inj:
            lossy = AnalysisRunner.do_analysis_run(
                data, analyzers, batch_size=batch, sharding=make_mesh(8),
                placement="host", monitor=mon,
            )
        assert inj.fired and mon.shard_losses >= 1
        a = analyzers[0]
        # same entry set -> identical HLL registers -> EXACT equality;
        # a dropped canary contribution shows as an undercount
        assert lossy.metric(a).value.get() == clean.metric(a).value.get()
