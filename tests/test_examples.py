"""Run every example end to end and assert on its outcome — the
`examples/ExamplesTest.scala` analog: the examples double as the
integration-test layer for the public API surface."""

import sys
from pathlib import Path

import numpy as np
import pytest

REPO_ROOT = str(Path(__file__).resolve().parent.parent)
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from deequ_tpu import CheckStatus
from deequ_tpu.constraints import ConstraintStatus


class TestExamples:
    def test_basic_example(self, capsys):
        from examples import basic_example

        result = basic_example.main()
        # productName has a null -> the ERROR-level isComplete fails; the
        # URL ratio is 2/5 < 0.5 -> the WARNING check fails too
        assert result.status == CheckStatus.ERROR
        statuses = {
            str(cr.constraint): cr.status
            for check_result in result.check_results.values()
            for cr in check_result.constraint_results
        }
        failed = [c for c, s in statuses.items() if s != ConstraintStatus.SUCCESS]
        assert len(failed) == 2
        assert "We found errors" in capsys.readouterr().out

    def test_incremental_metrics_example(self):
        from examples import incremental_metrics_example
        from deequ_tpu.analyzers import ApproxCountDistinct, Completeness, Size

        first, combined = incremental_metrics_example.main()
        assert first.metric(Size()).value.get() == 3.0
        assert combined.metric(Size()).value.get() == 5.0
        assert combined.metric(ApproxCountDistinct("id")).value.get() == 5.0
        assert combined.metric(Completeness("description")).value.get() == pytest.approx(0.4)

    def test_update_metrics_on_partitioned_data_example(self):
        from examples import update_metrics_on_partitioned_data_example
        from deequ_tpu.analyzers import Completeness

        table, updated = update_metrics_on_partitioned_data_example.main()
        assert table.metric(Completeness("manufacturerName")).value.get() == 1.0
        # the refreshed US partition introduced one null name (6 of 7 left)
        assert updated.metric(Completeness("manufacturerName")).value.get() == pytest.approx(6 / 7)

    def test_metrics_repository_example(self, capsys):
        from examples import metrics_repository_example

        frame = metrics_repository_example.main()
        out = capsys.readouterr().out
        assert "completeness of the productName column is: 0.8" in out
        assert len(frame) == 5  # five successful integrity metrics stored

    def test_anomaly_detection_example(self):
        from examples import anomaly_detection_example

        result = anomaly_detection_example.main()
        # size jumped 2 -> 5, more than the allowed 2x increase
        assert result.status != CheckStatus.SUCCESS

    def test_data_profiling_example(self):
        from examples import data_profiling_example
        from deequ_tpu.profiles import NumericColumnProfile

        result = data_profiling_example.main()
        total = result.profiles["totalNumber"]
        assert isinstance(total, NumericColumnProfile)
        assert total.minimum == 1.0
        assert total.maximum == 20.0
        assert total.mean == pytest.approx(11.0)
        assert total.data_type == "Fractional"
        status = result.profiles["status"]
        hist = {k: v.absolute for k, v in status.histogram.values.items()}
        assert hist == {"DELAYED": 4, "IN_TRANSIT": 2, "UNKNOWN": 2}

    def test_constraint_suggestion_example(self):
        from examples import constraint_suggestion_example

        result = constraint_suggestion_example.main()
        suggestions = result.all_suggestions
        assert suggestions
        columns = {s.column_name for s in suggestions}
        assert {"productName", "status"} <= columns
        # every suggestion carries runnable code
        assert all(s.code_for_constraint for s in suggestions)

    def test_kll_example(self):
        from examples import kll_example
        from deequ_tpu.profiles import NumericColumnProfile

        result = kll_example.main()
        num_views = result.column_profiles["numViews"]
        assert isinstance(num_views, NumericColumnProfile)
        assert num_views.kll is not None
        # KLLParameters(2, 0.64, 2): parameters = [shrinking_factor, sketch_size]
        assert num_views.kll.parameters == [0.64, 2.0]
        assert len(num_views.kll.buckets) == 2
        assert sum(b.count for b in num_views.kll.buckets) == 5

    def test_kll_check_example(self, capsys):
        from examples import kll_check_example

        result = kll_check_example.main()
        # max 12 > 10 and sketch size 2 < 16: both constraints fail
        assert result.status == CheckStatus.ERROR
        failed = [
            cr
            for check_result in result.check_results.values()
            for cr in check_result.constraint_results
            if cr.status != ConstraintStatus.SUCCESS
        ]
        assert len(failed) == 2
        assert "We found errors" in capsys.readouterr().out

    def test_multi_device_example(self, capsys):
        import jax

        from examples import multi_device_example

        sharded, merged, offline = multi_device_example.main()
        # all three distribution modes returned the same metric set
        assert set(sharded) == set(merged) == set(offline)
        n_devices = min(len(jax.devices()), 8)
        assert sharded["Size"] == n_devices * 4096
        assert "all three distribution modes agree" in capsys.readouterr().out

    def test_continuous_verification_example(self, capsys):
        from examples import continuous_verification_example

        statuses, flaky_handle, shed, snapshot = (
            continuous_verification_example.main()
        )
        # the injected-null batch surfaces its WARNING on that very merge
        assert statuses == [
            CheckStatus.SUCCESS, CheckStatus.SUCCESS, CheckStatus.WARNING,
        ]
        # the injected transient failure retried once and then succeeded
        assert flaky_handle.attempts == 2
        assert flaky_handle.result().status == CheckStatus.SUCCESS
        # the burst beyond the queue bound was shed, and the export plane
        # reconciles: accepted - shed, per tenant
        assert shed > 0
        counters = snapshot["counters"]
        assert counters["deequ_service_jobs_shed_total"]["tenant=burst"] == shed
        assert (
            counters["deequ_service_stream_batches_total"][
                "dataset=clickstream,tenant=tenant-a"
            ]
            == 3
        )
        out = capsys.readouterr().out
        assert "ServiceOverloaded" in out and "--- /metrics" in out
