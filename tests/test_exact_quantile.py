"""Exact-quantile mode (VERDICT r5 ask #9): ``relative_error=0.0``.

The reference admits ``relativeError=0`` as exact Greenwald-Khanna mode
(`analyzers/ApproxQuantiles.scala:30`); a KLL sketch cannot be exact in
bounded memory, so here 0.0 routes the analyzer OFF the fused scan onto a
host full-sort accumulator (`analyzers/sketches.py ExactQuantileState`)
that still rides the single shared pass and matches ``numpy.quantile``
bit-for-bit at O(n) host memory (the documented price of exactness).
"""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.analyzers import ApproxQuantile, ApproxQuantiles, Mean
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import IllegalAnalyzerParameterException
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor


@pytest.fixture
def quantile_data():
    rng = np.random.default_rng(5)
    n = 40001  # odd count: the median interpolates between real values
    vals = rng.normal(size=n) * 100
    vals[rng.random(n) < 0.04] = np.nan
    flags = rng.integers(0, 10, n)
    return Dataset.from_dict({"v": vals, "flag": flags}), vals, flags


class TestExactQuantile:
    def test_matches_numpy_exactly(self, quantile_data):
        data, vals, _ = quantile_data
        for q in (0.0, 0.25, 0.5, 0.9, 1.0):
            analyzer = ApproxQuantile("v", q, relative_error=0.0)
            ctx = AnalysisRunner.do_analysis_run(data, [analyzer], batch_size=4096)
            got = ctx.metric(analyzer).value.get()
            want = float(np.nanquantile(vals, q))
            assert got == want, (q, got, want)  # BIT-exact, not approx

    def test_multiple_quantiles_exact(self, quantile_data):
        data, vals, _ = quantile_data
        analyzer = ApproxQuantiles("v", (0.1, 0.5, 0.99), relative_error=0.0)
        ctx = AnalysisRunner.do_analysis_run(data, [analyzer], batch_size=4096)
        got = ctx.metric(analyzer).value.get()
        for q in (0.1, 0.5, 0.99):
            assert got[str(q)] == float(np.nanquantile(vals, q))

    def test_where_filter_exact(self, quantile_data):
        data, vals, flags = quantile_data
        analyzer = ApproxQuantile("v", 0.5, relative_error=0.0, where="flag < 5")
        ctx = AnalysisRunner.do_analysis_run(data, [analyzer], batch_size=4096)
        got = ctx.metric(analyzer).value.get()
        want = float(np.nanquantile(vals[flags < 5], 0.5))
        assert got == want

    def test_shares_the_single_pass(self, quantile_data):
        # exactness must not buy a second data pass: the accumulator folds
        # through the same shared scan as every other analyzer
        data, vals, _ = quantile_data
        mon = RunMonitor()
        ctx = AnalysisRunner.do_analysis_run(
            data,
            [ApproxQuantile("v", 0.5, relative_error=0.0), Mean("v")],
            batch_size=4096,
            monitor=mon,
        )
        assert mon.passes == 1
        assert ctx.metric(Mean("v")).value.is_success
        assert ctx.metric(
            ApproxQuantile("v", 0.5, relative_error=0.0)
        ).value.get() == float(np.nanquantile(vals, 0.5))

    def test_empty_after_filter_is_empty_metric(self):
        data = Dataset.from_dict({"v": [1.0, 2.0], "flag": [1, 1]})
        analyzer = ApproxQuantile("v", 0.5, relative_error=0.0, where="flag > 5")
        ctx = AnalysisRunner.do_analysis_run(data, [analyzer])
        assert not ctx.metric(analyzer).value.is_success

    def test_aggregated_states_merge_by_concatenation(self):
        from deequ_tpu.analyzers.sketches import ExactQuantileState

        a = ExactQuantileState().add(np.array([1.0, 5.0]))
        b = ExactQuantileState().add(np.array([2.0, 9.0, 3.0]))
        merged = a.merge(b)
        assert merged.count == 5
        assert float(np.quantile(merged.values(), 0.5)) == 3.0

    def test_checkpointer_is_dropped_not_blown(self, quantile_data):
        # ExactQuantileState is deliberately unregistered for persistence;
        # a configured checkpointer must degrade to "no checkpoints" with a
        # warning (the mesh precedent), never raise mid-save or silently
        # lose the whole battery to bisection
        data, vals, _ = quantile_data
        from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
        from deequ_tpu.reliability import IngestCheckpointer

        ck = IngestCheckpointer(InMemoryStateProvider(), every=1)
        mon = RunMonitor()
        ctx = AnalysisRunner.do_analysis_run(
            data,
            [ApproxQuantile("v", 0.5, relative_error=0.0), Mean("v")],
            batch_size=4096,
            checkpointer=ck,
            monitor=mon,
        )
        assert mon.checkpoint_saves == 0  # dropped, not attempted
        assert mon.isolation_reruns == 0  # and nothing degraded
        assert ctx.metric(
            ApproxQuantile("v", 0.5, relative_error=0.0)
        ).value.get() == float(np.nanquantile(vals, 0.5))
        assert ctx.metric(Mean("v")).value.is_success

    def test_negative_relative_error_still_rejected(self):
        data = Dataset.from_dict({"v": [1.0, 2.0]})
        analyzer = ApproxQuantile("v", 0.5, relative_error=-0.1)
        ctx = AnalysisRunner.do_analysis_run(data, [analyzer])
        value = ctx.metric(analyzer).value
        assert not value.is_success
        assert isinstance(value.exception, IllegalAnalyzerParameterException)
        assert "interval [0, 1]" in str(value.exception)

    def test_nonzero_error_stays_kll_backed(self, quantile_data):
        # relative_error > 0 must keep riding the fused device scan: no
        # host accumulator, bounded memory, approximate answer near truth
        data, vals, _ = quantile_data
        analyzer = ApproxQuantile("v", 0.5, relative_error=0.01)
        assert not analyzer.host_exclusive
        ctx = AnalysisRunner.do_analysis_run(data, [analyzer], batch_size=4096)
        got = ctx.metric(analyzer).value.get()
        want = float(np.nanquantile(vals, 0.5))
        # rank error 0.01 over ~40k values: generous value-space envelope
        assert abs(got - want) < 10.0
