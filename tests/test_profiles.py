"""Column profiler tests — the analog of the reference
`profiles/ColumnProfilerTest.scala` / `KLL/KLLProfileTest.scala`."""

import json

import numpy as np
import pytest

from deequ_tpu.data import Dataset
from deequ_tpu.profiles import (
    ColumnProfiler,
    ColumnProfilerRunner,
    NumericColumnProfile,
    StandardColumnProfile,
    determine_type,
)
from deequ_tpu.runners.engine import RunMonitor


@pytest.fixture
def mixed_data():
    return Dataset.from_dict(
        {
            "item": ["1", "2", "3", "4", "5", "6"],
            "att1": ["a", "b", "a", "a", "b", "a"],
            "numeric_string": ["1.5", "2.5", "3.5", None, "5.5", "6.5"],
            "int_string": ["1", "2", "3", "4", "5", "6"],
            "num": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0],
            "ints": [10, 20, 30, 40, 50, 60],
            "bools": [True, False, True, False, True, True],
        }
    )


class TestColumnProfiler:
    def test_profile_types(self, mixed_data):
        profiles = ColumnProfiler.profile(mixed_data)
        assert profiles.num_records == 6
        assert profiles["att1"].data_type == "String"
        assert isinstance(profiles["att1"], StandardColumnProfile)
        # string columns with numeric content are detected + promoted
        assert profiles["numeric_string"].data_type == "Fractional"
        assert isinstance(profiles["numeric_string"], NumericColumnProfile)
        assert profiles["int_string"].data_type == "Integral"
        assert profiles["item"].data_type == "Integral"
        # non-string columns keep their known types
        assert profiles["num"].data_type == "Fractional"
        assert profiles["num"].is_data_type_inferred is False
        assert profiles["ints"].data_type == "Integral"
        assert profiles["bools"].data_type == "Boolean"

    def test_numeric_statistics(self, mixed_data):
        profiles = ColumnProfiler.profile(mixed_data)
        p = profiles["num"]
        assert p.mean == pytest.approx(3.5)
        assert p.minimum == 1.0
        assert p.maximum == 6.0
        assert p.sum == 21.0
        assert p.std_dev == pytest.approx(np.std([1, 2, 3, 4, 5, 6]))
        assert len(p.approx_percentiles) == 100
        assert p.approx_percentiles[0] == 1.0
        assert p.approx_percentiles[-1] == 6.0
        # casted string column gets numeric stats too (nulls excluded)
        ps = profiles["numeric_string"]
        assert ps.mean == pytest.approx((1.5 + 2.5 + 3.5 + 5.5 + 6.5) / 5)
        assert ps.completeness == pytest.approx(5 / 6)

    def test_histograms_low_cardinality(self, mixed_data):
        profiles = ColumnProfiler.profile(mixed_data)
        h = profiles["att1"].histogram
        assert h is not None
        assert h["a"].absolute == 4
        assert h["b"].absolute == 2
        assert h["a"].ratio == pytest.approx(4 / 6)
        # booleans histogrammed as their string forms
        hb = profiles["bools"].histogram
        assert hb is not None
        assert hb["true"].absolute == 4

    def test_histogram_threshold(self):
        data = Dataset.from_dict({"many": [str(i) for i in range(300)]})
        profiles = ColumnProfiler.profile(data, low_cardinality_histogram_threshold=120)
        assert profiles["many"].histogram is None
        profiles2 = ColumnProfiler.profile(data, low_cardinality_histogram_threshold=1000)
        assert profiles2["many"].histogram is not None

    def test_pass_count(self, mixed_data):
        """Full profile in <= 3 data passes; the third only exists when a
        casted numeric-string column also needs a histogram (reference
        always needs 3, `ColumnProfiler.scala:57-68`)."""
        mon = RunMonitor()
        ColumnProfiler.profile(mixed_data, monitor=mon)
        assert mon.passes == 3  # mixed_data has casted histogram columns
        mon2 = RunMonitor()
        data = Dataset.from_dict({"x": [1.0, 2.0], "s": ["a", "b"]})
        ColumnProfiler.profile(data, monitor=mon2)
        assert mon2.passes == 2  # no casted histogram columns -> 2 passes

    def test_histogram_keys_are_original_strings(self):
        """Numeric-string histograms key by ORIGINAL values, not the casted
        floats (reference pass 3 reads the raw data)."""
        data = Dataset.from_dict({"int_string": ["1", "2", "3", "1"]})
        profiles = ColumnProfiler.profile(data)
        hist = profiles["int_string"].histogram
        assert set(hist.values.keys()) == {"1", "2", "3"}
        assert hist["1"].absolute == 2

    def test_histogram_nan_vs_null(self):
        import pyarrow as pa

        data = Dataset.from_arrow(
            pa.table({"f": pa.array([1.0, float("nan"), None], type=pa.float64())})
        )
        from deequ_tpu.analyzers import Histogram
        from deequ_tpu.runners import AnalysisRunner

        ctx = AnalysisRunner.do_analysis_run(data, [Histogram("f")])
        hist = ctx.metric(Histogram("f")).value.get()
        assert hist["NullValue"].absolute == 1
        # JVM Double.toString renders NaN as "NaN" (not Python's 'nan')
        assert hist["NaN"].absolute == 1
        assert hist["1.0"].absolute == 1

    def test_predefined_types_not_inferred(self, mixed_data):
        profiles = ColumnProfiler.profile(
            mixed_data, predefined_types={"int_string": "Integral"}
        )
        assert profiles["int_string"].is_data_type_inferred is False

    def test_restrict_to_columns(self, mixed_data):
        profiles = ColumnProfiler.profile(mixed_data, restrict_to_columns=["num"])
        assert set(profiles.profiles) == {"num"}
        with pytest.raises(ValueError):
            ColumnProfiler.profile(mixed_data, restrict_to_columns=["nope"])

    def test_predefined_types(self, mixed_data):
        profiles = ColumnProfiler.profile(
            mixed_data, predefined_types={"int_string": "String"}
        )
        assert profiles["int_string"].data_type == "String"
        assert isinstance(profiles["int_string"], StandardColumnProfile)

    def test_runner_builder_and_json(self, mixed_data, tmp_path):
        path = str(tmp_path / "profiles.json")
        profiles = (
            ColumnProfilerRunner.on_data(mixed_data)
            .restrict_to_columns(["num", "att1"])
            .save_column_profiles_json_to_path(path)
            .run()
        )
        payload = json.loads(open(path).read())
        by_col = {c["column"]: c for c in payload["columns"]}
        assert by_col["num"]["mean"] == pytest.approx(3.5)
        assert by_col["att1"]["dataType"] == "String"
        assert {h["value"] for h in by_col["att1"]["histogram"]} == {"a", "b"}

    def test_repository_reuse(self, mixed_data):
        from deequ_tpu.repository import InMemoryMetricsRepository, ResultKey

        repo = InMemoryMetricsRepository()
        key = ResultKey(1)
        p1 = ColumnProfiler.profile(
            mixed_data,
            metrics_repository=repo,
            save_in_metrics_repository_using_key=key,
        )
        mon = RunMonitor()
        p2 = ColumnProfiler.profile(
            mixed_data,
            metrics_repository=repo,
            reuse_existing_results_using_key=key,
            monitor=mon,
        )
        assert mon.passes == 0  # fully served from the repository
        assert p2["num"].mean == p1["num"].mean

    def test_kll_in_profile(self, mixed_data):
        from deequ_tpu.analyzers import KLLParameters

        profiles = ColumnProfiler.profile(
            mixed_data, kll_parameters=KLLParameters(512, 0.64, 3)
        )
        kll = profiles["num"].kll
        assert kll is not None
        assert len(kll.buckets) == 3
        assert sum(b.count for b in kll.buckets) == 6


class TestDetermineType:
    def _dist(self, **counts):
        from deequ_tpu.metrics import Distribution, DistributionValue

        total = sum(counts.values()) or 1
        return Distribution(
            {k: DistributionValue(v, v / total) for k, v in counts.items()},
            number_of_bins=len(counts),
        )

    def test_decision_tree(self):
        assert determine_type(self._dist(Unknown=5)) == "Unknown"
        assert determine_type(self._dist(String=1, Integral=5)) == "String"
        assert determine_type(self._dist(Boolean=1, Integral=1)) == "String"
        assert determine_type(self._dist(Boolean=3, Unknown=1)) == "Boolean"
        assert determine_type(self._dist(Fractional=1, Integral=5)) == "Fractional"
        assert determine_type(self._dist(Integral=5, Unknown=2)) == "Integral"


class TestProfilerPassCounts:
    """Schema-typed numeric columns profile in the FIRST scan (the reference
    needs its pass 2, `ColumnProfiler.scala:153-171`); pass 2 only runs for
    inference-casted string columns, pass 3 only for histogram targets."""

    def test_native_numeric_high_cardinality_profiles_in_one_pass(self):
        import numpy as np

        from deequ_tpu.profiles import ColumnProfilerRunner, NumericColumnProfile
        from deequ_tpu.runners.engine import RunMonitor

        rng = np.random.default_rng(0)
        a = rng.normal(size=5000)
        data = Dataset.from_dict({"a": a, "b": rng.integers(0, 10**9, 5000)})
        mon = RunMonitor()
        result = ColumnProfilerRunner.on_data(data).with_monitor(mon).run()
        assert mon.passes == 1, mon.passes
        profile = result.profiles["a"]
        assert isinstance(profile, NumericColumnProfile)
        assert profile.mean == pytest.approx(float(a.mean()), rel=1e-9)
        assert profile.kll is not None

    def test_low_cardinality_strings_profile_in_one_pass(self):
        import numpy as np

        from deequ_tpu.profiles import ColumnProfilerRunner
        from deequ_tpu.runners.engine import RunMonitor

        rng = np.random.default_rng(1)
        data = Dataset.from_dict(
            {
                "n": rng.normal(size=2000),
                "c": [f"c{int(v)}" for v in rng.integers(0, 5, 2000)],
            }
        )
        # ingest-time adaptive dictionary encoding makes the low-card string
        # column's histogram eligible for pass 1 (distinct <= dictionary
        # size <= threshold), so the whole profile is ONE data pass — the
        # reference needs three (`ColumnProfiler.scala:57-68`)
        mon = RunMonitor()
        result = ColumnProfilerRunner.on_data(data).with_monitor(mon).run()
        assert mon.passes == 1, mon.passes
        assert result.profiles["c"].histogram is not None
        hist = result.profiles["c"].histogram
        assert sum(v.absolute for v in hist.values.values()) == 2000

    def test_unencoded_low_cardinality_strings_add_histogram_pass(self, monkeypatch):
        import numpy as np

        from deequ_tpu.data import ADAPTIVE_DICT_ENCODE_ENV
        from deequ_tpu.profiles import ColumnProfilerRunner
        from deequ_tpu.runners.engine import RunMonitor

        monkeypatch.setenv(ADAPTIVE_DICT_ENCODE_ENV, "0")
        rng = np.random.default_rng(1)
        data = Dataset.from_dict(
            {
                "n": rng.normal(size=2000),
                "c": [f"c{int(v)}" for v in rng.integers(0, 5, 2000)],
            }
        )
        mon = RunMonitor()
        result = ColumnProfilerRunner.on_data(data).with_monitor(mon).run()
        assert mon.passes == 2, mon.passes  # pass 1 + histogram pass; no cast pass
        assert result.profiles["c"].histogram is not None

    def test_casted_string_column_still_two_data_passes(self):
        from deequ_tpu.profiles import ColumnProfilerRunner, NumericColumnProfile
        from deequ_tpu.runners.engine import RunMonitor

        data = Dataset.from_dict(
            {"t": [f"{i}.5" for i in range(200)]}  # numeric-looking strings
        )
        mon = RunMonitor()
        result = ColumnProfilerRunner.on_data(data).with_monitor(mon).run()
        profile = result.profiles["t"]
        assert isinstance(profile, NumericColumnProfile)
        assert profile.mean == pytest.approx(sum(i + 0.5 for i in range(200)) / 200)
        assert mon.passes >= 2  # inference pass + casted numeric pass
