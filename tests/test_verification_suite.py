"""End-to-end VerificationSuite scenarios, ported from the reference's
`VerificationSuiteTest.scala` (the 417-LoC integration layer): status
aggregation across check levels in any order, required analyzers alongside
checks, anomaly-check wiring with explicit configs and history windows,
state persistence hooks, repository conflict semantics, and constraint
ordering guarantees."""

import math

import pytest

from deequ_tpu import (
    AnomalyCheckConfig,
    Check,
    CheckLevel,
    CheckStatus,
    DoubleMetric,
    Entity,
    InMemoryMetricsRepository,
    ResultKey,
    Success,
    VerificationSuite,
)
from deequ_tpu.analyzers import (
    Completeness,
    MutualInformation,
    Size,
    Sum,
    Uniqueness,
)
from deequ_tpu.anomalydetection import AbsoluteChangeStrategy
from deequ_tpu.data import Dataset
from deequ_tpu.runners.context import AnalyzerContext


def _df_with_n_rows(n: int) -> Dataset:
    return Dataset.from_dict(
        {"item": [f"{i}" for i in range(n)], "att1": [f"v{i}" for i in range(n)]}
    )


class TestStatusAggregation:
    """The suite status is the max over check statuses, independent of the
    order checks were added (reference `:60-85`)."""

    def _checks(self):
        return [
            Check(CheckLevel.ERROR, "group-1").has_size(lambda s: s == 12),  # succeeds
            Check(CheckLevel.WARNING, "group-2-W").has_completeness(
                "att2", lambda v: v > 0.8
            ),  # warns (att2 completeness is 8/12)
            Check(CheckLevel.ERROR, "group-2-E").has_size(lambda s: s > 50),  # errors
        ]

    @pytest.mark.parametrize("order", [(0, 1, 2), (2, 1, 0), (1, 2, 0)])
    def test_error_dominates_in_any_order(self, df_missing, order):
        checks = self._checks()
        suite = VerificationSuite.on_data(df_missing)
        for i in order:
            suite = suite.add_check(checks[i])
        assert suite.run().status == CheckStatus.ERROR

    def test_warning_when_no_error(self, df_missing):
        result = (
            VerificationSuite.on_data(df_missing)
            .add_check(self._checks()[0])
            .add_check(self._checks()[1])
            .run()
        )
        assert result.status == CheckStatus.WARNING


class TestRequiredAnalyzers:
    def test_mandatory_analysis_alongside_checks(self, df_full):
        """(reference `:87-122`) — required analyzers of every entity kind
        run in the same pass and land in the suite metrics."""
        check = (
            Check(CheckLevel.ERROR, "group-1")
            .is_complete("att1")
            .has_completeness("att1", lambda v: v == 1.0)
        )
        result = (
            VerificationSuite.on_data(df_full)
            .add_check(check)
            .add_required_analyzers(
                [Size(), Completeness("att2"), Uniqueness(["att2"]),
                 MutualInformation(["att1", "att2"])]
            )
            .run()
        )
        assert result.status == CheckStatus.SUCCESS
        metrics = result.metrics
        assert metrics[Size()].value.get() == 4.0
        assert metrics[Completeness("att2")].value.get() == 1.0
        # att2 = [c, d, d, f]: two singleton groups of four rows
        assert metrics[Uniqueness(["att2"])].value.get() == 0.5
        # att1 = [a, b, a, a], att2 = [c, d, d, f]
        mi = metrics[MutualInformation(["att1", "att2"])].value.get()
        pxy = [0.25, 0.25, 0.25, 0.25]
        px = {"a": 0.75, "b": 0.25}
        py = {"c": 0.25, "d": 0.5, "f": 0.25}
        want = (
            0.25 * math.log(0.25 / (px["a"] * py["c"]))
            + 0.25 * math.log(0.25 / (px["b"] * py["d"]))
            + 0.25 * math.log(0.25 / (px["a"] * py["d"]))
            + 0.25 * math.log(0.25 / (px["a"] * py["f"]))
        )
        assert mi == pytest.approx(want, rel=1e-9)

    def test_runs_with_no_constraints(self, df_full):
        """(reference `:125-140`) — a suite with only required analyzers
        still computes metrics."""
        result = VerificationSuite.on_data(df_full).add_required_analyzer(Size()).run()
        assert result.status == CheckStatus.SUCCESS
        assert result.metrics[Size()].value.get() == 4.0


class TestRepositorySemantics:
    def test_new_results_preferred_on_conflict(self, df_numeric):
        """(reference `:225-249`) — saveOrAppend overwrites conflicting
        previous metrics for the same key."""
        repository = InMemoryMetricsRepository()
        key = ResultKey(0, {})
        stale = AnalyzerContext(
            {Size(): DoubleMetric(Entity.DATASET, "Size", "*", Success(100.0))}
        )
        repository.save(key, stale)

        result = (
            VerificationSuite.on_data(df_numeric)
            .use_repository(repository)
            .add_required_analyzers([Size(), Completeness("item")])
            .save_or_append_result(key)
            .run()
        )
        loaded = repository.load_by_key(key)
        assert loaded.metric(Size()).value.get() == 6.0  # not the stale 100.0
        assert loaded.metric(Completeness("item")).value.get() == result.metrics[
            Completeness("item")
        ].value.get()


class TestAnomalyCheckWiring:
    """(reference `:251-287` + `evaluateWithRepositoryWithHistory`)."""

    def _repository_with_history(self) -> InMemoryMetricsRepository:
        repository = InMemoryMetricsRepository()
        for ts in (1, 2):
            repository.save(
                ResultKey(ts, {"Region": "EU"}),
                AnalyzerContext(
                    {Size(): DoubleMetric(Entity.DATASET, "Size", "*", Success(float(ts)))}
                ),
            )
        for ts in (3, 4):
            repository.save(
                ResultKey(ts, {"Region": "NA"}),
                AnalyzerContext(
                    {Size(): DoubleMetric(Entity.DATASET, "Size", "*", Success(float(ts)))}
                ),
            )
        return repository

    def test_multiple_anomaly_checks_with_configs(self):
        repository = self._repository_with_history()
        df = _df_with_n_rows(11)
        result = (
            VerificationSuite.on_data(df)
            .use_repository(repository)
            .add_required_analyzers([Completeness("item")])
            .save_or_append_result(ResultKey(5, {}))
            .add_anomaly_check(
                AbsoluteChangeStrategy(-2.0, 2.0),
                Size(),
                AnomalyCheckConfig(CheckLevel.WARNING, "Anomaly check to fail"),
            )
            .add_anomaly_check(
                AbsoluteChangeStrategy(-7.0, 7.0),
                Size(),
                AnomalyCheckConfig(
                    CheckLevel.ERROR, "Anomaly check to succeed", {}, 0, 11
                ),
            )
            .add_anomaly_check(AbsoluteChangeStrategy(-7.0, 7.0), Size())
            .run()
        )
        statuses = [cr.status for cr in result.check_results.values()]
        # size jumped 4 -> 11: |7| > 2 trips the first check (WARNING level),
        # |7| <= 7 passes the other two
        assert statuses[0] == CheckStatus.WARNING
        assert statuses[1] == CheckStatus.SUCCESS
        assert statuses[2] == CheckStatus.SUCCESS


class TestStatePersistence:
    def test_state_persister_called_and_states_aggregatable(self, df_numeric):
        """(reference `:316-360`) — saveStatesWith captures mergeable
        states; aggregateWith folds them into a later run (doubling sums
        when the same data is seen twice)."""
        from deequ_tpu.analyzers.state_provider import InMemoryStateProvider

        provider = InMemoryStateProvider()
        analyzers = [Sum("att2"), Completeness("att1")]
        (
            VerificationSuite.on_data(df_numeric)
            .add_required_analyzers(analyzers)
            .save_states_with(provider)
            .run()
        )
        assert provider.load(Sum("att2")) is not None
        result = (
            VerificationSuite.on_data(df_numeric)
            .add_required_analyzers(analyzers)
            .aggregate_with(provider)
            .run()
        )
        assert result.metrics[Sum("att2")].value.get() == 18.0 * 2
        assert result.metrics[Completeness("att1")].value.get() == 1.0


class TestConstraintOrdering:
    def test_constraint_results_keep_declaration_order(self, df_numeric):
        """(reference `:362-392`)."""
        from deequ_tpu.constraints import completeness_constraint, compliance_constraint

        expected = [
            completeness_constraint("att1", lambda v: v == 1.0),
            compliance_constraint("att1 is positive", "att1 > 0", lambda v: v == 1.0),
        ]
        check = Check(CheckLevel.ERROR, "check")
        for c in expected:
            check = check.add_constraint(c)
        assert list(check.constraints) == expected

        result = VerificationSuite.on_data(df_numeric).add_check(check).run()
        pairs = list(
            zip(check.constraints, result.check_results[check].constraint_results, strict=True)
        )
        assert len(pairs) == len(expected)
        for declared, evaluated in pairs:
            assert declared == evaluated.constraint
