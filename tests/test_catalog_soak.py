"""Tenant-catalog soak tool: the ISSUE 17 acceptance drills at tiny
tier-1 scale (the CI-sized soak is `python -m tools.catalog_soak`; the
bench's `catalog_soak` stage runs it detached and `bench_diff` gates the
`gated_throughput_fraction` scalar)."""

import pytest

from tools.catalog_soak import run_gate_throughput, run_tiering_soak

pytestmark = pytest.mark.catalog


def test_small_tiering_soak_drills_hold():
    summary = run_tiering_soak(registered=20, active=4, batches=2,
                               rows=512, workers=2)
    assert summary["ok"], summary
    assert summary["hot_count"] == 4  # hot tier tracks ACTIVE tenants
    assert summary["registered_count"] == 20
    assert summary["edit_drill"]["reloads"] == 1
    assert summary["corrupt_drill"]["quarantine_bumps"] == 1
    assert summary["corrupt_drill"]["preserved"] == 1


def test_small_gate_throughput_bit_exact():
    """Tiny frames make the timing fraction meaningless (interpreter
    noise dwarfs both folds) — tier-1 pins the CORRECTNESS half of the
    drill: bit-exact metrics and the gate-rows counter."""
    summary = run_gate_throughput(batches=3, rows=2048)
    assert summary["ok"], summary
    assert summary["bit_exact"]
    assert summary["gate_rows"] == 3 * 2048
