"""JVM interop (VERDICT r5 ask #5, first leg): the reference's HLL
word-array state blob (`StateProvider.scala:187-311` persistLongArrayState
layout — big-endian int32 word count + big-endian int64 words) reads into
a live ApproxCountDistinctState; ``words_to_registers`` finally has a
production consumer. Fixture-blob round trips are bit-exact and the
cardinality estimate is identical on both sides."""

import struct

import numpy as np
import pytest

from deequ_tpu.analyzers import ApproxCountDistinct
from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import CorruptStateError
from deequ_tpu.interop import (
    JVM_HLL_BLOB_BYTES,
    read_jvm_hll_state_blob,
    write_jvm_hll_state_blob,
)
from deequ_tpu.ops.hll import M, NUM_WORDS, registers_to_words
from deequ_tpu.runners.analysis_runner import AnalysisRunner


def _engine_state(rows=5000, distinct=700):
    data = Dataset.from_dict({"c": [f"v{i % distinct}" for i in range(rows)]})
    provider = InMemoryStateProvider()
    AnalysisRunner.do_analysis_run(
        data, [ApproxCountDistinct("c")], save_states_with=provider
    )
    return provider.load(ApproxCountDistinct("c"))


class TestBlobLayout:
    def test_fixture_blob_layout_pinned(self):
        """A hand-built blob in the reference layout: register i holds
        value (i % 61). 6-bit registers, 10 per word, little-endian within
        the word; the FILE layout is big-endian JVM DataOutputStream."""
        registers = np.array([i % 61 for i in range(M)], dtype=np.int32)
        words = registers_to_words(registers)
        blob = struct.pack(">i", NUM_WORDS) + words.view(np.int64).astype(
            ">i8"
        ).tobytes()
        assert len(blob) == JVM_HLL_BLOB_BYTES
        state = read_jvm_hll_state_blob(blob)
        np.testing.assert_array_equal(np.asarray(state.registers), registers)

    def test_word_zero_bit_layout_pinned(self):
        """Registers [1, 2, 3, 0, ...] pack into word0 as 1 | 2<<6 | 3<<12
        (the StatefulHyperloglogPlus 6-bit stride); pin the exact long so
        the byte layout can never silently flip endianness or stride."""
        registers = np.zeros(M, dtype=np.int32)
        registers[0], registers[1], registers[2] = 1, 2, 3
        blob = write_jvm_hll_state_blob(
            type("S", (), {"registers": registers})()
        )
        (count,) = struct.unpack_from(">i", blob, 0)
        (word0,) = struct.unpack_from(">q", blob, 4)
        assert count == NUM_WORDS
        assert word0 == (1 | (2 << 6) | (3 << 12))


class TestRoundTrip:
    def test_engine_state_round_trips_bit_exact(self):
        state = _engine_state()
        blob = write_jvm_hll_state_blob(state)
        assert len(blob) == JVM_HLL_BLOB_BYTES
        back = read_jvm_hll_state_blob(blob)
        np.testing.assert_array_equal(
            np.asarray(state.registers), np.asarray(back.registers)
        )
        assert back.metric_value() == state.metric_value()

    def test_blob_state_merges_into_engine_run(self):
        """The interop state is LIVE: it merges with engine-computed
        states through the ordinary aggregate machinery, like a JVM
        day-partition handed to this engine."""
        from deequ_tpu.analyzers.base import merge_states_batched

        a = _engine_state(rows=2000, distinct=300)
        b = read_jvm_hll_state_blob(
            write_jvm_hll_state_blob(_engine_state(rows=2000, distinct=500))
        )
        merged = merge_states_batched(ApproxCountDistinct("c"), [a, b])
        # max-merge of registers: the merged estimate covers the union and
        # equals merging the two native states directly
        native = merge_states_batched(ApproxCountDistinct("c"), [a, b])
        np.testing.assert_array_equal(
            np.asarray(merged.registers), np.asarray(native.registers)
        )
        assert merged.metric_value() >= b.metric_value()


class TestMalformedBlobs:
    def test_short_blob_typed(self):
        with pytest.raises(CorruptStateError):
            read_jvm_hll_state_blob(b"\x00\x00")

    def test_wrong_word_count_typed(self):
        blob = struct.pack(">i", 13) + b"\x00" * 8 * 13
        with pytest.raises(CorruptStateError, match="word count"):
            read_jvm_hll_state_blob(blob)

    def test_truncated_words_typed(self):
        good = write_jvm_hll_state_blob(_engine_state(rows=100, distinct=10))
        with pytest.raises(CorruptStateError):
            read_jvm_hll_state_blob(good[:-8])

    def test_wrong_register_shape_rejected_on_write(self):
        with pytest.raises(ValueError, match="registers"):
            write_jvm_hll_state_blob(
                type("S", (), {"registers": np.zeros(7, dtype=np.int32)})()
            )
