"""JVM interop (VERDICT r5 ask #5, first leg): the reference's HLL
word-array state blob (`StateProvider.scala:187-311` persistLongArrayState
layout — big-endian int32 word count + big-endian int64 words) reads into
a live ApproxCountDistinctState; ``words_to_registers`` finally has a
production consumer. Fixture-blob round trips are bit-exact and the
cardinality estimate is identical on both sides."""

import struct

import numpy as np
import pytest

from deequ_tpu.analyzers import ApproxCountDistinct
from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import CorruptStateError
from deequ_tpu.interop import (
    JVM_HLL_BLOB_BYTES,
    read_jvm_hll_state_blob,
    write_jvm_hll_state_blob,
)
from deequ_tpu.ops.hll import M, NUM_WORDS, registers_to_words
from deequ_tpu.runners.analysis_runner import AnalysisRunner


def _engine_state(rows=5000, distinct=700):
    data = Dataset.from_dict({"c": [f"v{i % distinct}" for i in range(rows)]})
    provider = InMemoryStateProvider()
    AnalysisRunner.do_analysis_run(
        data, [ApproxCountDistinct("c")], save_states_with=provider
    )
    return provider.load(ApproxCountDistinct("c"))


class TestBlobLayout:
    def test_fixture_blob_layout_pinned(self):
        """A hand-built blob in the reference layout: register i holds
        value (i % 61). 6-bit registers, 10 per word, little-endian within
        the word; the FILE layout is big-endian JVM DataOutputStream."""
        registers = np.array([i % 61 for i in range(M)], dtype=np.int32)
        words = registers_to_words(registers)
        blob = struct.pack(">i", NUM_WORDS) + words.view(np.int64).astype(
            ">i8"
        ).tobytes()
        assert len(blob) == JVM_HLL_BLOB_BYTES
        state = read_jvm_hll_state_blob(blob)
        np.testing.assert_array_equal(np.asarray(state.registers), registers)

    def test_word_zero_bit_layout_pinned(self):
        """Registers [1, 2, 3, 0, ...] pack into word0 as 1 | 2<<6 | 3<<12
        (the StatefulHyperloglogPlus 6-bit stride); pin the exact long so
        the byte layout can never silently flip endianness or stride."""
        registers = np.zeros(M, dtype=np.int32)
        registers[0], registers[1], registers[2] = 1, 2, 3
        blob = write_jvm_hll_state_blob(
            type("S", (), {"registers": registers})()
        )
        (count,) = struct.unpack_from(">i", blob, 0)
        (word0,) = struct.unpack_from(">q", blob, 4)
        assert count == NUM_WORDS
        assert word0 == (1 | (2 << 6) | (3 << 12))


class TestRoundTrip:
    def test_engine_state_round_trips_bit_exact(self):
        state = _engine_state()
        blob = write_jvm_hll_state_blob(state)
        assert len(blob) == JVM_HLL_BLOB_BYTES
        back = read_jvm_hll_state_blob(blob)
        np.testing.assert_array_equal(
            np.asarray(state.registers), np.asarray(back.registers)
        )
        assert back.metric_value() == state.metric_value()

    def test_blob_state_merges_into_engine_run(self):
        """The interop state is LIVE: it merges with engine-computed
        states through the ordinary aggregate machinery, like a JVM
        day-partition handed to this engine."""
        from deequ_tpu.analyzers.base import merge_states_batched

        a = _engine_state(rows=2000, distinct=300)
        b = read_jvm_hll_state_blob(
            write_jvm_hll_state_blob(_engine_state(rows=2000, distinct=500))
        )
        merged = merge_states_batched(ApproxCountDistinct("c"), [a, b])
        # max-merge of registers: the merged estimate covers the union and
        # equals merging the two native states directly
        native = merge_states_batched(ApproxCountDistinct("c"), [a, b])
        np.testing.assert_array_equal(
            np.asarray(merged.registers), np.asarray(native.registers)
        )
        assert merged.metric_value() >= b.metric_value()


class TestMalformedBlobs:
    def test_short_blob_typed(self):
        with pytest.raises(CorruptStateError):
            read_jvm_hll_state_blob(b"\x00\x00")

    def test_wrong_word_count_typed(self):
        blob = struct.pack(">i", 13) + b"\x00" * 8 * 13
        with pytest.raises(CorruptStateError, match="word count"):
            read_jvm_hll_state_blob(blob)

    def test_truncated_words_typed(self):
        good = write_jvm_hll_state_blob(_engine_state(rows=100, distinct=10))
        with pytest.raises(CorruptStateError):
            read_jvm_hll_state_blob(good[:-8])

    def test_wrong_register_shape_rejected_on_write(self):
        with pytest.raises(ValueError, match="registers"):
            write_jvm_hll_state_blob(
                type("S", (), {"registers": np.zeros(7, dtype=np.int32)})()
            )


# ---------------------------------------------------------------------------
# Second leg (ISSUE 7): the KLL sketch codec (KLLSketchSerializer.scala
# layout + KLLState's global min/max trailer)
# ---------------------------------------------------------------------------


def _kll_state(rows=20_000, sketch_size=64, seed=5):
    import jax.numpy as jnp

    from deequ_tpu.ops.kll import kll_init, kll_update

    rng = np.random.default_rng(seed)
    state = kll_init(sketch_size)
    for _ in range(5):
        values = rng.normal(0, 10, rows // 5)
        state = kll_update(
            state, jnp.asarray(values), jnp.ones(len(values), dtype=bool)
        )
    return state


class TestKLLBlob:
    def test_round_trip_preserves_sketch_contents(self):
        from deequ_tpu.interop import (
            read_jvm_kll_state_blob,
            write_jvm_kll_state_blob,
        )

        state = _kll_state()
        blob = write_jvm_kll_state_blob(state, shrinking_factor=0.64)
        back, shrinking = read_jvm_kll_state_blob(blob)
        assert shrinking == 0.64
        assert back.sketch_size == state.sketch_size
        assert int(back.count) == int(state.count)
        assert float(back.g_min) == float(state.g_min)
        assert float(back.g_max) == float(state.g_max)
        assert np.array_equal(np.asarray(back.sizes), np.asarray(state.sizes))
        assert np.array_equal(
            np.asarray(back.parity), np.asarray(state.parity)
        )
        sizes = np.asarray(state.sizes)
        for level in range(len(sizes)):
            n = int(sizes[level])
            assert np.array_equal(
                np.asarray(back.items)[level, :n],
                np.asarray(state.items)[level, :n],
            ), level

    def test_round_trip_quantiles_identical(self):
        from deequ_tpu.interop import (
            read_jvm_kll_state_blob,
            write_jvm_kll_state_blob,
        )
        from deequ_tpu.ops.kll_host import HostKLL

        state = _kll_state()
        back, _ = read_jvm_kll_state_blob(write_jvm_kll_state_blob(state))
        a, b = HostKLL.from_state(state), HostKLL.from_state(back)
        for q in (0.0, 0.1, 0.5, 0.9, 1.0):
            assert a.quantile(q) == b.quantile(q), q

    def test_header_layout_pinned(self):
        """int32 sketchSize, float64 shrinkingFactor, int64 count, int32
        compactor count — big-endian DataOutputStream conventions."""
        from deequ_tpu.interop import write_jvm_kll_state_blob
        from deequ_tpu.ops.kll import kll_init

        blob = write_jvm_kll_state_blob(kll_init(128), shrinking_factor=0.5)
        sketch_size, shrink, count, n_comp = struct.unpack_from(">idqi", blob, 0)
        assert (sketch_size, shrink, count, n_comp) == (128, 0.5, 0, 0)
        # empty sketch: header + max/min trailer only
        assert len(blob) == struct.calcsize(">idqi") + 16

    def test_malformed_blobs_typed(self):
        from deequ_tpu.interop import (
            read_jvm_kll_state_blob,
            write_jvm_kll_state_blob,
        )

        blob = write_jvm_kll_state_blob(_kll_state())
        for bad in (b"", blob[:8], blob[:-3], blob + b"\x00"):
            with pytest.raises(CorruptStateError):
                read_jvm_kll_state_blob(bad)
        # implausible header fields are structural violations too
        bad_sketch = struct.pack(">idqi", -5, 0.64, 0, 0) + b"\x00" * 16
        with pytest.raises(CorruptStateError):
            read_jvm_kll_state_blob(bad_sketch)
        bad_shrink = struct.pack(">idqi", 64, 7.5, 0, 0) + b"\x00" * 16
        with pytest.raises(CorruptStateError):
            read_jvm_kll_state_blob(bad_shrink)


# ---------------------------------------------------------------------------
# Third leg (ISSUE 7): the Gson metrics-history JSON dialect
# (AnalysisResultSerde.scala)
# ---------------------------------------------------------------------------


class TestGsonMetricsHistory:
    def _history(self):
        from deequ_tpu.analyzers import Mean, Size, Uniqueness
        from deequ_tpu.repository import AnalysisResult, ResultKey

        data = Dataset.from_dict(
            {
                "x": np.arange(200, dtype=np.float64),
                "y": (np.arange(200) % 9).astype(np.float64),
            }
        )
        ctx = AnalysisRunner.do_analysis_run(
            data, [Size(), Mean("x"), Uniqueness(("x", "y"))]
        )
        return [
            AnalysisResult(ResultKey(1111, {"env": "prod"}), ctx),
            AnalysisResult(ResultKey(2222, {"env": "dev"}), ctx),
        ]

    def test_round_trip(self):
        from deequ_tpu.analyzers import Mean, Size, Uniqueness
        from deequ_tpu.interop import (
            read_jvm_metrics_history_json,
            write_jvm_metrics_history_json,
        )

        history = self._history()
        payload = write_jvm_metrics_history_json(history)
        back = read_jvm_metrics_history_json(payload)
        assert [r.result_key.data_set_date for r in back] == [1111, 2222]
        assert back[0].result_key.tags_dict == {"env": "prod"}
        want = history[0].analyzer_context
        got = back[0].analyzer_context
        for a in (Size(), Mean("x"), Uniqueness(("x", "y"))):
            assert got.metric(a).value.get() == want.metric(a).value.get(), a

    def test_jvm_dialect_shape(self):
        """No formatVersion/checksum envelope, successful metrics only,
        and the reference's literal 'Mutlicolumn' entity spelling."""
        import json

        from deequ_tpu.interop import write_jvm_metrics_history_json

        payload = write_jvm_metrics_history_json(self._history())
        assert "formatVersion" not in payload
        assert "checksum" not in payload
        assert "Mutlicolumn" in payload  # the reference's famous typo
        records = json.loads(payload)
        assert isinstance(records, list) and len(records) == 2
        assert set(records[0]) == {"resultKey", "analyzerContext"}

    def test_failure_metrics_skipped_on_write(self):
        from deequ_tpu.analyzers import Completeness, Size
        from deequ_tpu.interop import (
            read_jvm_metrics_history_json,
            write_jvm_metrics_history_json,
        )
        from deequ_tpu.repository import AnalysisResult, ResultKey

        data = Dataset.from_dict({"x": np.arange(10, dtype=np.float64)})
        # Completeness over a MISSING column precondition-fails -> Failure
        ctx = AnalysisRunner.do_analysis_run(
            data, [Size(), Completeness("nope")]
        )
        assert ctx.metric(Completeness("nope")).value.is_failure
        payload = write_jvm_metrics_history_json(
            [AnalysisResult(ResultKey(1), ctx)]
        )
        back = read_jvm_metrics_history_json(payload)
        metric_map = back[0].analyzer_context.metric_map
        assert len(metric_map) == 1  # only the successful Size survived

    def test_reference_written_payload_loads(self):
        """A hand-written JVM-side payload (the dialect a Gson
        AnalysisResultSerde emits) loads without our envelope fields."""
        from deequ_tpu.interop import read_jvm_metrics_history_json

        payload = (
            '[{"resultKey": {"dataSetDate": 1630000000000, '
            '"tags": {"table": "orders"}}, '
            '"analyzerContext": {"metricMap": ['
            '{"analyzer": {"analyzerName": "Size", "where": null}, '
            '"metric": {"entity": "Dataset", "instance": "*", '
            '"name": "Size", "metricName": "DoubleMetric", "value": 42.0}}, '
            '{"analyzer": {"analyzerName": "Uniqueness", '
            '"columns": ["a", "b"]}, '
            '"metric": {"entity": "Mutlicolumn", "instance": "a,b", '
            '"name": "Uniqueness", "metricName": "DoubleMetric", '
            '"value": 0.25}}]}}]'
        )
        results = read_jvm_metrics_history_json(payload)
        assert results[0].result_key.data_set_date == 1630000000000
        values = {
            type(a).__name__: m.value.get()
            for a, m in results[0].analyzer_context.metric_map.items()
        }
        assert values == {"Size": 42.0, "Uniqueness": 0.25}

    def test_corrupt_payloads_typed(self):
        from deequ_tpu.interop import read_jvm_metrics_history_json

        for bad in (
            "{not json",
            '{"a": 1}',
            '[{"resultKey": {}}]',
            '[{"resultKey": {"dataSetDate": 1}, "analyzerContext": '
            '{"metricMap": [{"analyzer": {"analyzerName": "NoSuch"}, '
            '"metric": {}}]}}]',
        ):
            with pytest.raises(CorruptStateError):
                read_jvm_metrics_history_json(bad)
