"""Constraint-factory matrix, ported from the reference's
`ConstraintsTest.scala`: every factory evaluated directly against the
canned fixtures with the reference's expected values/statuses."""

import math

import pytest

from deequ_tpu import constraints as C
from deequ_tpu.constraints import (
    ASSERTION_EXCEPTION,
    ConstraintDecorator,
    ConstraintStatus,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner


def calculate(constraint, data):
    """Reference `ConstraintUtils.calculate`: run just the constraint's
    analyzer, then evaluate the constraint against the metric map."""
    inner = (
        constraint.inner if isinstance(constraint, ConstraintDecorator) else constraint
    )
    ctx = AnalysisRunner.do_analysis_run(data, [inner.analyzer])
    return constraint.evaluate(ctx.metric_map)


@pytest.fixture
def df_conditionally_uninformative():
    """(reference `FixtureSupport.getDfWithConditionallyUninformativeColumns`)."""
    return Dataset.from_dict({"att1": [1, 2, 3], "att2": [0, 0, 0]})


class TestCompletenessConstraint:
    def test_assert_on_wrong_completeness(self, df_missing):
        # att1 is half present, att2 three quarters (reference `:32-43`)
        assert calculate(
            C.completeness_constraint("att1", lambda v: v == 0.5), df_missing
        ).status == ConstraintStatus.SUCCESS
        assert calculate(
            C.completeness_constraint("att1", lambda v: v != 0.5), df_missing
        ).status == ConstraintStatus.FAILURE
        assert calculate(
            C.completeness_constraint("att2", lambda v: v == 0.75), df_missing
        ).status == ConstraintStatus.SUCCESS
        assert calculate(
            C.completeness_constraint("att2", lambda v: v != 0.75), df_missing
        ).status == ConstraintStatus.FAILURE


class TestHistogramConstraints:
    def test_assert_on_bin_number(self, df_missing):
        # att1 holds a, b and NullValue: 3 bins (reference `:46-52`)
        assert calculate(
            C.histogram_bin_constraint("att1", lambda v: v == 3), df_missing
        ).status == ConstraintStatus.SUCCESS
        assert calculate(
            C.histogram_bin_constraint("att1", lambda v: v != 3), df_missing
        ).status == ConstraintStatus.FAILURE

    def test_missing_column_value_in_picker_is_assertion_failure(self, df_missing):
        # the value picker indexes a bin that does not exist: structured
        # assertion-exception message, not a crash (reference `:53-66`)
        result = calculate(
            C.histogram_constraint(
                "att1", lambda dist: dist["non-existent-column-value"].ratio == 3
            ),
            df_missing,
        )
        assert result.status == ConstraintStatus.FAILURE
        assert result.message is not None
        assert ASSERTION_EXCEPTION in result.message


class TestMutualInformationConstraint:
    def test_conditionally_uninformative_columns_have_zero_mi(
        self, df_conditionally_uninformative
    ):
        # att2 is constant: knowing att1 adds nothing (reference `:69-75`)
        assert calculate(
            C.mutual_information_constraint("att1", "att2", lambda v: v == 0),
            df_conditionally_uninformative,
        ).status == ConstraintStatus.SUCCESS


class TestBasicStatsConstraints:
    def test_approx_quantile(self, df_numeric):
        assert calculate(
            C.approx_quantile_constraint("att1", 0.5, lambda v: v == 3.0), df_numeric
        ).status == ConstraintStatus.SUCCESS

    def test_minimum(self, df_numeric):
        assert calculate(
            C.min_constraint("att1", lambda v: v == 1.0), df_numeric
        ).status == ConstraintStatus.SUCCESS

    def test_maximum(self, df_numeric):
        assert calculate(
            C.max_constraint("att1", lambda v: v == 6.0), df_numeric
        ).status == ConstraintStatus.SUCCESS

    def test_mean(self, df_numeric):
        assert calculate(
            C.mean_constraint("att1", lambda v: v == 3.5), df_numeric
        ).status == ConstraintStatus.SUCCESS

    def test_sum(self, df_numeric):
        assert calculate(
            C.sum_constraint("att1", lambda v: v == 21.0), df_numeric
        ).status == ConstraintStatus.SUCCESS

    def test_standard_deviation(self, df_numeric):
        # population stddev of 1..6
        want = math.sqrt(sum((x - 3.5) ** 2 for x in range(1, 7)) / 6)
        assert calculate(
            C.standard_deviation_constraint(
                "att1", lambda v: v == pytest.approx(want, rel=1e-12)
            ),
            df_numeric,
        ).status == ConstraintStatus.SUCCESS

    def test_approx_count_distinct(self, df_numeric):
        assert calculate(
            C.approx_count_distinct_constraint("att1", lambda v: v == 6.0), df_numeric
        ).status == ConstraintStatus.SUCCESS

    def test_correlation_of_distinct_columns(self, df_numeric):
        # numpy oracle: corr(att2=[0,0,0,5,6,7], att3=[0,0,0,4,6,7])
        want = 0.992763360363403
        assert calculate(
            C.correlation_constraint(
                "att2", "att3", lambda v: v == pytest.approx(want, rel=1e-12)
            ),
            df_numeric,
        ).status == ConstraintStatus.SUCCESS


class TestUniquenessConstraints:
    def test_uniqueness_of_unique_column(self, df_full):
        assert calculate(
            C.uniqueness_constraint(["item"], lambda v: v == 1.0), df_full
        ).status == ConstraintStatus.SUCCESS

    def test_uniqueness_of_repeating_column(self, df_full):
        # att1 = [a, b, a, a]: only b is unique -> 1/4
        assert calculate(
            C.uniqueness_constraint(["att1"], lambda v: v == 0.25), df_full
        ).status == ConstraintStatus.SUCCESS

    def test_distinctness(self, df_full):
        # att1 has 2 distinct groups over 4 rows
        assert calculate(
            C.distinctness_constraint(["att1"], lambda v: v == 0.5), df_full
        ).status == ConstraintStatus.SUCCESS


class TestComplianceAndPattern:
    def test_compliance(self, df_numeric):
        assert calculate(
            C.compliance_constraint("att1 > 2", "att1 > 2", lambda v: v == pytest.approx(4 / 6)),
            df_numeric,
        ).status == ConstraintStatus.SUCCESS

    def test_pattern_match(self, df_full):
        assert calculate(
            C.pattern_match_constraint("att1", r"^[a-z]$", lambda v: v == 1.0), df_full
        ).status == ConstraintStatus.SUCCESS

    def test_data_type_ratio(self):
        from deequ_tpu.constraints import ConstrainableDataTypes

        data = Dataset.from_dict({"v": ["1", "2.0", "x", "true"]})
        assert calculate(
            C.data_type_constraint(
                "v", ConstrainableDataTypes.NUMERIC, lambda v: v == 0.5
            ),
            data,
        ).status == ConstraintStatus.SUCCESS


class TestSizeConstraint:
    def test_size(self, df_full):
        assert calculate(
            C.size_constraint(lambda v: v == 4), df_full
        ).status == ConstraintStatus.SUCCESS
        assert calculate(
            C.size_constraint(lambda v: v > 4), df_full
        ).status == ConstraintStatus.FAILURE
