"""Fleet scheduler tests (ISSUE 12): default-on mesh sharding, disjoint
sub-mesh packing, mesh-shape-qualified warmth keys, elastic re-packing.

The conftest provisions 8 virtual CPU devices, so every packing shape the
fleet cuts (8 / 4+4 / 2-device slices) is executable here. The fleet is
OFF by default on the CPU backend (virtual devices share host cores), so
each test opts in explicitly with ``VerificationService(fleet=True)`` or
``DEEQU_TPU_FLEET=1`` — the same override an operator uses for drills.

Bit-exactness discipline: the parity batteries use INTEGER-VALUED columns
whose sums are exact in float64, so metrics are bit-identical regardless
of how many shards the fold was split across (merge re-association of
exact sums cannot round). That is what lets "alone on the full 8-device
mesh" compare ``==`` against "packed onto a 4-device sub-mesh".
"""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.data import Dataset

pytestmark = pytest.mark.fleet


def _exact_checks():
    """A battery whose merges are exact at any shard split (counts,
    min/max, integer-valued sums)."""
    return [
        Check(CheckLevel.ERROR, "fleet parity")
        .has_size(lambda n: n > 0)
        .is_complete("x")
        .has_min("x", lambda v: v >= 0)
        .has_max("x", lambda v: v < 1000)
        .has_sum("x", lambda s: s > 0),
    ]


def _exact_data(rows: int = 100_000, seed: int = 0) -> Dataset:
    rng = np.random.default_rng(seed)
    return Dataset.from_dict(
        {"x": rng.integers(0, 1000, rows).astype(np.float64)}
    )


def _values(result):
    return {
        repr(a): m.value.get()
        for a, m in result.metrics.items()
        if m.value.is_success
    }


class TestPacking:
    def _fleet(self, n=8):
        from deequ_tpu.service.fleet import FleetScheduler

        class _Dev:
            def __init__(self, i):
                self.id = i
                self.device_kind = "fake"

        return FleetScheduler(devices=[_Dev(i) for i in range(n)])

    def test_slice_sizes(self):
        from deequ_tpu.service.fleet import FleetScheduler

        size = FleetScheduler._slice_size
        assert size(8, 1) == 8
        assert size(8, 2) == 4
        assert size(8, 3) == 2
        assert size(8, 4) == 2
        assert size(8, 5) == 1
        assert size(7, 2) == 2  # post-loss: largest pow2 <= 3
        assert size(1, 1) == 1
        assert size(0, 1) == 0

    def test_two_tenants_disjoint_halves(self):
        fleet = self._fleet()
        try:
            fleet.acquire("a")
            fleet.acquire("b")
            a, b = fleet.devices_of("a"), fleet.devices_of("b")
            assert len(a) == len(b) == 4
            assert not set(a) & set(b)
        finally:
            fleet.close()

    def test_four_tenants_disjoint_pairs(self):
        fleet = self._fleet()
        try:
            for t in "abcd":
                fleet.acquire(t)
            slices = [set(fleet.devices_of(t)) for t in "abcd"]
            assert all(len(s) == 2 for s in slices)
            for i in range(4):
                for j in range(i + 1, 4):
                    assert not slices[i] & slices[j]
        finally:
            fleet.close()

    def test_membership_is_sticky_across_release(self):
        """Releasing the last lease must NOT re-pack (mesh shapes would
        oscillate per drain); the slice stays assigned until eviction."""
        fleet = self._fleet()
        try:
            fleet.acquire("a")
            gen = fleet.snapshot()["generation"]
            fleet.release("a")
            assert fleet.snapshot()["generation"] == gen
            assert fleet.devices_of("a")  # still assigned
            assert fleet.evict_idle() == 1
            assert not fleet.devices_of("a")
        finally:
            fleet.close()

    def test_repacks_counter_matches_snapshot(self):
        """Every re-pack (membership growth AND loss) reaches the export
        plane — the counter and snapshot()['repacks'] never diverge."""
        fleet = self._fleet()
        try:
            fleet.acquire("a")
            fleet.acquire("b")
            fleet.mark_unhealthy([7])
            snap = fleet.snapshot()
            counted = fleet.metrics.counter_value(
                "deequ_service_fleet_repacks_total"
            )
            assert counted == float(snap["repacks"]) == 3.0
        finally:
            fleet.close()

    def test_idle_tenants_reclaimed_at_next_repack(self):
        """A departed tenant must not shrink live tenants' slices
        forever: the next natural re-pack prunes members past the idle
        TTL. A tenant merely BETWEEN folds (zero refs, recent activity)
        must survive the same re-pack — sequential multi-tenant
        workloads depend on that stickiness."""
        import time

        fleet = self._fleet()
        try:
            for t in "abcd":
                fleet.acquire(t)
            for t in "abcd":
                fleet.release(t)
            # a, b, c departed LONG ago; d is just between folds
            for t in "abc":
                fleet._last_seen[t] = (
                    time.monotonic() - fleet.IDLE_TTL_S - 1
                )
            assert len(fleet.devices_of("d")) == 2  # old packing holds
            fleet.acquire("e")  # arrival re-packs; TTL-idle members drop
            snap = fleet.snapshot()
            assert set(snap["tenants"]) == {"d", "e"}
            assert len(fleet.devices_of("d")) == 4
            assert len(fleet.devices_of("e")) == 4
        finally:
            fleet.close()

    def test_loss_repacks_over_survivors(self):
        fleet = self._fleet()
        try:
            fleet.acquire("a")
            fleet.acquire("b")
            fleet.mark_unhealthy([5])
            snap = fleet.snapshot()
            assert 5 not in snap["healthy"]
            for positions in snap["assignment"].values():
                assert 5 not in positions
            a, b = fleet.devices_of("a"), fleet.devices_of("b")
            assert a and b and not set(a) & set(b)
        finally:
            fleet.close()

    def test_peek_predicts_the_slice_acquire_grants(self):
        """The submit-time warmth key / warm closure compile for the
        slice the pickup-time lease will ACTUALLY grant — peeking the
        first free slice instead would warm the wrong device tuple for
        every non-first tenant."""
        fleet = self._fleet()
        try:
            fleet.acquire("a")
            for t in ("b", "c", "d"):
                predicted = fleet.peek(t)
                granted = fleet.acquire(t)
                assert predicted.positions == granted.positions, (
                    t, predicted.positions, granted.positions,
                )
        finally:
            fleet.close()

    def test_more_tenants_than_devices_wrap(self):
        fleet = self._fleet(n=2)
        try:
            for i in range(5):
                fleet.acquire(f"t{i}")
            # every tenant still gets a (single-chip) slice
            assert all(fleet.devices_of(f"t{i}") for i in range(5))
        finally:
            fleet.close()


class TestMeshQualifiedWarmth:
    """The cache white-box satellite: a 4-device sub-mesh must MISS on an
    8-device-warm battery."""

    def test_signature_carries_mesh_shape(self):
        from deequ_tpu.analyzers import Completeness, Size
        from deequ_tpu.service import shape_qualified_signature

        battery = [Size(), Completeness("x")]
        plain = shape_qualified_signature(battery, 4096)
        at8 = shape_qualified_signature(battery, 4096, 8)
        at4 = shape_qualified_signature(battery, 4096, 4)
        assert plain != at8 != at4
        assert ("__mesh__", 8) in at8
        assert ("__mesh__", 4) in at4
        # single chip keeps the EXACT pre-fleet key (the escape hatch's
        # byte-for-byte promise)
        assert shape_qualified_signature(battery, 4096, 1) == plain
        assert shape_qualified_signature(battery, 4096, None) == plain

    def test_submesh_misses_on_full_mesh_warmth(self):
        from deequ_tpu.analyzers import Completeness, Size
        from deequ_tpu.service import (
            PlacementRouter,
            shape_qualified_signature,
        )

        battery = [Size(), Completeness("x")]
        router = PlacementRouter(background_warm=False)
        try:
            sig8 = shape_qualified_signature(battery, 4096, 8)
            sig4 = shape_qualified_signature(battery, 4096, 4)
            router.note_ran(sig8, worker_id=0, placement="device")
            assert router.is_warm(sig8)
            # the 4-device sub-mesh reads COLD: its pjit program has a
            # different collective layout than the 8-device one
            assert not router.is_warm(sig4)
            assert router.decide(sig4) == "host"
        finally:
            router.close()

    def test_lease_qualifies_like_its_device_count(self):
        from deequ_tpu.analyzers import Size
        from deequ_tpu.service import shape_qualified_signature
        from deequ_tpu.service.fleet import FleetScheduler

        fleet = FleetScheduler(devices=list(range(8)))
        try:
            lease = fleet.acquire("a")
            sig = shape_qualified_signature([Size()], 1024, lease)
            assert ("__mesh__", lease.n_dev) in sig
        finally:
            fleet.close()


class TestSubMeshIsolationParity:
    """Two tenants on disjoint sub-meshes produce bit-exact metrics vs
    each running ALONE on the full mesh (the sub-mesh isolation parity
    satellite)."""

    def test_batch_jobs_bit_exact(self):
        from deequ_tpu.service import VerificationService

        checks = _exact_checks()
        data_a = _exact_data(seed=1)
        data_b = _exact_data(seed=2)

        def run_alone(data):
            with VerificationService(
                workers=2, background_warm=False, fleet=True
            ) as svc:
                lease = svc.fleet.peek("solo")
                assert lease.n_dev == 8  # alone -> the full mesh
                return _values(
                    svc.verify(data, checks, tenant="solo", timeout=120)
                )

        alone_a = run_alone(data_a)
        alone_b = run_alone(data_b)

        with VerificationService(
            workers=4, background_warm=False, fleet=True
        ) as svc:
            ha = svc.submit_verification(data_a, checks, tenant="a")
            hb = svc.submit_verification(data_b, checks, tenant="b")
            ra, rb = ha.result(120), hb.result(120)
            pos_a = svc.fleet.devices_of("a")
            pos_b = svc.fleet.devices_of("b")
        assert len(pos_a) == len(pos_b) == 4
        assert not set(pos_a) & set(pos_b)
        assert _values(ra) == alone_a
        assert _values(rb) == alone_b

    def test_single_chip_escape_hatch_bit_exact(self, monkeypatch):
        """DEEQU_TPU_FLEET=0 restores single-chip routing; metrics equal
        the fleet-sharded run bit-for-bit on the exact battery."""
        from deequ_tpu.service import VerificationService

        checks = _exact_checks()
        data = _exact_data(seed=3)
        with VerificationService(
            workers=2, background_warm=False, fleet=True
        ) as svc:
            sharded = _values(
                svc.verify(data, checks, tenant="a", timeout=120)
            )
        monkeypatch.setenv("DEEQU_TPU_FLEET", "0")
        with VerificationService(workers=2, background_warm=False) as svc:
            assert svc.fleet is None
            single = _values(
                svc.verify(data, checks, tenant="a", timeout=120)
            )
        assert sharded == single


class TestFleetStreaming:
    """Streaming folds shard-local + butterfly-merge at drain boundaries
    when the fleet grants a multi-device slice."""

    @pytest.fixture(autouse=True)
    def _force_stream_mesh(self, monkeypatch):
        # shard every eligible fold (no 64k floor); the mesh floor
        # outranks the crossover's fast route by contract, so no
        # DEEQU_TPU_FAST_PATH_MAX_ROWS override is needed — these tests
        # pin exactly that
        monkeypatch.setenv("DEEQU_TPU_FLEET_STREAM_MIN_ROWS", "0")

    def _table(self, seed: int, rows: int = 8192):
        import pyarrow as pa

        r = np.random.default_rng(seed)
        return pa.table(
            {"x": r.integers(0, 1000, rows).astype(np.float64)}
        )

    def test_mesh_stream_folds_bit_exact_vs_single_chip(self, monkeypatch):
        from deequ_tpu.service import VerificationService

        def run(fleet: bool):
            with VerificationService(
                workers=2, background_warm=False, fleet=fleet
            ) as svc:
                session = svc.session("t-a", "stream", _exact_checks())
                for b in range(3):
                    session.ingest(self._table(b))
                folds = svc.metrics.counter_value(
                    "deequ_service_fleet_stream_folds_total"
                )
                return _values(session.current()), folds

        fleet_metrics, fleet_folds = run(fleet=True)
        single_metrics, single_folds = run(fleet=False)
        assert fleet_folds == 3.0  # every fold rode the sub-mesh
        assert not single_folds
        assert fleet_metrics == single_metrics

    def test_shard_loss_mid_stream_recovers_and_repacks(self):
        from deequ_tpu.reliability import FaultSpec, inject
        from deequ_tpu.service import VerificationService

        with VerificationService(
            workers=2, background_warm=False, fleet=True
        ) as svc:
            session = svc.session("t-a", "stream", _exact_checks())
            session.ingest(self._table(0))
            with inject(
                FaultSpec("sharded_fold", "mesh_loss", at=1, shard=2)
            ) as inj:
                session.ingest(self._table(1))
            assert inj.fired  # the loss really hit this fold
            session.ingest(self._table(2))
            snap = svc.fleet.snapshot()
            cum = _values(session.current())
        assert session.batches_ingested == 3
        # the dead device left the packing; later folds avoid it
        assert len(snap["healthy"]) < 8
        with VerificationService(
            workers=2, background_warm=False, fleet=False
        ) as svc:
            ref = svc.session("t-a", "stream", _exact_checks())
            for b in range(3):
                ref.ingest(self._table(b))
            assert cum == _values(ref.current())


class TestFleetDefaults:
    def test_cpu_backend_defaults_off(self, monkeypatch):
        from deequ_tpu.service.fleet import fleet_enabled

        monkeypatch.delenv("DEEQU_TPU_FLEET", raising=False)
        # conftest runs on the CPU backend: the virtual 8-device mesh
        # shares host cores, so the fleet must not default on
        assert not fleet_enabled()
        monkeypatch.setenv("DEEQU_TPU_FLEET", "1")
        assert fleet_enabled()
        monkeypatch.setenv("DEEQU_TPU_FLEET", "0")
        assert not fleet_enabled()

    def test_explicit_mesh_disables_fleet(self):
        from deequ_tpu.parallel import make_mesh
        from deequ_tpu.service import VerificationService

        with VerificationService(
            workers=1, background_warm=False, mesh=make_mesh(2), fleet=True
        ) as svc:
            assert svc.fleet is None  # legacy one-global-mesh mode wins

    def test_mesh_substrate_names_the_fallback(self):
        from deequ_tpu.service import mesh_substrate

        sub = mesh_substrate()
        assert sub["substrate"] == "cpu-virtual"
        assert sub["chip_count"] == 8
        assert sub["backend"] == "cpu"
