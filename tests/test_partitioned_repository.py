"""Time-partitioned metrics repository (ISSUE 15 tentpole) + the FS
windowed-load satellite: O(queried window) pins, compaction, replace-key,
quarantine, JVM-dialect import."""

import datetime
import json
import os

import numpy as np
import pytest

from deequ_tpu.analyzers import Completeness, Mean, Size
from deequ_tpu.data import Dataset
from deequ_tpu.repository import (
    AnalysisResult,
    FileSystemMetricsRepository,
    PartitionedMetricsRepository,
    ResultKey,
    month_bucket,
)
from deequ_tpu.runners import AnalysisRunner

DAY_MS = 86_400_000
BASE_MS = 1_735_689_600_000  # 2025-01-01T00:00Z


@pytest.fixture(scope="module")
def ctx():
    data = Dataset.from_dict(
        {"x": np.random.default_rng(0).normal(10, 2, 64)}
    )
    return AnalysisRunner.do_analysis_run(
        data, [Size(), Completeness("x"), Mean("x")]
    )


def populate(repo, days, ctx, tags=None):
    for d in range(days):
        repo.save(ResultKey(BASE_MS + d * DAY_MS, tags or {}), ctx)


class TestLayout:
    def test_month_bucket(self):
        assert month_bucket(BASE_MS) == "2025-01"
        assert month_bucket(BASE_MS + 40 * DAY_MS) == "2025-02"
        assert month_bucket(0) == "1970-01"

    def test_entries_land_in_month_buckets(self, tmp_path, ctx):
        repo = PartitionedMetricsRepository(str(tmp_path / "hist"))
        populate(repo, 90, ctx)
        assert repo.buckets() == ["2025-01", "2025-02", "2025-03"]
        assert len(repo.load().get()) == 90

    def test_windowed_load_walks_only_intersecting_buckets(self, tmp_path, ctx):
        """THE O(queried window) pin: a one-month query over a year of
        dailies walks ONE bucket and deserializes exactly the window's
        entries — never the other 11 months'."""
        repo = PartitionedMetricsRepository(str(tmp_path / "hist"))
        populate(repo, 365, ctx)
        assert len(repo.buckets()) == 12  # 2025-01 .. 2025-12
        lo = BASE_MS + 150 * DAY_MS
        hi = BASE_MS + 170 * DAY_MS
        repo.entries_deserialized = 0
        repo.buckets_walked = 0
        got = repo.load().after(lo).before(hi).get()
        assert len(got) == 21
        assert repo.buckets_walked <= 2  # the window straddles <= 2 months
        assert repo.entries_deserialized <= 62  # walked buckets' entries,
        # never the year's 365 (in-bucket entries outside the bounds are
        # peeked and skipped, not deserialized)

    def test_save_is_append_not_full_rewrite(self, tmp_path, ctx):
        """A save touches its own month bucket only — the legacy layout's
        O(all history) rewrite is gone."""
        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=10_000
        )
        populate(repo, 60, ctx)
        jan = tmp_path / "hist" / "2025-01"
        before = sorted(os.listdir(jan))
        repo.save(ResultKey(BASE_MS + 45 * DAY_MS), ctx)  # lands in Feb
        assert sorted(os.listdir(jan)) == before


class TestCompaction:
    def test_bucket_compacts_past_threshold(self, tmp_path, ctx):
        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=8
        )
        populate(repo, 20, ctx)
        jan = tmp_path / "hist" / "2025-01"
        files = os.listdir(jan)
        loose = [f for f in files if f.startswith("e-")]
        assert "compacted.json" in files
        assert len(loose) < 8  # compaction keeps loose files bounded
        assert len(repo.load().get()) == 20  # nothing lost

    def test_explicit_compact_merges_and_dedups(self, tmp_path, ctx):
        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=10_000
        )
        populate(repo, 5, ctx)
        n = repo.compact("2025-01")
        assert n == 5
        jan = tmp_path / "hist" / "2025-01"
        assert [f for f in os.listdir(jan) if f.startswith("e-")] == []
        assert len(repo.load().get()) == 5

    def test_stale_loose_entry_never_wins_after_failed_removal(
        self, tmp_path, ctx, monkeypatch
    ):
        """Best-effort removal of a replaced entry FAILING must not let
        the stale entry serve beside — or, after compaction, instead of —
        its replacement: loose names sort by recency and reads merge
        last-wins per key."""
        from deequ_tpu import io as dio
        from deequ_tpu.data import Dataset
        from deequ_tpu.runners import AnalysisRunner

        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=10_000
        )
        key = ResultKey(BASE_MS, {"env": "prod"})
        repo.save(key, ctx)
        new_ctx = AnalysisRunner.do_analysis_run(
            Dataset.from_dict({"y": [1.0, 2.0]}), [Size()]
        )
        monkeypatch.setattr(
            dio, "remove_file",
            lambda path: (_ for _ in ()).throw(OSError("readonly")),
        )
        repo.save(key, new_ctx)  # removal of the old loose entry fails
        monkeypatch.undo()
        got = repo.load().get()
        assert len(got) == 1  # never a duplicate
        assert got[0].analyzer_context.metric_map[Size()].value.get() == 2.0
        repo.compact(month_bucket(BASE_MS))
        got = repo.load().get()
        assert len(got) == 1
        assert got[0].analyzer_context.metric_map[Size()].value.get() == 2.0

    def test_compaction_stamp_beats_stale_merged_loose_file(
        self, tmp_path, ctx, monkeypatch
    ):
        """A loose file compaction merged but failed to REMOVE predates
        the compaction stamp, so it can never shadow a newer compacted
        replacement of its key."""
        from deequ_tpu import io as dio
        from deequ_tpu.data import Dataset
        from deequ_tpu.runners import AnalysisRunner

        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=10_000
        )
        key = ResultKey(BASE_MS, {"env": "prod"})
        repo.save(key, ctx)  # v1 (Size == 64)
        monkeypatch.setattr(
            dio, "remove_file",
            lambda path: (_ for _ in ()).throw(OSError("readonly")),
        )
        repo.compact(month_bucket(BASE_MS))  # v1 merged; loose v1 remains
        monkeypatch.undo()
        v2 = AnalysisRunner.do_analysis_run(
            Dataset.from_dict({"y": [1.0, 2.0]}), [Size()]
        )
        monkeypatch.setattr(
            dio, "remove_file",
            lambda path: (_ for _ in ()).throw(OSError("readonly")),
        )
        repo.save(key, v2)  # prune of stale loose v1 fails too
        monkeypatch.undo()
        got = repo.load().get()
        assert len(got) == 1
        assert got[0].analyzer_context.metric_map[Size()].value.get() == 2.0
        repo.compact(month_bucket(BASE_MS))
        got = repo.load().get()
        assert len(got) == 1
        assert got[0].analyzer_context.metric_map[Size()].value.get() == 2.0

    def test_corrupt_loose_entry_self_heals(self, tmp_path, ctx):
        """A checksum-corrupt LOOSE entry quarantines ONCE (bytes in the
        sidecar, file dropped) — later reads serve clean instead of
        re-quarantining forever."""
        from deequ_tpu.repository.fs import quarantined_total

        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=10_000
        )
        populate(repo, 3, ctx)
        [entry] = sorted(
            f for f in os.listdir(tmp_path / "hist" / "2025-01")
            if f.startswith("e-")
        )[-1:]
        path = tmp_path / "hist" / "2025-01" / entry
        raw = path.read_text()
        i = raw.index("Mean") + 1
        path.write_text(raw[:i] + ("X" if raw[i] != "X" else "Y") + raw[i + 1:])
        before = quarantined_total()
        assert len(repo.load().get()) == 2
        assert quarantined_total() - before == 1
        assert not path.exists()  # healed
        assert len(repo.load().get()) == 2
        assert quarantined_total() - before == 1  # no re-quarantine

    def test_compaction_drops_corrupt_entries(self, tmp_path, ctx):
        """Compaction is where standing bit rot inside compacted.json
        self-heals: checksum-corrupt entries quarantine and DROP from the
        rewrite."""
        from deequ_tpu.repository.fs import quarantined_total

        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=10_000
        )
        populate(repo, 4, ctx)
        repo.compact("2025-01")
        target = tmp_path / "hist" / "2025-01" / "compacted.json"
        raw = target.read_text()
        i = raw.index("Mean") + 1
        target.write_text(raw[:i] + ("X" if raw[i] != "X" else "Y") + raw[i + 1:])
        before = quarantined_total()
        assert repo.compact("2025-01") == 3  # the rotten entry dropped
        assert quarantined_total() - before == 1
        # subsequent reads are clean — no per-read re-quarantine
        assert len(repo.load().get()) == 3
        assert quarantined_total() - before == 1

    def test_replace_key_across_loose_and_compacted(self, tmp_path, ctx):
        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=10_000
        )
        key = ResultKey(BASE_MS, {"env": "prod"})
        repo.save(key, ctx)
        repo.compact("2025-01")
        repo.save(key, ctx)  # replaces the compacted entry
        repo.save(key, ctx)  # replaces the loose entry
        assert len(repo.load().get()) == 1
        assert repo.load_by_key(key) is not None
        # distinct tags are distinct keys
        repo.save(ResultKey(BASE_MS, {"env": "test"}), ctx)
        assert len(repo.load().get()) == 2


class TestQuarantine:
    def test_flipped_byte_quarantines_one_entry(self, tmp_path, ctx):
        from deequ_tpu.repository.fs import quarantined_total

        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=4
        )
        populate(repo, 10, ctx)
        target = tmp_path / "hist" / "2025-01" / "compacted.json"
        raw = target.read_text()
        i = raw.index("Mean") + 1
        target.write_text(
            raw[:i] + ("X" if raw[i] != "X" else "Y") + raw[i + 1:]
        )
        before = quarantined_total()
        got = repo.load().get()
        assert len(got) == 9  # the flipped entry alone is gone
        assert quarantined_total() - before == 1
        side = tmp_path / "hist.quarantine"
        assert side.is_dir() and list(side.iterdir())

    def test_torn_bucket_serves_rest_and_compaction_refuses(self, tmp_path, ctx):
        from deequ_tpu.exceptions import CorruptStateError

        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=2
        )
        populate(repo, 40, ctx)  # jan + feb, both compacted
        (tmp_path / "hist" / "2025-01" / "compacted.json").write_text(
            '[{"torn"'
        )
        got = repo.load().get()
        # feb's 9 entries keep serving, plus january's one still-loose
        # entry — tearing the compacted file costs exactly its payload
        assert len(got) == 10
        # saves are APPEND-ONLY (one atomic loose write, the compacted
        # file untouched) so saving into a torn bucket is safe — it is
        # COMPACTION that refuses typed (its rewrite would erase whatever
        # the torn file still holds)
        with pytest.raises(CorruptStateError):
            repo.compact("2025-01")
        repo.save(ResultKey(BASE_MS + 10 * DAY_MS, {"k": "new"}), ctx)
        assert any(
            r.result_key.tags_dict.get("k") == "new"
            for r in repo.load().get()
        )

    def test_injected_corrupt_fault_takes_the_quarantine_path(self, tmp_path, ctx):
        from deequ_tpu.reliability import FaultSpec, inject
        from deequ_tpu.repository.fs import quarantined_total

        repo = PartitionedMetricsRepository(
            str(tmp_path / "hist"), compact_threshold=2
        )
        populate(repo, 6, ctx)
        before = quarantined_total()
        with inject(FaultSpec("repository_load", "corrupt", at=1)) as inj:
            got = repo.load().get()
        assert inj.fired
        assert quarantined_total() > before
        # that read's bucket payload quarantined; the next read recovers
        assert len(repo.load().get()) == 6
        assert len(got) < 6


class TestLoaderSemantics:
    def test_filters_match_reference_loader(self, tmp_path, ctx):
        repo = PartitionedMetricsRepository(str(tmp_path / "hist"))
        repo.save(ResultKey(BASE_MS, {"env": "prod"}), ctx)
        repo.save(ResultKey(BASE_MS + DAY_MS, {"env": "test"}), ctx)
        repo.save(ResultKey(BASE_MS + 2 * DAY_MS, {"env": "prod"}), ctx)
        assert len(repo.load().get()) == 3
        assert len(repo.load().with_tag_values({"env": "prod"}).get()) == 2
        assert len(repo.load().after(BASE_MS + DAY_MS).get()) == 2
        assert len(repo.load().before(BASE_MS + DAY_MS).get()) == 2
        only = repo.load().for_analyzers([Size()]).get()
        assert all(
            set(r.analyzer_context.metric_map) == {Size()} for r in only
        )

    def test_records_and_json(self, tmp_path, ctx):
        repo = PartitionedMetricsRepository(str(tmp_path / "hist"))
        repo.save(ResultKey(BASE_MS, {"env": "prod"}), ctx)
        rows = repo.load().get_success_metrics_as_records(with_tags=["env"])
        assert rows and all(r["env"] == "prod" for r in rows)
        json.loads(repo.load().get_success_metrics_as_json())

    def test_survives_reopen(self, tmp_path, ctx):
        path = str(tmp_path / "hist")
        PartitionedMetricsRepository(path).save(ResultKey(BASE_MS), ctx)
        reopened = PartitionedMetricsRepository(path)
        loaded = reopened.load_by_key(ResultKey(BASE_MS))
        assert loaded.metric_map[Size()].value.get() == 64.0


class TestJvmDialect:
    def test_gson_history_imports(self, tmp_path, ctx):
        from deequ_tpu.interop import write_jvm_metrics_history_json

        repo = PartitionedMetricsRepository(str(tmp_path / "hist"))
        payload = write_jvm_metrics_history_json([
            AnalysisResult(ResultKey(BASE_MS + d * DAY_MS, {"jvm": "1"}), ctx)
            for d in range(3)
        ])
        assert repo.import_jvm_history(payload) == 3
        got = repo.load().with_tag_values({"jvm": "1"}).get()
        assert len(got) == 3
        # storage is the checksummed NATIVE layout (round-trips verified)
        assert repo.load_by_key(
            ResultKey(BASE_MS, {"jvm": "1"})
        ).metric_map[Size()].value.get() == 64.0


class TestLegacyFsWindowedLoad:
    def test_bounded_query_skips_out_of_window_deserialization(
        self, tmp_path, ctx
    ):
        """THE ISSUE-15 regression pin for the legacy one-file layout: a
        [after, before]-bounded load deserializes ONLY in-window entries
        (result-key dates are peeked from the raw dicts first)."""
        repo = FileSystemMetricsRepository(str(tmp_path / "legacy.json"))
        for t in range(50):
            repo.save(ResultKey(t * 1000), ctx)
        repo.entries_deserialized = 0
        got = repo.load().after(10_000).before(19_000).get()
        assert len(got) == 10
        assert repo.entries_deserialized == 10
        # an unbounded load still deserializes everything
        repo.entries_deserialized = 0
        assert len(repo.load().get()) == 50
        assert repo.entries_deserialized == 50

    def test_windowed_results_equal_unwindowed_filter(self, tmp_path, ctx):
        repo = FileSystemMetricsRepository(str(tmp_path / "legacy.json"))
        for t in range(20):
            repo.save(ResultKey(t, {"i": str(t)}), ctx)
        windowed = repo.load().after(5).before(12).get()
        full = [
            r for r in repo.load().get()
            if 5 <= r.result_key.data_set_date <= 12
        ]
        assert [r.result_key for r in windowed] == [
            r.result_key for r in full
        ]

    def test_unpeekable_entry_still_quarantines(self, tmp_path, ctx):
        """A structurally-odd entry (no peekable date) must flow through
        full deserialization so the quarantine path sees it — the window
        peek must not hide corruption."""
        from deequ_tpu.repository.fs import quarantined_total

        path = tmp_path / "legacy.json"
        repo = FileSystemMetricsRepository(str(path))
        repo.save(ResultKey(1000), ctx)
        entries = json.loads(path.read_text())
        entries.append({"garbage": True})
        path.write_text(json.dumps(entries))
        before = quarantined_total()
        got = repo.load().after(500).before(1500).get()
        assert len(got) == 1
        assert quarantined_total() - before == 1
