"""Device-resident frequency engine (ROADMAP item 3): bit-exact parity
against the host group-by/spill path across cardinalities and key types,
overflow-tier activation, spill-dir lifecycle, env-knob validation, and
ported reference `UniquenessTest.scala` scenarios.

The engine computes grouping frequencies ON DEVICE as fixed-shape sorted
(hash-key, count) tables folded in the fused pass; the host accumulator
(and its ``_SpillStore``) is the LAST-RESORT tier. Parity here is ``==``,
not approx: scalar frequency reductions are pure functions of the count
multiset, the single-column integral mixes are bijective, and Entropy's
float reduction runs in canonical (sorted-counts) order on both paths.
"""

import glob
import os
import tempfile

import numpy as np
import pandas as pd
import pytest

from deequ_tpu.analyzers import (
    CountDistinct,
    Distinctness,
    Entropy,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor


def _battery(cols):
    one = cols[0]
    return [
        Uniqueness(cols), Distinctness(cols), CountDistinct(cols),
        UniqueValueRatio(cols), Entropy(one) if len(cols) == 1 else Uniqueness(cols),
    ]


def _run(data, battery, monitor=None, **kw):
    return AnalysisRunner.do_analysis_run(
        data, battery, monitor=monitor, **kw
    )


def _values(ctx, battery):
    return {repr(a): ctx.metric(a).value.get() for a in battery}


def _parity(data, cols, monkeypatch, expect_device_sets=1, batch_size=None):
    """Run the battery through the device table engine, then with the
    engine disabled (host group-by), and require BIT-EXACT equality."""
    battery = _battery(cols)
    mon = RunMonitor()
    kw = {"batch_size": batch_size} if batch_size else {}
    dev = _values(_run(data, battery, monitor=mon, **kw), battery)
    assert mon.device_freq_sets == expect_device_sets, (
        mon.device_freq_sets, expect_device_sets
    )
    assert mon.freq_overflow_fallbacks == 0
    monkeypatch.setenv("DEEQU_TPU_DEVICE_FREQ", "0")
    try:
        host = _values(_run(data, battery, **kw), battery)
    finally:
        # restore NOW: callers invoke _parity more than once per test, and
        # monkeypatch only reverts at teardown
        monkeypatch.delenv("DEEQU_TPU_DEVICE_FREQ")
    for k in dev:
        assert dev[k] == host[k], (k, dev[k], host[k])
    return dev


class TestBitExactParity:
    """Device table engine vs host spill path across cardinalities and
    key kinds — the tentpole's correctness contract."""

    @pytest.mark.parametrize("distinct", [100, 5_000, 60_000])
    def test_integral_cardinality_sweep(self, distinct, monkeypatch):
        rng = np.random.default_rng(distinct)
        n = max(4 * distinct, 20_000)
        data = Dataset.from_dict({"k": rng.integers(0, distinct, n)})
        _parity(data, ["k"], monkeypatch)

    def test_negative_and_extreme_integers(self, monkeypatch):
        rng = np.random.default_rng(2)
        vals = np.concatenate([
            rng.integers(-(2**62), 2**62, 30_000),
            np.array([0, -1, 2**63 - 1, -(2**63)], dtype=np.int64),
        ])
        data = Dataset.from_dict({"k": vals})
        _parity(data, ["k"], monkeypatch)

    def test_strings_high_cardinality(self, monkeypatch):
        rng = np.random.default_rng(3)
        vals = [f"key-{v:07d}" for v in rng.integers(0, 40_000, 120_000)]
        data = Dataset.from_dict({"s": vals})
        _parity(data, ["s"], monkeypatch)

    def test_fractional_with_nan_and_negzero(self, monkeypatch):
        rng = np.random.default_rng(4)
        vals = rng.integers(0, 9_000, 60_000).astype(np.float64) / 8.0
        vals[::13] = np.nan    # NaN VALUES form one real group
        vals[::29] = -0.0      # -0.0 and 0.0 are the same group
        vals[::31] = 0.0
        data = Dataset.from_dict({"f": vals})
        _parity(data, ["f"], monkeypatch)

    def test_nulls_masked_rows(self, monkeypatch):
        rng = np.random.default_rng(5)
        vals = pd.array(rng.integers(0, 7_000, 50_000), dtype="Int64")
        vals[::7] = pd.NA      # masked rows leave the frequency table but
        data = Dataset.from_dict({"k": vals})  # still count in num_rows
        _parity(data, ["k"], monkeypatch)

    def test_multicolumn_mixed_kinds(self, monkeypatch):
        """Multi-column grouping sets finally leave the host path: chained
        xxhash64 combined keys over int+string+float columns."""
        rng = np.random.default_rng(6)
        n = 60_000
        data = Dataset.from_dict({
            "i": rng.integers(0, 500, n),
            "s": [f"s{v}" for v in rng.integers(0, 200, n)],
            "f": np.round(rng.random(n), 2),
        })
        _parity(data, ["i", "s"], monkeypatch)
        _parity(data, ["i", "s", "f"], monkeypatch)

    def test_multicolumn_order_sensitivity(self):
        """(a,b) and (b,a) group identically as SETS of rows, and both
        orders must produce the same metrics (chained keys differ, count
        multisets cannot)."""
        rng = np.random.default_rng(7)
        n = 30_000
        data = Dataset.from_dict({
            "a": rng.integers(0, 300, n), "b": rng.integers(0, 77, n),
        })
        ab = _values(_run(data, [Uniqueness(["a", "b"])]), [Uniqueness(["a", "b"])])
        ba = _values(_run(data, [Uniqueness(["b", "a"])]), [Uniqueness(["b", "a"])])
        assert list(ab.values()) == list(ba.values())

    def test_batched_equals_single_batch(self, monkeypatch):
        """Cross-batch state folding (append + in-trace compaction) equals
        a one-batch run — the semigroup contract the mesh merge rides."""
        rng = np.random.default_rng(8)
        data = Dataset.from_dict({"k": rng.integers(0, 20_000, 100_000)})
        battery = _battery(["k"])
        whole = _values(_run(data, battery), battery)
        batched = _values(_run(data, battery, batch_size=4096), battery)
        assert whole == batched

    @pytest.mark.slow
    def test_five_million_distinct(self, monkeypatch):
        """The BENCH-scale knee: 5e6 distinct keys still fit the default
        table (2^22 slots is exceeded -> capped at rows) — overflow tier
        activates only when slots < distinct."""
        rng = np.random.default_rng(9)
        n = 10_000_000
        data = Dataset.from_dict({"k": rng.integers(0, 5_000_000, n)})
        _parity(data, ["k"], monkeypatch, batch_size=1 << 20)


class TestOverflowTier:
    def test_compaction_path_parity_when_table_fits(self, monkeypatch):
        """Force the NON-resident trace (tiny buffer cap -> in-pass
        sort-merge compactions) with a table big enough for every group:
        no loss, metrics bit-exact — the compaction machinery itself is
        parity-checked, not just the resident fast path."""
        monkeypatch.setenv("DEEQU_TPU_FREQ_BUFFER_ENTRIES", "8192")
        rng = np.random.default_rng(22)
        data = Dataset.from_dict({"k": rng.integers(0, 9_000, 60_000)})
        _parity(data, ["k"], monkeypatch, batch_size=4096)

    def test_overflow_falls_back_to_host_exactly(self, monkeypatch):
        """A table too small for the key space overflows with EXACT loss
        accounting; the runner re-runs the set through the host
        accumulator and the metrics stay bit-exact. (The buffer cap is
        forced below the row count: a RESIDENT run never overflows — its
        drain is exact at any cardinality up to the buffer.)"""
        monkeypatch.setenv("DEEQU_TPU_FREQ_BUFFER_ENTRIES", "8192")
        monkeypatch.setenv("DEEQU_TPU_FREQ_TABLE_SLOTS", "1024")
        rng = np.random.default_rng(10)
        data = Dataset.from_dict({"k": rng.integers(0, 30_000, 80_000)})
        battery = _battery(["k"])
        mon = RunMonitor()
        dev = _values(_run(data, battery, monitor=mon, batch_size=8192), battery)
        assert mon.freq_overflow_fallbacks >= 1
        monkeypatch.setenv("DEEQU_TPU_DEVICE_FREQ", "0")
        host = _values(_run(data, battery, batch_size=8192), battery)
        assert dev == host

    def test_fitting_table_never_overflows(self, monkeypatch):
        """slots >= num_rows can never overflow: no fallback pass."""
        rng = np.random.default_rng(11)
        data = Dataset.from_dict({"k": rng.integers(0, 50_000, 60_000)})
        mon = RunMonitor()
        _run(data, [CountDistinct(["k"])], monitor=mon)
        assert mon.device_freq_sets == 1
        assert mon.freq_overflow_fallbacks == 0

    def test_mixed_overflow_and_fitting_sets(self, monkeypatch):
        """Only the overflowing set re-runs on the host tier; fitting sets
        keep their device result."""
        monkeypatch.setenv("DEEQU_TPU_FREQ_BUFFER_ENTRIES", "8192")
        monkeypatch.setenv("DEEQU_TPU_FREQ_TABLE_SLOTS", "2048")
        rng = np.random.default_rng(12)
        n = 40_000
        wide = rng.integers(0, 30_000, n)     # overflows 2048 slots
        narrow = rng.integers(0, 900, n)      # fits
        data = Dataset.from_dict({"wide": wide, "narrow": narrow})
        battery = [CountDistinct(["wide"]), CountDistinct(["narrow"])]
        mon = RunMonitor()
        ctx = _run(data, battery, monitor=mon, batch_size=8192)
        assert mon.device_freq_sets == 2
        assert mon.freq_overflow_fallbacks == 1
        assert ctx.metric(CountDistinct(["wide"])).value.get() == len(np.unique(wide))
        assert ctx.metric(CountDistinct(["narrow"])).value.get() == len(np.unique(narrow))


class TestSpillDirLifecycle:
    """Satellite: the host spill tier's temp dirs must not leak."""

    def _spill_dirs(self):
        return set(glob.glob(os.path.join(
            tempfile.gettempdir(), "deequ-tpu-freq-spill-*"
        )))

    def test_spilled_then_collected_leaves_no_directory(self, monkeypatch):
        """Regression (satellite 1): a run that spilled to disk releases
        its ``deequ-tpu-freq-spill-*`` dir as soon as metrics are derived
        — explicit close, not GC luck."""
        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "500")
        monkeypatch.setenv("DEEQU_TPU_DEVICE_FREQ", "0")  # force host tier
        before = self._spill_dirs()
        data = Dataset.from_dict({"k": np.arange(30_000) % 20_000})
        ctx = _run(data, [Uniqueness(["k"]), CountDistinct(["k"])])
        assert ctx.metric(CountDistinct(["k"])).value.get() == 20_000.0
        # the state object may still be alive inside the result context —
        # the explicit close must already have removed the directory
        assert self._spill_dirs() == before

    def test_close_is_idempotent_and_blocks_reads(self):
        from deequ_tpu.analyzers.grouping import FrequenciesAndNumRows

        state = FrequenciesAndNumRows.empty(["k"])
        os.environ["DEEQU_TPU_MAX_FREQUENCY_ENTRIES"] = "100"
        try:
            state._append_run(
                pd.Series(np.ones(2000, dtype=np.int64), index=pd.RangeIndex(2000))
            )
            state._flush()
        finally:
            del os.environ["DEEQU_TPU_MAX_FREQUENCY_ENTRIES"]
        assert state.spilled
        spill_dir = state._spill.dir
        assert os.path.isdir(spill_dir)
        state.close()
        state.close()  # idempotent
        assert not os.path.exists(spill_dir)
        with pytest.raises(RuntimeError, match="closed"):
            list(state.iter_merged_chunks())

    def test_unspilled_close_is_noop(self):
        from deequ_tpu.analyzers.grouping import FrequenciesAndNumRows

        state = FrequenciesAndNumRows.empty(["k"])
        state._append_run(pd.Series(np.int64(3), index=pd.Index(["a"])))
        state.close()
        assert state.num_distinct() == 1


class TestEnvKnobs:
    """Satellite: warn-and-fallback validation (the watchdog/trace
    convention) for the frequency-engine knobs."""

    def _fresh(self, monkeypatch):
        from deequ_tpu.analyzers import grouping

        monkeypatch.setattr(grouping, "_ENV_WARNED", set())
        return grouping

    def test_invalid_table_slots_warns_and_defaults(self, monkeypatch, caplog):
        g = self._fresh(monkeypatch)
        monkeypatch.setenv("DEEQU_TPU_FREQ_TABLE_SLOTS", "a-lot")
        with caplog.at_level("WARNING"):
            assert g.freq_table_slots() == g.DEFAULT_FREQ_TABLE_SLOTS
            assert g.freq_table_slots() == g.DEFAULT_FREQ_TABLE_SLOTS
        warned = [r for r in caplog.records if "DEEQU_TPU_FREQ_TABLE_SLOTS" in r.message]
        assert len(warned) == 1  # warn ONCE, not per pass

    def test_nonpositive_table_slots_rejected(self, monkeypatch):
        g = self._fresh(monkeypatch)
        monkeypatch.setenv("DEEQU_TPU_FREQ_TABLE_SLOTS", "-8")
        assert g.freq_table_slots() == g.DEFAULT_FREQ_TABLE_SLOTS

    def test_valid_table_slots_honored(self, monkeypatch):
        g = self._fresh(monkeypatch)
        monkeypatch.setenv("DEEQU_TPU_FREQ_TABLE_SLOTS", "4096")
        assert g.freq_table_slots() == 4096

    def test_invalid_buffer_entries_warns_and_defaults(self, monkeypatch, caplog):
        g = self._fresh(monkeypatch)
        monkeypatch.setenv("DEEQU_TPU_FREQ_BUFFER_ENTRIES", "0x2000")
        with caplog.at_level("WARNING"):
            assert g.freq_buffer_entries() == g.DEFAULT_FREQ_BUFFER_ENTRIES
        assert any(
            "DEEQU_TPU_FREQ_BUFFER_ENTRIES" in r.message for r in caplog.records
        )

    def test_invalid_max_cardinality_warns_and_defaults(self, monkeypatch, caplog):
        g = self._fresh(monkeypatch)
        monkeypatch.setenv("DEEQU_TPU_DEVICE_FREQ_MAX_CARDINALITY", "64k")
        with caplog.at_level("WARNING"):
            assert g.device_freq_max_cardinality() == g.DEVICE_FREQ_MAX_CARDINALITY
        assert any(
            "DEEQU_TPU_DEVICE_FREQ_MAX_CARDINALITY" in r.message
            for r in caplog.records
        )

    def test_invalid_device_freq_switch_stays_enabled(self, monkeypatch, caplog):
        g = self._fresh(monkeypatch)
        monkeypatch.setenv("DEEQU_TPU_DEVICE_FREQ", "yes")
        with caplog.at_level("WARNING"):
            assert g.device_freq_enabled() is True
        assert any("DEEQU_TPU_DEVICE_FREQ" in r.message for r in caplog.records)

    def test_disable_switch_routes_to_host(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_DEVICE_FREQ", "0")
        rng = np.random.default_rng(13)
        data = Dataset.from_dict({"k": rng.integers(0, 9_000, 20_000)})
        mon = RunMonitor()
        _run(data, [CountDistinct(["k"])], monitor=mon)
        assert mon.device_freq_sets == 0


class TestHashingPrimitives:
    """The numpy twins must be bit-identical to the traced jnp hashing —
    what makes host-side parity reconstruction possible at all."""

    def test_splitmix64_twins_bit_identical(self):
        import jax.numpy as jnp

        from deequ_tpu.ops.hashing import splitmix64, splitmix64_jnp

        rng = np.random.default_rng(14)
        v = rng.integers(0, 2**64, 4096, dtype=np.uint64)
        got = np.asarray(splitmix64_jnp(jnp.asarray(v)))
        assert (got == splitmix64(v)).all()

    def test_splitmix64_bijective_on_sample(self):
        from deequ_tpu.ops.hashing import splitmix64

        v = np.arange(100_000, dtype=np.uint64)
        assert len(np.unique(splitmix64(v))) == len(v)

    def test_xxhash64_u64_twins_and_chaining(self):
        import jax.numpy as jnp

        from deequ_tpu.ops.hashing import (
            xxhash64_u64,
            xxhash64_u64_jnp,
        )

        rng = np.random.default_rng(15)
        v = rng.integers(0, 2**64, 2048, dtype=np.uint64)
        seeds = rng.integers(0, 2**64, 2048, dtype=np.uint64)
        # scalar seed agrees with the pinned host xxhash64_u64
        got = np.asarray(xxhash64_u64_jnp(jnp.asarray(v), jnp.uint64(42)))
        assert (got == xxhash64_u64(v, 42)).all()
        # per-row seeds (multi-column chaining) agree with the numpy twin
        got = np.asarray(xxhash64_u64_jnp(jnp.asarray(v), jnp.asarray(seeds)))
        assert (got == xxhash64_u64(v, seeds)).all()

    def test_resident_flag_changes_program_identity(self):
        """``resident`` flips the traced update (cond-free append vs
        conditional compaction) without changing state shapes or feature
        kinds — so it MUST split the bundled-program signature, or a
        non-resident run whose (slots, buffer) match a cached resident
        program would run the cond-free trace and silently overflow."""
        from deequ_tpu.analyzers.grouping import DeviceFrequencyTableScan
        from deequ_tpu.runners.engine import _scan_signature

        res = DeviceFrequencyTableScan(
            ("k",), ("num",), 1 << 12, 1 << 12, resident=True
        )
        cond = DeviceFrequencyTableScan(
            ("k",), ("num",), 1 << 12, 1 << 12, resident=False
        )
        assert _scan_signature(res) != _scan_signature(cond)

    def test_freq_compact_overflow_accounting_exact(self):
        import jax.numpy as jnp

        from deequ_tpu.ops import freq_compact
        from deequ_tpu.ops.hashing import FREQ_KEY_SENTINEL

        sent = np.uint64(FREQ_KEY_SENTINEL)
        keys = np.array([7, 3, 3, 9, 1, 1, 1], dtype=np.uint64)
        counts = np.array([2, 1, 4, 5, 1, 1, 1], dtype=np.int64)
        pad = np.full(3, sent, dtype=np.uint64)
        ok, oc, n, kept, total = freq_compact(
            jnp.concatenate([jnp.asarray(keys), jnp.asarray(pad)]),
            jnp.concatenate([jnp.asarray(counts), jnp.zeros(3, jnp.int64)]),
            2, jnp.uint64(sent),
        )
        # 4 uniques {1:3, 3:5, 7:2, 9:5}; out_size=2 keeps the two smallest
        assert int(n) == 4
        assert list(np.asarray(ok)) == [1, 3]
        assert list(np.asarray(oc)) == [3, 5]
        assert int(total) == 15 and int(kept) == 8  # 7 rows lost, exactly


class TestStateMergePaths:
    def test_split_fold_merge_equals_single_fold(self):
        """Two half-dataset table states merged == one whole-dataset state
        (the collective_merge_states semigroup contract)."""
        import jax.numpy as jnp

        from deequ_tpu.analyzers.grouping import DeviceFrequencyTableScan

        rng = np.random.default_rng(16)
        keys = rng.integers(0, 5_000, 16_384, dtype=np.uint64)
        scan = DeviceFrequencyTableScan(("k",), ("num",), 8192, 4096)
        z = jnp.zeros((), jnp.int64)

        def fold(arr):
            st = scan.init_state()
            from deequ_tpu.ops.hashing import splitmix64_jnp

            for at in range(0, len(arr), 4096):
                c = arr[at : at + 4096]
                hashed = splitmix64_jnp(jnp.asarray(c))
                st = st.append_keys(
                    hashed, z, jnp.asarray(len(c), jnp.int64)
                )
            return st

        whole = scan.drain(fold(keys))
        halves = scan.merge(fold(keys[:8192]), fold(keys[8192:]))
        merged = scan.drain(halves)

        def pairs(hf):
            # key ORDER is not part of the HashedFrequencies contract (the
            # native drain emits in probe order) — the multiset is
            order = np.argsort(hf.keys)
            return hf.keys[order].tolist(), hf.counts[order].tolist()

        assert pairs(whole) == pairs(merged)
        assert whole.num_rows == merged.num_rows
        assert whole.stream_summary() == merged.stream_summary()

    def test_hashed_frequencies_refuses_value_keyed_merge(self):
        from deequ_tpu.analyzers.grouping import (
            FrequenciesAndNumRows,
            HashedFrequencies,
        )

        hf = HashedFrequencies(
            np.array([1], dtype=np.uint64), np.array([2], dtype=np.int64), 2, ["k"]
        )
        with pytest.raises(TypeError, match="never mix"):
            hf.sum(FrequenciesAndNumRows.empty(["k"]))
        with pytest.raises(TypeError, match="never mix"):
            FrequenciesAndNumRows.empty(["k"]).sum(hf)


@pytest.mark.grouping
@pytest.mark.chaos
class TestGroupingChaos:
    """Satellite: the overflow tier under the existing fault-injection
    sites — a device fault mid-pass and an injected overflow both land on
    the host last-resort tier with exact metrics."""

    def _data(self, distinct=20_000, n=60_000, seed=20):
        rng = np.random.default_rng(seed)
        return Dataset.from_dict({"k": rng.integers(0, distinct, n)})

    def test_device_fault_during_table_pass_fails_over_exact(self):
        from deequ_tpu.reliability.faults import FaultSpec, inject

        data = self._data()
        battery = _battery(["k"])
        want = _values(_run(data, battery), battery)
        mon = RunMonitor()
        with inject(
            FaultSpec("device_update", "device", at=2), seed=7
        ) as inj:
            got = _values(_run(data, battery, monitor=mon, batch_size=8192), battery)
        assert inj.fired
        # whatever ladder rung caught it (failover, isolation of the table
        # scan, or the host fallback pass), the run must complete with the
        # exact metrics and a second pass must have served the set
        assert mon.passes >= 2
        assert got == want

    def test_overflow_tier_with_host_fault_still_terminates_typed(self, monkeypatch):
        """Overflow fallback pass + an injected analyzer fault in it: the
        grouping analyzers degrade TYPED, never hang or go silently
        wrong."""
        from deequ_tpu.reliability.faults import FaultSpec, inject

        monkeypatch.setenv("DEEQU_TPU_FREQ_TABLE_SLOTS", "1024")
        data = self._data()
        battery = _battery(["k"])
        mon = RunMonitor()
        with inject(
            FaultSpec("device_update", "device", at=3, count=None, every=1000),
            seed=11,
        ):
            ctx = _run(data, battery, monitor=mon, batch_size=8192)
        for a in battery:
            value = ctx.metric(a).value
            if value.is_failure:
                assert value.exception is not None  # typed, not swallowed
            else:
                assert np.isfinite(value.get())

    def test_overflow_chaos_metrics_exact_when_fallback_clean(self, monkeypatch):
        """Forced overflow (tiny table) with faults armed at unreached
        sites: the fallback path alone must reproduce exact metrics."""
        from deequ_tpu.reliability.faults import FaultSpec, inject

        monkeypatch.setenv("DEEQU_TPU_FREQ_BUFFER_ENTRIES", "8192")
        monkeypatch.setenv("DEEQU_TPU_FREQ_TABLE_SLOTS", "1024")
        data = self._data(seed=21)
        battery = _battery(["k"])
        monkeypatch.setenv("DEEQU_TPU_DEVICE_FREQ", "0")
        want = _values(_run(data, battery, batch_size=8192), battery)
        monkeypatch.delenv("DEEQU_TPU_DEVICE_FREQ")
        mon = RunMonitor()
        with inject(FaultSpec("checkpoint", "device", at=1), seed=13):
            got = _values(_run(data, battery, monitor=mon, batch_size=8192), battery)
        assert mon.freq_overflow_fallbacks >= 1
        assert got == want


class TestUniquenessReference:
    """Ported reference `UniquenessTest.scala` scenarios, run through the
    DEVICE frequency engine (the suite's fixtures are low-cardinality, so
    the reference behaviors must survive the hashed path too)."""

    def test_all_unique_column_is_one(self):
        data = Dataset.from_dict({"unique": ["a", "b", "c", "d", "e", "f"]})
        ctx = _run(data, [Uniqueness(["unique"])])
        assert ctx.metric(Uniqueness(["unique"])).value.get() == 1.0

    def test_non_unique_column(self):
        # reference fixture: att1 = a,b,a,a -> one singleton out of 4 rows
        data = Dataset.from_dict({"att1": ["a", "b", "a", "a"]})
        ctx = _run(data, [Uniqueness(["att1"])])
        assert ctx.metric(Uniqueness(["att1"])).value.get() == 0.25

    def test_unique_with_nulls(self):
        """Nulls leave the frequency table but stay in the denominator
        (reference: uniqueness counts null groups out)."""
        data = Dataset.from_dict({"c": pd.array([1, 2, 3, None, None], dtype="Int64")})
        ctx = _run(data, [Uniqueness(["c"]), Distinctness(["c"])])
        assert ctx.metric(Uniqueness(["c"])).value.get() == 3 / 5
        assert ctx.metric(Distinctness(["c"])).value.get() == 3 / 5

    def test_multi_column_uniqueness(self):
        """reference: (att1, att2) pairs — all pairs distinct -> 1.0 even
        though each column alone is not unique."""
        data = Dataset.from_dict({
            "att1": ["a", "a", "b", "b"], "att2": ["x", "y", "x", "y"],
        })
        single = Uniqueness(["att1"])
        pair = Uniqueness(["att1", "att2"])
        ctx = _run(data, [single, pair])
        assert ctx.metric(pair).value.get() == 1.0
        assert ctx.metric(single).value.get() == 0.0

    def test_all_null_column_yields_empty_metric(self):
        data = Dataset.from_dict({"c": pd.array([None, None], dtype="Int64")})
        ctx = _run(data, [Uniqueness(["c"])])
        value = ctx.metric(Uniqueness(["c"])).value
        assert value.is_failure  # EmptyStateException analog

    def test_unique_value_ratio(self):
        # reference: values a,a,b,c,d -> 3 singletons / 4 distinct
        data = Dataset.from_dict({"c": ["a", "a", "b", "c", "d"]})
        ctx = _run(data, [UniqueValueRatio(["c"])])
        assert ctx.metric(UniqueValueRatio(["c"])).value.get() == 0.75


@pytest.mark.grouping
class TestCardinalityPreRouting:
    """The pre-routing probe keeps confidently-low-cardinality sets on the
    host group-by (whose value_counts fast path wins below the sweep knee)
    while clustered layouts and genuine high cardinality stay on the
    device table. Perf-only routing: metrics stay bit-exact either way."""

    def _probe(self):
        from deequ_tpu.analyzers.grouping import probably_low_cardinality

        return probably_low_cardinality

    def _big(self, distinct, sort=False, rows=2_200_000, seed=21):
        rng = np.random.default_rng(seed)
        keys = rng.integers(0, distinct, rows)
        if sort:
            keys = np.sort(keys)
        return Dataset.from_dict({"k": keys}), keys

    def test_low_cardinality_at_scale_probes_true(self):
        data, _ = self._big(100)
        assert self._probe()(data, ("k",)) is True

    def test_high_cardinality_probes_false(self):
        data, _ = self._big(1_000_000)
        assert self._probe()(data, ("k",)) is False

    def test_clustered_layout_probes_false(self):
        # sorted by key: every slice is low-card but later slices keep
        # revealing NEW keys — total cardinality is unknowable from
        # slices, so the probe must NOT claim low-cardinality
        data, _ = self._big(500_000, sort=True)
        assert self._probe()(data, ("k",)) is False

    def test_small_runs_skip_the_probe(self):
        rng = np.random.default_rng(5)
        data = Dataset.from_dict({"k": rng.integers(0, 50, 100_000)})
        assert self._probe()(data, ("k",)) is False  # below the row floor

    def test_multi_column_product_estimate(self):
        rng = np.random.default_rng(9)
        n = 2_200_000
        data = Dataset.from_dict({
            "a": rng.integers(0, 300, n), "b": rng.integers(0, 300, n),
        })
        # 300 x 300 = 90k possible pairs > the 2^15 ceiling: not confident
        assert self._probe()(data, ("a", "b")) is False
        small = Dataset.from_dict({
            "a": rng.integers(0, 100, n), "b": rng.integers(0, 100, n),
        })
        assert self._probe()(small, ("a", "b")) is True

    def test_knob_zero_disables_probe(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_FREQ_HOST_ROUTE", "0")
        data, _ = self._big(100)
        assert self._probe()(data, ("k",)) is False

    def test_invalid_knob_warns_and_stays_enabled(self, monkeypatch, caplog):
        from deequ_tpu.analyzers import grouping

        monkeypatch.setattr(grouping, "_ENV_WARNED", set())
        monkeypatch.setenv("DEEQU_TPU_FREQ_HOST_ROUTE", "sometimes")
        data, _ = self._big(100)
        import logging

        with caplog.at_level(logging.WARNING):
            assert self._probe()(data, ("k",)) is True
        assert any("DEEQU_TPU_FREQ_HOST_ROUTE" in r.message for r in caplog.records)

    def test_end_to_end_low_card_routes_host_bit_exact(self, monkeypatch):
        data, _ = self._big(100)
        battery = _battery(["k"])
        mon = RunMonitor()
        routed = _values(_run(data, battery, monitor=mon, batch_size=1 << 20), battery)
        assert mon.device_freq_sets == 0  # probe kept it on the host path
        monkeypatch.setenv("DEEQU_TPU_FREQ_HOST_ROUTE", "0")
        mon2 = RunMonitor()
        forced = _values(_run(data, battery, monitor=mon2, batch_size=1 << 20), battery)
        assert mon2.device_freq_sets == 1
        assert routed == forced
