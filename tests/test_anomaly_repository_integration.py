"""Anomaly detection over repository history, end to end — the
`MetricsRepositoryAnomalyDetectionIntegrationTest.scala` analog: a month of
simulated per-marketplace metric history, then a verification run whose
anomaly checks filter that history by tag AND date window before judging
the freshly computed metrics."""

import datetime

import pyarrow as pa
import pytest

from deequ_tpu import (
    AnomalyCheckConfig,
    Check,
    CheckLevel,
    CheckStatus,
    DoubleMetric,
    Entity,
    InMemoryMetricsRepository,
    ResultKey,
    Success,
    VerificationSuite,
)
from deequ_tpu.analyzers import Maximum, Mean, Minimum, Size
from deequ_tpu.anomalydetection import AbsoluteChangeStrategy, OnlineNormalStrategy
from deequ_tpu.data import Dataset
from deequ_tpu.repository import FileSystemMetricsRepository
from deequ_tpu.runners.context import AnalyzerContext


def _date_ms(year: int, month: int, day: int) -> int:
    return int(
        datetime.datetime(year, month, day, tzinfo=datetime.timezone.utc).timestamp()
        * 1000
    )


def _test_data() -> Dataset:
    """(reference `getTestData`: 8 EU rows, sales mean 206.625)."""
    rows = [
        ("item1", "US", 100), ("item1", "US", 1000), ("item1", "US", 20),
        ("item2", "DE", 20), ("item2", "DE", 333),
        ("item3", None, 12), ("item4", None, 45), ("item5", None, 123),
    ]
    return Dataset.from_arrow(
        pa.table(
            {
                "item": pa.array([r[0] for r in rows]),
                "origin": pa.array([r[1] for r in rows]),
                "sales": pa.array([r[2] for r in rows], type=pa.int64()),
                "marketplace": pa.array(["EU"] * len(rows)),
            }
        )
    )


def _fill_repository_with_previous_results(repository) -> None:
    """30 July-2018 days of Size/Mean history per marketplace (reference
    `fillRepositoryWithPreviousResults`)."""
    for past_day in range(1, 31):
        eu = AnalyzerContext(
            {
                Size(): DoubleMetric(Entity.DATASET, "Size", "*", Success(past_day // 3 * 1.0)),
                Mean("sales"): DoubleMetric(
                    Entity.COLUMN, "Mean", "sales", Success(past_day * 7.0)
                ),
            }
        )
        na = AnalyzerContext(
            {
                Size(): DoubleMetric(Entity.DATASET, "Size", "*", Success(float(past_day))),
                Mean("sales"): DoubleMetric(
                    Entity.COLUMN, "Mean", "sales", Success(past_day * 9.0)
                ),
            }
        )
        when = _date_ms(2018, 7, past_day)
        repository.save(ResultKey(when, {"marketplace": "EU"}), eu)
        repository.save(ResultKey(when, {"marketplace": "NA"}), na)


def _run_everything(repository):
    data = _test_data()
    check = (
        Check(CheckLevel.ERROR, "check")
        .is_complete("item")
        .is_complete("origin")
        .is_contained_in("marketplace", ["EU"])
        .is_non_negative("sales")
    )
    filter_eu = {"marketplace": "EU"}
    after = _date_ms(2018, 1, 1)
    before = _date_ms(2018, 8, 1)
    return (
        VerificationSuite.on_data(data)
        .add_check(check)
        .add_required_analyzers([Maximum("sales"), Minimum("sales")])
        .use_repository(repository)
        # size must only increase: new size 8 < last EU size 10 -> anomaly
        .add_anomaly_check(
            AbsoluteChangeStrategy(0.0),
            Size(),
            AnomalyCheckConfig(
                CheckLevel.ERROR, "Size only increases", filter_eu, after, before
            ),
        )
        # mean sales 206.625 is within 2 stddev of the EU history (~111 +/- ~62)
        .add_anomaly_check(
            OnlineNormalStrategy(upper_deviation_factor=2.0, ignore_anomalies=False),
            Mean("sales"),
            AnomalyCheckConfig(
                CheckLevel.WARNING,
                "Sales mean within 2 standard deviations",
                filter_eu,
                after,
                before,
            ),
        )
        .save_or_append_result(ResultKey(_date_ms(2018, 8, 1), filter_eu))
        .run()
    )


def _assert_results(result) -> None:
    by_description = {
        check.description: check_result
        for check, check_result in result.check_results.items()
    }
    # the NA history (size up to 30, means *9) must NOT leak into the
    # EU-filtered checks: with it, size 8 would not be the anomaly judgement
    # the reference pins
    assert by_description["Size only increases"].status == CheckStatus.ERROR
    assert (
        by_description["Sales mean within 2 standard deviations"].status
        == CheckStatus.SUCCESS
    )
    assert by_description["check"].status == CheckStatus.ERROR  # origin has nulls


class TestAnomalyDetectionOverRepositoryHistory:
    def test_in_memory_repository(self):
        repository = InMemoryMetricsRepository()
        _fill_repository_with_previous_results(repository)
        _assert_results(_run_everything(repository))

    def test_filesystem_repository(self, tmp_path):
        repository = FileSystemMetricsRepository(str(tmp_path / "metrics.json"))
        _fill_repository_with_previous_results(repository)
        _assert_results(_run_everything(repository))

    def test_new_result_lands_in_repository(self):
        repository = InMemoryMetricsRepository()
        _fill_repository_with_previous_results(repository)
        _run_everything(repository)
        saved = repository.load_by_key(
            ResultKey(_date_ms(2018, 8, 1), {"marketplace": "EU"})
        )
        assert saved is not None
        assert saved.metric(Size()).value.get() == 8.0
        assert saved.metric(Mean("sales")).value.get() == pytest.approx(206.625)
