"""Serde/state round-trip matrix (VERDICT round-2 item 7): every analyzer's
state round-trips bit-exactly through BOTH state providers, and every
analyzer + metric round-trips through the JSON result serde — the
`StateProviderTest.scala:187-311` / `AnalysisResultSerdeTest.scala:75-106`
analog."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    KLLParameters,
    KLLSketch,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.analyzers.state_provider import (
    FileSystemStateProvider,
    InMemoryStateProvider,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner

ALL_ANALYZERS = [
    Size(),
    Size(where="x > 0"),
    Completeness("x"),
    Compliance("pos", "x > 0"),
    PatternMatch("s", r"v\d+"),
    Mean("x"),
    Sum("x"),
    Minimum("x"),
    Maximum("x"),
    MinLength("s"),
    MaxLength("s"),
    StandardDeviation("x"),
    Correlation("x", "y"),
    DataType("s"),
    ApproxCountDistinct("s"),
    ApproxQuantile("x", 0.5),
    ApproxQuantiles("x", (0.25, 0.5, 0.75)),
    KLLSketch("x", KLLParameters(512, 0.64, 20)),
    Uniqueness(["cat"]),
    Distinctness(["cat"]),
    UniqueValueRatio(["cat"]),
    CountDistinct(["cat"]),
    Entropy("cat"),
    MutualInformation(["cat", "cat2"]),
    Histogram("cat"),
]


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(9)
    n = 5000
    return Dataset.from_arrow(
        pa.table(
            {
                "x": pa.array(rng.normal(size=n), mask=rng.random(n) < 0.05),
                "y": pa.array(rng.normal(size=n)),
                "s": pa.array([None if i % 17 == 0 else f"v{i % 97}" for i in range(n)]),
                "cat": pa.array([f"c{int(v)}" for v in rng.integers(0, 40, n)]),
                "cat2": pa.array([f"d{int(v)}" for v in rng.integers(0, 7, n)]),
            }
        )
    )


def _states_equal(a, b) -> None:
    """Bit-exact pytree equality (incl. dtypes) for numpy/jax state trees
    and FrequenciesAndNumRows."""
    from deequ_tpu.analyzers.grouping import FrequenciesAndNumRows

    if isinstance(a, FrequenciesAndNumRows):
        assert isinstance(b, FrequenciesAndNumRows)
        assert a.num_rows == b.num_rows
        assert a.group_columns == b.group_columns
        pd.testing.assert_series_equal(
            a.frequencies.sort_index(), b.frequencies.sort_index(),
            check_names=False,
        )
        return
    import jax

    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype, (x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y)


class TestStateProviderRoundTrips:
    @pytest.fixture(scope="class")
    def computed_states(self, data):
        sp = InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(data, ALL_ANALYZERS, save_states_with=sp)
        return {a: sp.load(a) for a in ALL_ANALYZERS}

    @pytest.mark.parametrize("analyzer", ALL_ANALYZERS, ids=lambda a: str(a)[:60])
    def test_filesystem_round_trip_bit_exact(self, analyzer, computed_states, tmp_path):
        state = computed_states[analyzer]
        assert state is not None, f"no state persisted for {analyzer}"
        sp = FileSystemStateProvider(str(tmp_path))
        sp.persist(analyzer, state)
        _states_equal(state, sp.load(analyzer))

    @pytest.mark.parametrize("analyzer", ALL_ANALYZERS, ids=lambda a: str(a)[:60])
    def test_memory_round_trip_identity(self, analyzer, computed_states):
        state = computed_states[analyzer]
        sp = InMemoryStateProvider()
        sp.persist(analyzer, state)
        _states_equal(state, sp.load(analyzer))

    def test_loaded_states_yield_identical_metrics(self, data, computed_states, tmp_path):
        """A full persist + reload + run_on_aggregated_states cycle produces
        the same metrics as the original run."""
        sp = FileSystemStateProvider(str(tmp_path))
        for a, state in computed_states.items():
            sp.persist(a, state)
        direct = AnalysisRunner.do_analysis_run(data, ALL_ANALYZERS)
        from_states = AnalysisRunner.run_on_aggregated_states(
            data.schema, ALL_ANALYZERS, [sp]
        )
        for a in ALL_ANALYZERS:
            dv = direct.metric(a).value
            sv = from_states.metric(a).value
            assert dv.is_success == sv.is_success, a
            if dv.is_success and isinstance(dv.get(), float):
                assert sv.get() == pytest.approx(dv.get(), rel=1e-9, abs=1e-12), a

    def test_hll_word_packing_parity(self, computed_states):
        """HLL registers survive the reference's packed uint64[52] word
        layout bit-exactly (`StatefulHyperloglogPlus.scala:170-186`)."""
        from deequ_tpu.ops.hll import registers_to_words, words_to_registers

        regs = np.asarray(computed_states[ApproxCountDistinct("s")].registers)
        assert regs.max() > 0  # non-trivial state
        np.testing.assert_array_equal(
            words_to_registers(registers_to_words(regs)), regs
        )


class TestResultSerde:
    def test_every_analyzer_and_metric_round_trips_json(self, data):
        from deequ_tpu.repository.serde import (
            deserialize_analyzer,
            deserialize_metric,
            serialize_analyzer,
            serialize_metric,
        )

        ctx = AnalysisRunner.do_analysis_run(data, ALL_ANALYZERS)
        for a, metric in ctx.metric_map.items():
            assert deserialize_analyzer(serialize_analyzer(a)) == a, a
            m2 = deserialize_metric(serialize_metric(metric))
            assert m2.name == metric.name and m2.instance == metric.instance
            if metric.value.is_success and isinstance(metric.value.get(), float):
                assert m2.value.get() == metric.value.get(), a

    def test_full_result_round_trip_via_repository(self, data, tmp_path):
        import json

        from deequ_tpu.repository import FileSystemMetricsRepository, ResultKey

        repo = FileSystemMetricsRepository(str(tmp_path / "metrics.json"))
        key = ResultKey(123456, {"tag": "serde"})
        ctx = AnalysisRunner.do_analysis_run(
            data,
            ALL_ANALYZERS,
            metrics_repository=repo,
            save_or_append_results_with_key=key,
        )
        loaded = repo.load_by_key(key)
        for a, metric in ctx.metric_map.items():
            got = loaded.metric(a)
            if metric.value.is_success and isinstance(metric.value.get(), float):
                assert got is not None and got.value.get() == metric.value.get(), a
        # the stored file is well-formed json
        json.loads((tmp_path / "metrics.json").read_text())


def _thirds(data) -> list:
    n = data.num_rows
    return [
        Dataset.from_arrow(data.arrow.slice(i * n // 3, (i + 1) * n // 3 - i * n // 3))
        for i in range(3)
    ]


class TestMergeAlgebraMatrix:
    """Semigroup law for EVERY analyzer: states computed on disjoint
    partitions and merged must yield the same metrics as one computation
    over the union (the `StatesTest`/`IncrementalAnalyzerTest` analog, and
    the correctness contract behind BASELINE config 4)."""

    def test_three_way_partition_merge_equals_full_run(self, data):
        providers = []
        for part in _thirds(data):
            sp = InMemoryStateProvider()
            AnalysisRunner.do_analysis_run(part, ALL_ANALYZERS, save_states_with=sp)
            providers.append(sp)

        merged = AnalysisRunner.run_on_aggregated_states(
            data.schema, ALL_ANALYZERS, providers
        )
        full = AnalysisRunner.do_analysis_run(data, ALL_ANALYZERS)
        from deequ_tpu.metrics import Distribution

        for a in ALL_ANALYZERS:
            mv, fv = merged.metric(a).value, full.metric(a).value
            assert mv.is_success == fv.is_success, a
            if not mv.is_success:
                continue
            if a.name.startswith(("ApproxQuantile", "KLLSketch")):
                continue  # sketch estimates vary across splits within bounds
            got, want = mv.get(), fv.get()
            if isinstance(want, float):
                assert got == pytest.approx(want, rel=1e-9, abs=1e-12), a
            elif isinstance(want, Distribution):
                # exact distributions (DataType, Histogram) merge exactly
                assert {k: v.absolute for k, v in got.values.items()} == {
                    k: v.absolute for k, v in want.values.items()
                }, a
            else:
                raise AssertionError(f"unchecked metric value type for {a}: {type(want)}")

    def test_sketch_merges_stay_within_error_envelopes(self, data):
        providers = []
        battery = [ApproxCountDistinct("s"), ApproxQuantile("x", 0.5)]
        for part in _thirds(data):
            sp = InMemoryStateProvider()
            AnalysisRunner.do_analysis_run(part, battery, save_states_with=sp)
            providers.append(sp)
        merged = AnalysisRunner.run_on_aggregated_states(data.schema, battery, providers)
        # HLL merge is exact (register max): equals the full-run estimate
        full = AnalysisRunner.do_analysis_run(data, battery)
        assert merged.metric(ApproxCountDistinct("s")).value.get() == full.metric(
            ApproxCountDistinct("s")
        ).value.get()
        # merged quantile stays within the rank-error envelope of the truth
        xs = data.arrow["x"].drop_null().to_numpy()
        med = merged.metric(ApproxQuantile("x", 0.5)).value.get()
        rank_err = abs((xs <= med).mean() - 0.5)
        assert rank_err < 0.02, (med, rank_err)


class TestFormatVersioning:
    """VERDICT r3 missing #2 / SURVEY §7 hard part 5: persisted formats carry
    an explicit version; loaders refuse versions they do not understand with
    a typed, actionable error instead of silently misreading the layout."""

    def test_json_roundtrip_carries_version(self):
        from deequ_tpu.repository import AnalysisResult, ResultKey
        from deequ_tpu.repository.serde import (
            SERDE_FORMAT_VERSION,
            deserialize_results,
            serialize_result,
            serialize_results,
        )
        from deequ_tpu.runners.context import AnalyzerContext

        result = AnalysisResult(ResultKey(1234, {"t": "v"}), AnalyzerContext({}))
        d = serialize_result(result)
        assert d["formatVersion"] == SERDE_FORMAT_VERSION
        back = deserialize_results(serialize_results([result]))
        assert back[0].result_key == result.result_key

    def test_json_unknown_version_raises(self):
        import json as _json

        from deequ_tpu.exceptions import UnsupportedFormatVersionError
        from deequ_tpu.repository.serde import deserialize_results

        payload = _json.dumps(
            [{"formatVersion": 99, "resultKey": {"dataSetDate": 0, "tags": {}},
              "analyzerContext": {"metricMap": []}}]
        )
        with pytest.raises(UnsupportedFormatVersionError, match="version 99"):
            deserialize_results(payload)

    def test_json_missing_version_is_v1(self):
        import json as _json

        from deequ_tpu.repository.serde import deserialize_results

        payload = _json.dumps(
            [{"resultKey": {"dataSetDate": 7, "tags": {}},
              "analyzerContext": {"metricMap": []}}]
        )
        assert deserialize_results(payload)[0].result_key.data_set_date == 7

    def test_npz_roundtrip_carries_version(self, tmp_path):
        from deequ_tpu.analyzers.state_provider import (
            STATE_FORMAT_VERSION,
            FileSystemStateProvider,
        )

        data = Dataset.from_dict({"x": np.arange(10, dtype=np.float64)})
        sp = FileSystemStateProvider(str(tmp_path))
        a = Mean("x")
        AnalysisRunner.do_analysis_run(data, [a], save_states_with=sp)
        npz_files = list(tmp_path.glob("*-state.npz"))
        assert npz_files
        payload = np.load(npz_files[0])
        assert int(payload["__format_version__"]) == STATE_FORMAT_VERSION
        state = sp.load(a)
        assert a.compute_metric_from(state).value.get() == pytest.approx(4.5)

    def test_npz_unknown_version_raises(self, tmp_path):
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider
        from deequ_tpu.exceptions import UnsupportedFormatVersionError

        data = Dataset.from_dict({"x": np.arange(10, dtype=np.float64)})
        sp = FileSystemStateProvider(str(tmp_path))
        a = Mean("x")
        AnalysisRunner.do_analysis_run(data, [a], save_states_with=sp)
        npz_file = next(iter(tmp_path.glob("*-state.npz")))
        payload = dict(np.load(npz_file))
        payload["__format_version__"] = np.int64(99)
        np.savez(npz_file, **payload)
        with pytest.raises(UnsupportedFormatVersionError, match="version 99"):
            sp.load(a)

    def test_frequency_sidecar_unknown_version_raises(self, tmp_path):
        import json as _json

        from deequ_tpu.analyzers import Uniqueness
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider
        from deequ_tpu.exceptions import UnsupportedFormatVersionError

        data = Dataset.from_dict({"s": np.array(["a", "b", "a"], dtype=object)})
        sp = FileSystemStateProvider(str(tmp_path))
        a = Uniqueness("s")
        AnalysisRunner.do_analysis_run(data, [a], save_states_with=sp)
        meta_file = next(iter(tmp_path.glob("*-meta.json")))
        meta = _json.loads(meta_file.read_text())
        meta["formatVersion"] = 99
        meta_file.write_text(_json.dumps(meta))
        with pytest.raises(UnsupportedFormatVersionError, match="version 99"):
            sp.load(a)

    def test_v1_json_layout_pinned(self):
        """Freeze the v1 metrics-history JSON byte layout: if this test
        breaks, you changed the persistence schema — bump
        SERDE_FORMAT_VERSION and add a migration path."""
        import json as _json

        from deequ_tpu.metrics import DoubleMetric, Entity, Success
        from deequ_tpu.repository import AnalysisResult, ResultKey
        from deequ_tpu.repository.serde import serialize_results
        from deequ_tpu.runners.context import AnalyzerContext

        a = Mean("x")
        metric = DoubleMetric(Entity.COLUMN, "Mean", "x", Success(4.5))
        result = AnalysisResult(ResultKey(1700000000000, {"env": "t"}),
                                AnalyzerContext({a: metric}))
        # "checksum" is an OPTIONAL trailing member: old readers ignore
        # unknown keys and the new reader accepts its absence (warn-once),
        # so its addition does not bump the version. The pinned digest also
        # freezes the checksum construction itself (canonical sorted-key
        # JSON under xxhash64 seed 0x5EED).
        frozen = (
            '[{"formatVersion": 1, "resultKey": {"dataSetDate": 1700000000000, '
            '"tags": {"env": "t"}}, "analyzerContext": {"metricMap": '
            '[{"analyzer": {"analyzerName": "Mean", "column": "x", "where": null}, '
            '"metric": {"entity": "Column", "instance": "x", "name": "Mean", '
            '"metricName": "DoubleMetric", "value": 4.5}}]}, '
            '"checksum": "2ec68193ff205f29"}]'
        )
        assert serialize_results([result]) == frozen
        assert _json.loads(frozen)  # stays valid JSON
        # the PRE-checksum v1 layout still deserializes (legacy history)
        from deequ_tpu.repository.serde import deserialize_results

        legacy = frozen.replace(', "checksum": "2ec68193ff205f29"', "")
        assert len(deserialize_results(legacy)) == 1

    def test_v2_npz_layout_pinned(self, tmp_path):
        """Freeze the v2 .npz state layout for MeanState: leaf order is
        (total, count) plus the registry markers. If this breaks, bump
        STATE_FORMAT_VERSION."""
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        data = Dataset.from_dict({"x": np.arange(10, dtype=np.float64)})
        sp = FileSystemStateProvider(str(tmp_path))
        AnalysisRunner.do_analysis_run(data, [Mean("x")], save_states_with=sp)
        payload = np.load(next(iter(tmp_path.glob("*-state.npz"))))
        # __checksum__ is an OPTIONAL member older readers ignore (their
        # loaders only look for leaf*/__-prefixed names they know), so its
        # addition does not bump the format version
        assert sorted(payload.files) == [
            "__checksum__", "__format_version__", "__state_type__",
            "__static__", "leaf0", "leaf1",
        ]
        assert int(payload["__format_version__"]) == 2
        assert str(payload["__state_type__"]) == "MeanState"
        assert float(payload["leaf0"]) == 45.0   # sum
        assert int(payload["leaf1"]) == 10       # count
        # and no pickle sidecar exists anymore
        assert not list(tmp_path.glob("*-treedef.pkl"))

    def test_v1_blob_loads_without_unpickling(self, tmp_path):
        """A round-<=4 v1 blob (positional leaves + a pickle treedef
        sidecar) must load through the analyzer-derived structure with the
        pickle file left UNREAD — a poisoned sidecar cannot execute."""
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        sp = FileSystemStateProvider(str(tmp_path))
        a = Mean("x")
        base = str(tmp_path / sp._key(a))
        np.savez(
            base + "-state.npz",
            __format_version__=np.int64(1),
            leaf0=np.float64(45.0),
            leaf1=np.int64(10),
        )
        with open(base + "-treedef.pkl", "wb") as fh:
            fh.write(b"\x80\x04poisoned pickle that must never be loaded")
        state = sp.load(a)
        assert float(state.total) == 45.0 and int(state.count) == 10

    def test_kll_static_field_round_trip(self, tmp_path):
        """KLLSketchState's static sketch_size survives the v2 registry
        round-trip for non-default parameters."""
        from deequ_tpu.analyzers import KLLParameters, KLLSketch
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        data = Dataset.from_dict({"x": np.arange(5000, dtype=np.float64)})
        a = KLLSketch("x", KLLParameters(sketch_size=512))
        sp = FileSystemStateProvider(str(tmp_path))
        AnalysisRunner.do_analysis_run(data, [a], save_states_with=sp)
        state = sp.load(a)
        assert state.sketch_size == 512
        assert int(state.count) == 5000

    def test_malformed_blobs_fail_loudly(self, tmp_path):
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        sp = FileSystemStateProvider(str(tmp_path))
        a = Mean("x")
        base = str(tmp_path / sp._key(a))
        # unknown state type
        np.savez(
            base + "-state.npz",
            __format_version__=np.int64(2),
            __state_type__=np.str_("EvilState"),
            __static__=np.str_("{}"),
            leaf0=np.float64(1.0),
        )
        with pytest.raises(ValueError, match="not in the reconstruction registry"):
            sp.load(a)
        # wrong leaf count
        np.savez(
            base + "-state.npz",
            __format_version__=np.int64(2),
            __state_type__=np.str_("MeanState"),
            __static__=np.str_("{}"),
            leaf0=np.float64(1.0),
        )
        with pytest.raises(ValueError, match="expected 2"):
            sp.load(a)
        # unknown static field
        np.savez(
            base + "-state.npz",
            __format_version__=np.int64(2),
            __state_type__=np.str_("MeanState"),
            __static__=np.str_('{"bogus": 3}'),
            leaf0=np.float64(1.0),
            leaf1=np.int64(2),
        )
        with pytest.raises(ValueError, match="static fields"):
            sp.load(a)
