"""Holt-Winters numeric parity fixture (VERDICT round-2 item 10): the
model recurrences are pinned against values computed directly from the
reference's update equations (`anomalydetection/seasonal/HoltWinters.scala:
76-124`) on a fixed series, so the scipy L-BFGS-B parameter fit cannot
silently sit on top of a diverged model."""

import numpy as np
import pytest

from deequ_tpu.anomalydetection.seasonal import (
    HoltWinters,
    MetricInterval,
    SeriesSeasonality,
    additive_holt_winters,
)

# a 3-week daily series with weekly shape + mild upward trend
SERIES = [
    52.0, 48.0, 55.0, 60.0, 51.0, 49.0, 58.0,
    54.0, 50.0, 57.0, 63.0, 53.0, 51.0, 60.0,
    56.0, 52.0, 59.0, 65.0, 55.0, 53.0, 62.0,
]

# computed from an independent transliteration of the reference recurrences
# (level/trend/seasonality updates + forecast append) with
# m=7, alpha=0.3, beta=0.1, gamma=0.2, 7 forecast points
GOLDEN_FORECASTS = [
    57.395022792, 53.4093265079, 60.4767359217, 65.949207499,
    56.6337166725, 54.8356517695, 64.0594997361,
]
GOLDEN_SSE = 10.57629367


def _reference_recurrence(series, m, n_forecast, alpha, beta, gamma):
    """Direct transliteration of `HoltWinters.scala:76-124`."""
    first = sum(series[:m])
    second = sum(series[m:2 * m])
    level = [first / m]
    trend = [(second - first) / (m * m)]
    seasonality = [x - level[0] for x in series[:m]]
    y = [level[0] + trend[0] + seasonality[0]]
    big_y = list(series)
    for t in range(len(series) + n_forecast):
        if t >= len(series):
            big_y.append(level[-1] + trend[-1] + seasonality[len(seasonality) - m])
        level.append(alpha * (big_y[t] - seasonality[t]) + (1 - alpha) * (level[t] + trend[t]))
        trend.append(beta * (level[t + 1] - level[t]) + (1 - beta) * trend[t])
        seasonality.append(
            gamma * (big_y[t] - level[t] - trend[t]) + (1 - gamma) * seasonality[t]
        )
        y.append(level[t + 1] + trend[t + 1] + seasonality[t + 1])
    # reference sign convention: seriesValue - modelForecast (`:128-131`)
    residuals = [s - yy for yy, s in zip(y, series)]
    return big_y[len(series):], residuals


class TestRecurrenceParity:
    def test_forecasts_match_pinned_goldens(self):
        result = additive_holt_winters(SERIES, 7, 7, 0.3, 0.1, 0.2)
        assert result.forecasts == pytest.approx(GOLDEN_FORECASTS, abs=1e-9)

    def test_sse_matches_pinned_golden(self):
        result = additive_holt_winters(SERIES, 7, 7, 0.3, 0.1, 0.2)
        sse = sum(r * r for r in result.residuals[: len(SERIES)])
        assert sse == pytest.approx(GOLDEN_SSE, abs=1e-8)

    @pytest.mark.parametrize(
        "alpha,beta,gamma", [(0.3, 0.1, 0.2), (0.9, 0.05, 0.5), (0.1, 0.9, 0.01)]
    )
    def test_matches_reference_recurrence_across_parameters(self, alpha, beta, gamma):
        got = additive_holt_winters(SERIES, 7, 5, alpha, beta, gamma)
        want_f, want_r = _reference_recurrence(SERIES, 7, 5, alpha, beta, gamma)
        assert got.forecasts == pytest.approx(want_f, abs=1e-12)
        assert got.residuals[: len(SERIES)] == pytest.approx(
            want_r[: len(SERIES)], abs=1e-12
        )

    def test_yearly_periodicity(self):
        series = [10.0 + (i % 12) + 0.1 * i for i in range(36)]
        got = additive_holt_winters(series, 12, 12, 0.5, 0.2, 0.3)
        want_f, _ = _reference_recurrence(series, 12, 12, 0.5, 0.2, 0.3)
        assert got.forecasts == pytest.approx(want_f, abs=1e-12)


class TestEndToEndStrategy:
    def test_detects_break_in_seasonal_series(self):
        from deequ_tpu.anomalydetection import DataPoint

        rng = np.random.default_rng(3)
        n = 42
        series = [
            50 + 5 * np.sin(2 * np.pi * (i % 7) / 7) + rng.normal(0, 0.3)
            for i in range(n)
        ]
        series[-1] += 25  # break the pattern on the newest point
        strategy = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        anomalies = strategy.detect(np.asarray(series), (n - 7, n))
        assert any(idx == n - 1 for idx, _ in anomalies)
