"""Test environment: force an 8-device CPU platform BEFORE jax initializes,
so shard-merge tests exercise real multi-device code paths — the analog of
the reference forcing 2 shuffle partitions to push partial-state merges
through cluster code paths (`SparkContextSpec.scala:75-84`)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# deterministic placement: tests exercise the device-stream path by default
# (the host ingest tier has explicit placement="host" tests)
os.environ.setdefault("DEEQU_TPU_PLACEMENT", "device")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")  # env var alone loses to the axon plugin

import numpy as np
import pytest


@pytest.fixture
def df_full():
    """4 complete rows (reference `utils/FixtureSupport.scala getDfFull`)."""
    from deequ_tpu.data import Dataset

    return Dataset.from_dict(
        {
            "item": ["1", "2", "3", "4"],
            "att1": ["a", "b", "a", "a"],
            "att2": ["c", "d", "d", "f"],
        }
    )


@pytest.fixture
def df_missing():
    """12 rows with nulls in att1/att2 (reference `FixtureSupport.getDfMissing`)."""
    import pyarrow as pa

    from deequ_tpu.data import Dataset

    rows = [
        ("1", "a", "f"),
        ("2", "b", "d"),
        ("3", None, "f"),
        ("4", "a", None),
        ("5", "a", "f"),
        ("6", None, "d"),
        ("7", None, "d"),
        ("8", "b", None),
        ("9", "a", "f"),
        ("10", None, None),
        ("11", None, "f"),
        ("12", None, "d"),
    ]
    return Dataset.from_arrow(
        pa.table(
            {
                "item": pa.array([r[0] for r in rows]),
                "att1": pa.array([r[1] for r in rows]),
                "att2": pa.array([r[2] for r in rows]),
            }
        )
    )


@pytest.fixture
def df_numeric():
    """6 rows of numeric values (reference `FixtureSupport.getDfWithNumericValues`)."""
    from deequ_tpu.data import Dataset

    return Dataset.from_dict(
        {
            "item": ["1", "2", "3", "4", "5", "6"],
            "att1": [1, 2, 3, 4, 5, 6],
            "att2": [0, 0, 0, 5, 6, 7],
            "att3": [0, 0, 0, 4, 6, 7],
        }
    )
