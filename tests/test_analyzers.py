"""Per-analyzer golden-value tests vs numpy oracles, incl. null handling —
the analog of the reference `analyzers/AnalyzerTests.scala` and
`analyzers/NullHandlingTests.scala`."""

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    MutualInformation,
    PatternMatch,
    Patterns,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner


def run(data, *analyzers, **kwargs):
    return AnalysisRunner.do_analysis_run(data, list(analyzers), **kwargs)


def value_of(context, analyzer):
    metric = context.metric(analyzer)
    assert metric is not None, f"no metric for {analyzer}"
    assert metric.value.is_success, f"failure: {metric.value}"
    return metric.value.get()


class TestSize:
    def test_size(self, df_missing):
        assert value_of(run(df_missing, Size()), Size()) == 12.0

    def test_size_with_where(self, df_numeric):
        a = Size(where="att1 > 3")
        assert value_of(run(df_numeric, a), a) == 3.0

    def test_size_empty(self):
        data = Dataset.from_dict({"att1": np.array([], dtype=np.float64)})
        assert value_of(run(data, Size()), Size()) == 0.0


class TestCompleteness:
    def test_completeness(self, df_missing):
        ctx = run(df_missing, Completeness("att1"), Completeness("att2"))
        assert value_of(ctx, Completeness("att1")) == pytest.approx(0.5)
        assert value_of(ctx, Completeness("att2")) == pytest.approx(0.75)

    def test_completeness_where(self, df_missing):
        a = Completeness("att2", where="item in ('4', '8', '9')")
        assert value_of(run(df_missing, a), a) == pytest.approx(1.0 / 3)

    def test_fails_on_missing_column(self, df_missing):
        ctx = run(df_missing, Completeness("nope"))
        assert ctx.metric(Completeness("nope")).value.is_failure


class TestNumeric:
    def test_mean(self, df_numeric):
        assert value_of(run(df_numeric, Mean("att1")), Mean("att1")) == pytest.approx(3.5)

    def test_sum(self, df_numeric):
        assert value_of(run(df_numeric, Sum("att1")), Sum("att1")) == pytest.approx(21.0)

    def test_min_max(self, df_numeric):
        ctx = run(df_numeric, Minimum("att1"), Maximum("att1"))
        assert value_of(ctx, Minimum("att1")) == pytest.approx(1.0)
        assert value_of(ctx, Maximum("att1")) == pytest.approx(6.0)

    def test_stddev(self, df_numeric):
        a = StandardDeviation("att1")
        expected = np.std(np.arange(1, 7))  # population stddev
        assert value_of(run(df_numeric, a), a) == pytest.approx(expected, rel=1e-12)

    def test_correlation(self, df_numeric):
        a = Correlation("att2", "att3")
        x = np.array([0, 0, 0, 5, 6, 7], dtype=float)
        y = np.array([0, 0, 0, 4, 6, 7], dtype=float)
        expected = np.corrcoef(x, y)[0, 1]
        assert value_of(run(df_numeric, a), a) == pytest.approx(expected, rel=1e-12)

    def test_correlation_of_column_with_itself(self, df_numeric):
        a = Correlation("att1", "att1")
        assert value_of(run(df_numeric, a), a) == pytest.approx(1.0)

    def test_mean_with_nulls(self):
        data = Dataset.from_dict({"x": [1.0, None, 3.0, None]})
        assert value_of(run(data, Mean("x")), Mean("x")) == pytest.approx(2.0)

    def test_mean_empty_column_is_failure(self):
        data = Dataset.from_dict({"x": [None, None]})
        import pyarrow as pa

        data = Dataset.from_arrow(pa.table({"x": pa.array([None, None], type=pa.float64())}))
        ctx = run(data, Mean("x"))
        assert ctx.metric(Mean("x")).value.is_failure

    def test_fails_on_non_numeric(self, df_full):
        ctx = run(df_full, Mean("att1"))
        assert ctx.metric(Mean("att1")).value.is_failure

    def test_where_filter(self, df_numeric):
        a = Mean("att1", where="att2 > 0")
        assert value_of(run(df_numeric, a), a) == pytest.approx(5.0)


class TestStrings:
    def test_min_max_length(self):
        data = Dataset.from_dict({"s": ["a", "bb", "ccc", None]})
        ctx = run(data, MinLength("s"), MaxLength("s"))
        assert value_of(ctx, MinLength("s")) == 1.0
        assert value_of(ctx, MaxLength("s")) == 3.0

    def test_pattern_match(self):
        data = Dataset.from_dict({"s": ["someone@example.com", "nope", None, "x@y.co"]})
        a = PatternMatch("s", Patterns.EMAIL)
        # nulls stay in the denominator (reference PatternMatch semantics)
        assert value_of(run(data, a), a) == pytest.approx(2.0 / 4)

    def test_compliance(self, df_numeric):
        a = Compliance("rule1", "att1 > 3")
        assert value_of(run(df_numeric, a), a) == pytest.approx(3.0 / 6)
        b = Compliance("rule2", "att1 > 0")
        assert value_of(run(df_numeric, b), b) == pytest.approx(1.0)


class TestDataType:
    def test_datatype_distribution(self):
        data = Dataset.from_dict({"s": ["1", "2.0", "true", "foo", None, "3"]})
        ctx = run(data, DataType("s"))
        dist = value_of(ctx, DataType("s"))
        assert dist["Integral"].absolute == 2
        assert dist["Fractional"].absolute == 1
        assert dist["Boolean"].absolute == 1
        assert dist["String"].absolute == 1
        assert dist["Unknown"].absolute == 1
        assert dist["Integral"].ratio == pytest.approx(2.0 / 6)

    def test_datatype_on_numeric_column(self, df_numeric):
        dist = value_of(run(df_numeric, DataType("att1")), DataType("att1"))
        assert dist["Integral"].absolute == 6


class TestGrouping:
    def test_uniqueness(self, df_missing):
        ctx = run(df_missing, Uniqueness(["att1"]))
        # att1 values: a x4, b x2 over 12 rows -> no group of size 1
        assert value_of(ctx, Uniqueness(["att1"])) == pytest.approx(0.0)

    def test_uniqueness_full(self, df_full):
        ctx = run(df_full, Uniqueness(["item"]))
        assert value_of(ctx, Uniqueness(["item"])) == pytest.approx(1.0)

    def test_distinctness(self, df_full):
        ctx = run(df_full, Distinctness(["att1"]))
        assert value_of(ctx, Distinctness(["att1"])) == pytest.approx(2.0 / 4)

    def test_unique_value_ratio(self, df_full):
        # att2: c:1, d:2, f:1 -> 2 unique of 3 distinct
        a = UniqueValueRatio(["att2"])
        assert value_of(run(df_full, a), a) == pytest.approx(2.0 / 3)

    def test_count_distinct(self, df_full):
        a = CountDistinct(["att1"])
        assert value_of(run(df_full, a), a) == 2.0

    def test_entropy(self, df_full):
        a = Entropy("att1")
        p = np.array([3, 1]) / 4.0
        expected = float(-(p * np.log(p)).sum())
        assert value_of(run(df_full, a), a) == pytest.approx(expected, rel=1e-12)

    def test_entropy_ignores_nulls_in_numerator_but_not_total(self, df_missing):
        # att1: a x4, b x2, 6 nulls; N = 12 (reference Entropy uses numRows)
        a = Entropy("att1")
        expected = -(4 / 12 * np.log(4 / 12) + 2 / 12 * np.log(2 / 12))
        assert value_of(run(df_missing, a), a) == pytest.approx(expected, rel=1e-12)

    def test_multi_column_uniqueness(self, df_full):
        a = Uniqueness(["att1", "att2"])
        assert value_of(run(df_full, a), a) == pytest.approx(1.0)

    def test_mutual_information(self, df_full):
        a = MutualInformation(["att1", "att2"])
        # joint: (a,c):1 (b,d):1 (a,d):1 (a,f):1 over N=4
        # px: a=3/4 b=1/4 ; py: c=1/4 d=2/4 f=1/4
        val = 0.0
        joint = {("a", "c"): 1, ("b", "d"): 1, ("a", "d"): 1, ("a", "f"): 1}
        px = {"a": 3 / 4, "b": 1 / 4}
        py = {"c": 1 / 4, "d": 2 / 4, "f": 1 / 4}
        for (x, y), c in joint.items():
            pxy = c / 4
            val += pxy * np.log(pxy / (px[x] * py[y]))
        assert value_of(run(df_full, a), a) == pytest.approx(val, rel=1e-12)

    def test_mutual_information_wrong_column_count(self, df_full):
        ctx = run(df_full, MutualInformation(["att1"]))
        assert ctx.metric(MutualInformation(["att1"])).value.is_failure


class TestHistogram:
    def test_histogram(self, df_full):
        a = Histogram("att1")
        dist = value_of(run(df_full, a), a)
        assert dist.number_of_bins == 2
        assert dist["a"].absolute == 3
        assert dist["a"].ratio == pytest.approx(0.75)

    def test_histogram_nulls_become_nullvalue(self, df_missing):
        a = Histogram("att1")
        dist = value_of(run(df_missing, a), a)
        assert dist["NullValue"].absolute == 6
        assert dist.number_of_bins == 3

    def test_histogram_with_binning(self):
        data = Dataset.from_dict({"x": [1, 2, 3, 4, 5, 6]})
        a = Histogram("x", binning_func=lambda v: "low" if v <= 3 else "high")
        dist = value_of(run(data, a), a)
        assert dist["low"].absolute == 3
        assert dist["high"].absolute == 3

    def test_histogram_numeric_formatting(self):
        data = Dataset.from_dict({"x": [1.0, 1.0, 2.5]})
        a = Histogram("x")
        dist = value_of(run(data, a), a)
        assert dist["1.0"].absolute == 2
        assert dist["2.5"].absolute == 1


class TestBatchInvariance:
    """Metrics must be identical regardless of batch partitioning — the
    shard-merge = full-recompute equivalence property (SURVEY §4c)."""

    @pytest.mark.parametrize("batch_size", [1, 2, 3, 7, 64])
    def test_batch_size_invariance(self, batch_size):
        rng = np.random.default_rng(0)
        n = 37
        data = Dataset.from_dict(
            {
                "x": rng.normal(size=n),
                "y": rng.normal(size=n),
                "s": [f"v{i % 5}" for i in range(n)],
            }
        )
        analyzers = [
            Size(),
            Mean("x"),
            Sum("x"),
            Minimum("x"),
            Maximum("x"),
            StandardDeviation("x"),
            Correlation("x", "y"),
            Completeness("s"),
            Uniqueness(["s"]),
            Entropy("s"),
        ]
        full = AnalysisRunner.do_analysis_run(data, analyzers, batch_size=64)
        batched = AnalysisRunner.do_analysis_run(data, analyzers, batch_size=batch_size)
        for a in analyzers:
            v1 = full.metric(a).value.get()
            v2 = batched.metric(a).value.get()
            assert v1 == pytest.approx(v2, rel=1e-9), f"{a} differs across batchings"
