"""The continuous verification service: scheduler semantics (priorities,
deadlines, typed retry, admission control), the ≥50-job fault-injection
soak, streaming micro-batch sessions with algebraic-state parity, the
cache-aware placement router, and the Prometheus/JSON export plane."""

import json
import threading
import time
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.service import (
    JobFailed,
    JobScheduler,
    JobTimeout,
    MetricsExporter,
    PlacementRouter,
    Priority,
    ServiceClosed,
    ServiceMetrics,
    ServiceOverloaded,
    SessionClosed,
    TransientFailure,
    VerificationService,
    battery_signature,
)


class TestSchedulerSemantics:
    def test_priority_classes_strict_order(self):
        sched = JobScheduler(workers=1, max_queue_depth=16)
        gate = threading.Event()
        order = []
        sched.submit(lambda ctx: gate.wait(30))  # occupy the only worker
        time.sleep(0.05)  # let the worker take the blocker
        handles = [
            sched.submit(lambda ctx: order.append("low"), priority=Priority.LOW),
            sched.submit(lambda ctx: order.append("normal"), priority=Priority.NORMAL),
            sched.submit(lambda ctx: order.append("high"), priority=Priority.HIGH),
        ]
        gate.set()
        for h in handles:
            h.result(30)
        assert order == ["high", "normal", "low"]
        sched.shutdown()

    def test_deadline_in_queue_is_typed_timeout_without_running(self):
        sched = JobScheduler(workers=1, max_queue_depth=16)
        gate = threading.Event()
        ran = []
        sched.submit(lambda ctx: gate.wait(30))
        time.sleep(0.05)
        h = sched.submit(lambda ctx: ran.append(1), deadline_s=0.01)
        time.sleep(0.1)  # deadline passes while queued
        gate.set()
        with pytest.raises(JobTimeout):
            h.result(30)
        assert ran == []  # the run was never wasted
        sched.shutdown()

    def test_deadline_during_execution_is_typed_timeout(self):
        sched = JobScheduler(workers=1, max_queue_depth=16)
        h = sched.submit(lambda ctx: time.sleep(0.1), deadline_s=0.02)
        with pytest.raises(JobTimeout) as exc_info:
            h.result(30)
        assert exc_info.value.deadline_s == 0.02
        sched.shutdown()

    def test_transient_failure_retries_with_backoff_then_succeeds(self):
        sched = JobScheduler(workers=2, max_queue_depth=16)
        attempts = []

        def flaky(ctx):
            attempts.append((ctx.attempt, time.monotonic()))
            if ctx.attempt < 3:
                raise TransientFailure("injected")
            return "done"

        h = sched.submit(flaky, max_retries=3, retry_backoff_s=0.02)
        assert h.result(30) == "done"
        assert h.attempts == 3
        # exponential backoff: gap 2 >= 2x base, after gap 1 >= base
        gaps = [attempts[i + 1][1] - attempts[i][1] for i in range(2)]
        assert gaps[0] >= 0.02 and gaps[1] >= 0.04
        assert sched.metrics.counter_value("deequ_service_job_retries_total") == 2
        sched.shutdown()

    def test_exhausted_retries_become_job_failed_with_cause(self):
        sched = JobScheduler(workers=1, max_queue_depth=16)

        def always_flaky(ctx):
            raise TransientFailure("still down")

        h = sched.submit(always_flaky, max_retries=2, retry_backoff_s=0.001)
        with pytest.raises(JobFailed) as exc_info:
            h.result(30)
        assert isinstance(exc_info.value.__cause__, TransientFailure)
        assert h.attempts == 3  # 1 try + 2 retries
        sched.shutdown()

    def test_non_retryable_error_fails_fast(self):
        sched = JobScheduler(workers=1, max_queue_depth=16)

        def broken(ctx):
            raise ValueError("bad battery")

        h = sched.submit(broken, max_retries=5)
        with pytest.raises(JobFailed) as exc_info:
            h.result(30)
        assert h.attempts == 1  # no retry burned on a permanent error
        assert isinstance(exc_info.value.__cause__, ValueError)
        sched.shutdown()

    def test_retry_on_registers_extra_transient_types(self):
        sched = JobScheduler(workers=1, max_queue_depth=16)
        attempts = []

        def conn_flaky(ctx):
            attempts.append(ctx.attempt)
            if ctx.attempt == 1:
                raise ConnectionError("reset")
            return "ok"

        h = sched.submit(
            conn_flaky, max_retries=2, retry_backoff_s=0.001,
            retry_on=(ConnectionError,),
        )
        assert h.result(30) == "ok" and attempts == [1, 2]
        sched.shutdown()

    def test_admission_control_sheds_typed(self):
        sched = JobScheduler(workers=1, max_queue_depth=2)
        gate = threading.Event()
        sched.submit(lambda ctx: gate.wait(30))
        time.sleep(0.05)
        sched.submit(lambda ctx: None)
        sched.submit(lambda ctx: None)
        with pytest.raises(ServiceOverloaded) as exc_info:
            sched.submit(lambda ctx: None)
        assert exc_info.value.max_queue_depth == 2
        assert sched.metrics.counter_value("deequ_service_jobs_shed_total") == 1
        gate.set()
        sched.shutdown()

    def test_affinity_never_reorders_same_serial_key(self):
        """Worker affinity must not promote a later same-serial-key entry
        past an earlier sibling (FIFO per key beats warm-worker routing)."""
        sched = JobScheduler(workers=1, max_queue_depth=16)
        sig1 = battery_signature([Mean("aff_fifo_col_1")])
        sig2 = battery_signature([Mean("aff_fifo_col_2")])
        # the lone worker 0 is warm for the SECOND job's battery
        sched.router.note_ran(sig2, 0, placement="device")
        gate = threading.Event()
        order = []
        sched.submit(lambda ctx: gate.wait(30))
        time.sleep(0.05)
        h1 = sched.submit(
            lambda ctx: order.append(1), signature=sig1, serial_key="k"
        )
        h2 = sched.submit(
            lambda ctx: order.append(2), signature=sig2, serial_key="k"
        )
        gate.set()
        h1.result(30)
        h2.result(30)
        assert order == [1, 2], "affinity must not break per-key FIFO"
        sched.shutdown()

    def test_retry_keeps_serial_key_fifo(self):
        """A retried serialized job must not let a later-submitted sibling
        with the same key overtake it during the backoff (streaming: batch
        N's retry must fold before batch N+1)."""
        sched = JobScheduler(workers=2, max_queue_depth=16)
        order = []

        def job_a(ctx):
            if ctx.attempt == 1:
                raise TransientFailure("flake")
            order.append("A")
            return "A"

        def job_b(ctx):
            order.append("B")
            return "B"

        ha = sched.submit(job_a, serial_key="s", max_retries=2,
                          retry_backoff_s=0.05)
        hb = sched.submit(job_b, serial_key="s")
        assert ha.result(30) == "A" and hb.result(30) == "B"
        assert order == ["A", "B"], "retry must complete before the sibling"
        sched.shutdown()

    def test_submit_after_shutdown_is_typed(self):
        sched = JobScheduler(workers=1, max_queue_depth=2)
        sched.shutdown()
        with pytest.raises(ServiceClosed):
            sched.submit(lambda ctx: None)

    def test_completed_late_job_keeps_result_reachable(self):
        """A job that FINISHES past its deadline has committed its side
        effects; the typed timeout must say so (completed=True) and the
        result must stay reachable on the handle."""
        sched = JobScheduler(workers=1, max_queue_depth=16)

        def late(ctx):
            time.sleep(0.05)
            return "committed"

        h = sched.submit(late, deadline_s=0.01)
        with pytest.raises(JobTimeout) as exc_info:
            h.result(30)
        assert exc_info.value.completed is True
        assert h.late_value == "committed"
        sched.shutdown()

    def test_streaming_late_fold_returns_committed_result(self):
        """ingest() must hand back the committed fold when it completes
        past the deadline — raising would bait a double-counting retry."""
        service = VerificationService(workers=1, background_warm=False)
        slow_gate = threading.Event()

        def slow_callback(result):
            time.sleep(0.08)  # push the fold past its deadline
            slow_gate.set()

        session = service.session(
            "a", "late", [Check(CheckLevel.ERROR, "c")],
            required_analyzers=[Size()], on_result=slow_callback,
        )
        data = Dataset.from_dict({"id": np.arange(50)})
        result = session.ingest(data, deadline_s=0.05)
        assert result.metrics[Size()].value.get() == 50.0
        assert slow_gate.is_set()
        assert session.current().metrics[Size()].value.get() == 50.0
        service.close()


def _soak_data(seed: int) -> Dataset:
    rng = np.random.default_rng(seed)
    return Dataset.from_dict(
        {"id": np.arange(64) + seed * 1000, "v": rng.normal(0, 1, 64)}
    )


class TestSoak:
    """≥50 concurrent jobs, mixed priorities, injected timeouts and
    transient failures: every job terminates with a result or a typed
    error, the queue stays bounded, and the export plane reconciles with
    the observed outcomes (ISSUE acceptance criterion 3)."""

    WORKERS = 4
    MAX_DEPTH = 12
    TARGET_ACCEPTED = 56

    def test_soak(self):
        service = VerificationService(
            workers=self.WORKERS, max_queue_depth=self.MAX_DEPTH,
            background_warm=False,
        )
        sched = service.scheduler
        check = Check(CheckLevel.ERROR, "soak").is_complete("id")
        priorities = [Priority.HIGH, Priority.NORMAL, Priority.LOW]

        max_pending = 0
        stop_sampling = threading.Event()

        def sample_depth():
            nonlocal max_pending
            while not stop_sampling.is_set():
                max_pending = max(max_pending, sched.pending())
                time.sleep(0.001)

        sampler = threading.Thread(target=sample_depth, daemon=True)
        sampler.start()

        def sleepy(ctx):
            time.sleep(0.005)
            return "slept"

        def transient_once(ctx):
            if ctx.attempt == 1:
                raise TransientFailure("injected flake")
            return "recovered"

        def transient_always(ctx):
            raise TransientFailure("injected permanent flake")

        def crashy(ctx):
            raise RuntimeError("injected crash")

        def slow(ctx):  # blows its deadline DURING execution
            time.sleep(0.05)
            return "too late"

        handles = []  # (handle, expected_outcome)
        shed = 0
        i = 0
        deadline = time.monotonic() + 60
        while len(handles) < self.TARGET_ACCEPTED and time.monotonic() < deadline:
            kind = i % 6
            prio = priorities[i % 3]
            i += 1
            try:
                if kind == 0:
                    h = service.submit_verification(
                        _soak_data(i), [check], tenant=f"t{i % 3}", priority=prio
                    )
                    expect = "success"
                elif kind == 1:
                    h = sched.submit(sleepy, priority=prio, tenant=f"t{i % 3}")
                    expect = "success"
                elif kind == 2:
                    h = sched.submit(
                        transient_once, priority=prio, max_retries=2,
                        retry_backoff_s=0.002,
                    )
                    expect = "success"
                elif kind == 3:
                    h = sched.submit(
                        transient_always, priority=prio, max_retries=1,
                        retry_backoff_s=0.002,
                    )
                    expect = "failed"
                elif kind == 4:
                    h = sched.submit(crashy, priority=prio)
                    expect = "failed"
                else:
                    h = sched.submit(slow, priority=prio, deadline_s=0.02)
                    expect = "timeout"
                handles.append((h, expect))
            except ServiceOverloaded:
                shed += 1
                time.sleep(0.002)  # back off like a real client

        assert len(handles) >= 50, "soak must push >=50 admitted jobs"

        outcomes = {"success": 0, "failed": 0, "timeout": 0}
        for h, expect in handles:
            # every handle terminates: a result or a TYPED service error
            try:
                h.result(timeout=120)
                outcome = "success"
            except JobTimeout:
                outcome = "timeout"
            except JobFailed:
                outcome = "failed"
            outcomes[outcome] += 1
            assert outcome == expect, (h.job_id, outcome, expect)
        stop_sampling.set()
        sampler.join(5)

        # queue depth stayed bounded by the DOCUMENTED invariant: admission
        # holds pending <= max depth, and only retries of jobs concurrently
        # claimed by workers may transiently exceed it — a batched pickup
        # (_PICK_BATCH per worker per lock round-trip) frees slots that new
        # admissions may take before a claimed job's retry re-enters, so
        # the provable bound is max_depth + workers * _PICK_BATCH (the
        # scheduler module docstring derives it; the old `+ workers` bound
        # ignored batched pickup and flaked 2-of-3 under load)
        from deequ_tpu.service.scheduler import _PICK_BATCH

        assert max_pending <= self.MAX_DEPTH + self.WORKERS * _PICK_BATCH
        assert sched.pending() == 0

        # the export plane reconciles with what we observed
        m = service.metrics
        assert m.counter_value("deequ_service_jobs_submitted_total") == len(handles)
        assert m.counter_value("deequ_service_jobs_shed_total") == shed
        assert shed > 0, "the soak must actually drive admission control"
        for outcome, count in outcomes.items():
            got = sum(
                v
                for (name, labels), v in m._counters.items()
                if name == "deequ_service_jobs_completed_total"
                and ("outcome", outcome) in labels
            )
            assert got == count, (outcome, got, count)
        # retries: at least one per recovered transient_once job
        n_once = sum(
            1 for (h, e) in handles if e == "success" and h.attempts == 2
        )
        assert m.counter_value("deequ_service_job_retries_total") >= n_once
        # phase timings flowed from RunMonitor into the plane
        snapshot = m.json_snapshot()
        phases = snapshot["counters"].get("deequ_service_phase_seconds_total", {})
        assert phases, "verification jobs must export phase timings"
        verif = [h for (h, e) in handles if e == "success" and h.phase_seconds]
        assert verif, "successful verification jobs carry per-job phase timers"
        assert snapshot["gauges"]["deequ_service_queue_depth"] == 0
        service.close()


class TestStreamingSession:
    def _batch(self, seed: int, rows: int = 200) -> Dataset:
        rng = np.random.default_rng(seed)
        return Dataset.from_dict(
            {
                "id": np.arange(rows) + seed * 10_000,
                "v": rng.normal(10.0, 2.0, rows),
                "cat": np.array(["a", "b", "c", "d"])[rng.integers(0, 4, rows)],
            }
        )

    ANALYZERS = ()

    def _analyzers(self):
        return [
            Size(), Completeness("v"), Mean("v"), Sum("v"), Minimum("v"),
            Maximum("v"), StandardDeviation("v"), Uniqueness(["id"]),
            ApproxCountDistinct("cat"),
        ]

    def test_three_microbatches_equal_one_concatenated_run(self):
        """ISSUE acceptance criterion 4: algebraic-state parity, with
        checks evaluated after every merge."""
        from deequ_tpu.verification import VerificationSuite

        batches = [self._batch(s) for s in (1, 2, 3)]
        # cumulative size check: fails exactly on the third merge, proving
        # checks run against the MERGED states after every batch
        check = Check(CheckLevel.ERROR, "bounded growth").has_size(
            lambda n: n <= 450
        )
        service = VerificationService(workers=2, background_warm=False)
        session = service.session(
            "tenant-x", "events", [check], required_analyzers=self._analyzers()
        )
        statuses = [session.ingest(b).status for b in batches]
        assert statuses == [
            CheckStatus.SUCCESS, CheckStatus.SUCCESS, CheckStatus.ERROR,
        ], "the size breach must surface mid-stream on the third merge"
        assert session.batches_ingested == 3
        assert session.rows_ingested == 600
        assert len(session.results) == 3

        concat = Dataset.from_arrow(
            pa.concat_tables([b.arrow for b in batches])
        )
        single = VerificationSuite.do_verification_run(
            concat, [check], self._analyzers()
        )
        streamed = session.results[-1]
        assert streamed.status == single.status == CheckStatus.ERROR

        single_metrics = {str(a): m for a, m in single.metrics.items()}
        streamed_metrics = {str(a): m for a, m in streamed.metrics.items()}
        assert set(single_metrics) == set(streamed_metrics)
        for name, metric in single_metrics.items():
            want = metric.value.get()
            got = streamed_metrics[name].value.get()
            assert got == pytest.approx(want, rel=1e-9, abs=1e-12), name

        # the state-only re-evaluation agrees too (no data pass)
        current = session.current()
        cur_metrics = {str(a): m for a, m in current.metrics.items()}
        for name in single_metrics:
            assert cur_metrics[name].value.get() == pytest.approx(
                single_metrics[name].value.get(), rel=1e-9, abs=1e-12
            ), name
        service.close()

    def test_session_get_or_create_and_close(self):
        service = VerificationService(workers=1, background_warm=False)
        s1 = service.session("a", "d1", [Check(CheckLevel.ERROR, "c")])
        s2 = service.session("a", "d1")
        assert s1 is s2
        other = service.session("b", "d1")
        assert other is not s1  # tenants are isolated
        s1.close()
        with pytest.raises(SessionClosed):
            s1.ingest(self._batch(1))
        # a bare GET of a closed session must not silently recreate it
        # with zero checks and empty state
        with pytest.raises(SessionClosed):
            service.session("a", "d1")
        s3 = service.session("a", "d1", [Check(CheckLevel.ERROR, "c")])
        assert s3 is not s1  # explicit recreation with checks is fine
        service.close()

    def test_pipelined_ingests_fold_in_order_and_spare_the_pool(self):
        """Scheduler-level serial keys: one session's pipelined folds run
        one at a time IN SUBMISSION ORDER (per-batch anomaly attribution)
        and occupy one worker, so other tenants' jobs still run."""
        service = VerificationService(workers=2, max_queue_depth=32,
                                      background_warm=False)
        session = service.session(
            "a", "ordered", [Check(CheckLevel.ERROR, "c")],
            required_analyzers=[Size()],
        )
        handles = [
            session.ingest(
                Dataset.from_dict({"id": np.arange(25) + i * 25}), wait=False
            )
            for i in range(4)
        ]
        # with 2 workers and 4 serialized folds pending, another tenant's
        # job still gets a worker promptly
        other = service.scheduler.submit(lambda ctx: "ran", tenant="b")
        assert other.result(30) == "ran"
        results = [h.result(120) for h in handles]
        # folds applied in submission order: cumulative sizes are monotone
        sizes = [r.metrics[Size()].value.get() for r in results]
        assert sizes == [25.0, 50.0, 75.0, 100.0]
        service.close()

    def test_detached_warm_sample_copies_one_row(self):
        """The warm closure must not pin the parent table's buffers."""
        from deequ_tpu.runners.engine import detached_warm_sample

        data = Dataset.from_dict(
            {
                "v": np.arange(1000, dtype=np.float64),
                "cat": np.array(["a", "b"] * 500),
            }
        )
        sample = detached_warm_sample(data)
        assert sample.num_rows == 1
        assert sample.schema.names == data.schema.names
        # deep copy: the sample's value buffer is NOT the parent's
        parent_buf = data.arrow["v"].chunk(0).buffers()[1]
        sample_buf = sample.arrow["v"].chunk(0).buffers()[1]
        assert sample_buf.address != parent_buf.address
        # dictionary encoding (and the full dictionary) survives: the warm
        # battery's device-frequency planning depends on it
        assert sample.dictionary_size("cat") == data.dictionary_size("cat")

    def test_batch_size_buckets_to_powers_of_two(self):
        """Variable-size micro-batches must converge on a bounded set of
        padded shapes (jit compiles per shape); raw row counts would
        compile a fresh program per distinct size."""
        from deequ_tpu.service.streaming import _bucket_batch_size

        assert _bucket_batch_size(1) == 1024  # floor
        assert _bucket_batch_size(500) == 1024
        assert _bucket_batch_size(1024) == 1024
        assert _bucket_batch_size(1025) == 2048
        assert _bucket_batch_size(800_000) == 1 << 20

    def test_session_batch_size_clamps_to_engine_default(self):
        """An oversize micro-batch must stream as engine-sized batches, not
        one giant one-off padded shape."""
        from deequ_tpu.config import DEFAULT_BATCH_SIZE
        from deequ_tpu.service.streaming import _session_batch_size

        assert _session_batch_size(5_000_000, None) == DEFAULT_BATCH_SIZE
        assert _session_batch_size(500, None) == 1024
        assert _session_batch_size(5_000_000, 4096) == 4096

    def test_variable_size_batches_share_bucket_shapes(self):
        """500-, 800- and 650-row batches all fold at the same padded
        shape, and parity vs the concatenated run still holds."""
        from deequ_tpu.verification import VerificationSuite

        service = VerificationService(workers=1, background_warm=False)
        session = service.session(
            "a", "varsize", [Check(CheckLevel.ERROR, "c")],
            required_analyzers=[Size(), Mean("v")],
        )
        tables = []
        for i, rows in enumerate((500, 800, 650)):
            batch = self._batch(i + 1, rows=rows)
            tables.append(batch.arrow)
            session.ingest(batch)
        concat = Dataset.from_arrow(pa.concat_tables(tables))
        single = VerificationSuite.do_verification_run(
            concat, [Check(CheckLevel.ERROR, "c")], [Size(), Mean("v")]
        )
        assert session.latest.metrics[Size()].value.get() == 1950.0
        assert session.latest.metrics[Mean("v")].value.get() == pytest.approx(
            single.metrics[Mean("v")].value.get(), rel=1e-9
        )
        service.close()

    def test_pipelined_ingests_get_distinct_job_ids(self):
        service = VerificationService(workers=1, background_warm=False)
        session = service.session(
            "a", "pipe", [Check(CheckLevel.ERROR, "c")],
            required_analyzers=[Size()],
        )
        h1 = session.ingest(self._batch(1, rows=50), wait=False)
        h2 = session.ingest(self._batch(2, rows=50), wait=False)
        assert h1.job_id != h2.job_id
        h1.result(120)
        h2.result(120)
        assert session.batches_ingested == 2
        service.close()

    def test_current_before_ingest_raises(self):
        service = VerificationService(workers=1, background_warm=False)
        session = service.session("a", "empty", [Check(CheckLevel.ERROR, "c")])
        with pytest.raises(ValueError, match="no ingested batches"):
            session.current()
        service.close()

    def test_callback_failure_never_discards_the_committed_fold(self):
        """By the time on_result runs, the batch is already merged into
        the persisted states: a callback error must be contained (logged +
        counted), never fail the job — a JobFailed would bait the caller
        into a double-counting re-ingest of a committed batch."""
        calls = []

        def flaky_callback(result):
            calls.append(result)
            raise TransientFailure("injected downstream flake")

        service = VerificationService(workers=1, background_warm=False)
        session = service.session(
            "a", "refold", [Check(CheckLevel.ERROR, "c")],
            required_analyzers=[Size()],
            on_result=flaky_callback, max_retries=2,
        )
        result = session.ingest(self._batch(1, rows=100))  # must not raise
        assert result.metrics[Size()].value.get() == 100.0
        assert session.batches_ingested == 1
        assert len(calls) == 1  # delivery attempted once, failure contained
        assert service.metrics.counter_value(
            "deequ_service_callback_failures_total"
        ) == 1
        final = session.current()
        assert final.metrics[Size()].value.get() == 100.0, "batch double-counted"
        service.close()

    def test_session_namespaces_are_unambiguous(self, tmp_path):
        """('team/a', 'x') and ('team', 'a/x') must not share one state
        directory — '/' inside a component is escaped before joining."""
        service = VerificationService(
            workers=1, background_warm=False, state_root=str(tmp_path)
        )
        check = Check(CheckLevel.ERROR, "c")
        s1 = service.session("team/a", "x", [check])
        s2 = service.session("team", "a/x", [check])
        assert s1.provider.path != s2.provider.path
        # empty components must stay distinct too: ("", "x") vs ("x", "")
        s3 = service.session("", "x", [check])
        s4 = service.session("x", "", [check])
        assert s3.provider.path != s4.provider.path
        service.close()

    def test_filesystem_backed_session_namespacing(self, tmp_path):
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        root = str(tmp_path)
        service = VerificationService(
            workers=1, background_warm=False, state_root=root
        )
        check = Check(CheckLevel.ERROR, "c").is_complete("v")
        s_a = service.session("team/alpha", "ds", [check])
        s_b = service.session("team/beta", "ds", [check])
        assert isinstance(s_a.provider, FileSystemStateProvider)
        assert s_a.provider.path != s_b.provider.path
        r1 = s_a.ingest(self._batch(1))
        assert r1.status == CheckStatus.SUCCESS
        # the same analyzer persisted by another tenant lands elsewhere
        s_b.ingest(self._batch(2))
        analyzer = Completeness("v")
        assert s_a.provider.load(analyzer) is not None
        assert s_b.provider.load(analyzer) is not None
        service.close()


class TestPlacementRouter:
    def test_cold_battery_routes_host_then_warm_routes_device(self):
        from deequ_tpu.runners.engine import (
            fused_program_is_cached,
            warm_fused_program,
        )

        metrics = ServiceMetrics()
        router = PlacementRouter(metrics, background_warm=False)
        battery = battery_signature([Mean("router_cold_col_xyz")])
        data = Dataset.from_dict(
            {"router_cold_col_xyz": np.arange(32, dtype=np.float64)}
        )
        assert not fused_program_is_cached(battery)
        assert router.decide(battery) == "host"
        assert metrics.counter_value(
            "deequ_service_placement_cache_misses_total"
        ) == 1
        # a data-aware warm runs the real pipeline -> the program EXECUTED
        warm_fused_program(battery, data=data)
        assert fused_program_is_cached(battery)
        assert router.decide(battery) is None
        assert metrics.counter_value(
            "deequ_service_placement_cache_hits_total"
        ) == 1
        router.close()

    def test_construction_alone_is_not_warm(self):
        """jax.jit compiles lazily: building the program object must not
        count as warm, or the 'warm' job would pay the cold compile in the
        request path (code-review finding)."""
        from deequ_tpu.runners.engine import (
            _fused_program,
            fused_program_is_cached,
        )

        battery = battery_signature([Mean("router_lazy_col_def")])
        _fused_program(battery, None)  # constructed, never dispatched
        assert not fused_program_is_cached(battery)

    def test_host_placement_run_does_not_fake_device_warmth(self):
        """A host-tier run never dispatches the fused device program; it
        must not register the battery as device-warm."""
        from deequ_tpu.runners import AnalysisRunner
        from deequ_tpu.runners.engine import fused_program_is_cached

        analyzer = Mean("router_hostrun_col_ghi")
        battery = battery_signature([analyzer])
        data = Dataset.from_dict(
            {"router_hostrun_col_ghi": np.arange(64, dtype=np.float64)}
        )
        AnalysisRunner.do_analysis_run(data, [analyzer], placement="host")
        assert not fused_program_is_cached(battery)
        AnalysisRunner.do_analysis_run(data, [analyzer], placement="device")
        assert fused_program_is_cached(battery)

    def test_background_warmer_closes_cold_window(self):
        from deequ_tpu.runners.engine import (
            fused_program_is_cached,
            warm_fused_program,
        )

        metrics = ServiceMetrics()
        router = PlacementRouter(metrics, background_warm=True)
        battery = battery_signature([Mean("router_warmer_col_abc")])
        data = Dataset.from_dict(
            {"router_warmer_col_abc": np.arange(32, dtype=np.float64)}
        )
        # cold now; the job-provided warm (as the service wires it) queues
        assert router.decide(
            battery, warm=lambda: warm_fused_program(battery, data=data)
        ) == "host"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if fused_program_is_cached(battery):
                break
            time.sleep(0.01)
        assert fused_program_is_cached(battery)
        assert router.decide(battery) is None
        router.close()

    def test_program_cache_single_instance_under_races(self):
        """Concurrent workers + warmer racing on one battery must share ONE
        PackedScanProgram — a losing duplicate (executed=False) overwriting
        the winner would read as cold forever."""
        from deequ_tpu.runners.engine import _fused_program

        battery = battery_signature([Mean("router_race_col_rr")])
        results = []
        barrier = threading.Barrier(6)

        def build():
            barrier.wait()
            results.append(_fused_program(battery, None))

        threads = [threading.Thread(target=build) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert len({id(p) for p in results}) == 1

    def test_warm_run_bypasses_device_feature_cache(self, monkeypatch):
        """The warm run's throwaway padded sample must not occupy (or evict
        from) the production device-feature-cache budget."""
        import deequ_tpu.runners.engine as eng

        monkeypatch.setenv(eng.DEVICE_FEATURE_CACHE_ENV, "1")
        eng.clear_device_feature_cache()
        try:
            data = Dataset.from_dict(
                {"router_warmcache_col": np.arange(64, dtype=np.float64)}
            )
            eng.warm_fused_program(
                battery_signature([Mean("router_warmcache_col")]), data=data
            )
            cache = eng._DEVICE_FEATURE_CACHE
            assert cache is None or not cache.store
        finally:
            eng.clear_device_feature_cache()

    def test_empty_signature_is_neutral(self):
        router = PlacementRouter(ServiceMetrics(), background_warm=False)
        assert router.decide(()) is None
        router.close()

    def test_ran_signature_counts_warm_despite_cache_key_drift(self):
        """The engine's real program key can include run-time additions
        (device-frequency scans) the signature cannot see; once a job with
        a signature has RUN, the router must report warm instead of
        routing every future job to the host tier forever."""
        from deequ_tpu.runners.engine import fused_program_is_cached

        metrics = ServiceMetrics()
        router = PlacementRouter(metrics, background_warm=False)
        sig = battery_signature([Mean("router_ran_col_qq")])
        assert not fused_program_is_cached(sig)
        # a HOST-tier run never compiled the device program: not warmth
        router.note_ran(sig, worker_id=0, placement="host")
        assert not router.is_warm(sig)
        # a DEVICE-tier run did: its dispatch compiled whatever it needed
        router.note_ran(sig, worker_id=0, placement="device")
        assert router.is_warm(sig)
        assert router.decide(sig) is None
        assert metrics.counter_value(
            "deequ_service_placement_cache_hits_total"
        ) == 1
        router.close()

    def test_close_drains_pipelined_ingests_before_closing_sessions(self):
        service = VerificationService(workers=1, max_queue_depth=16,
                                      background_warm=False)
        session = service.session(
            "a", "drain", [Check(CheckLevel.ERROR, "c")],
            required_analyzers=[Size()],
        )
        handles = [
            session.ingest(
                Dataset.from_dict({"id": np.arange(20) + i * 20}), wait=False
            )
            for i in range(3)
        ]
        service.close()  # must fold all queued batches, not SessionClosed them
        for h in handles:
            h.result(1)  # already done; typed error would raise here
        assert session.batches_ingested == 3

    def test_exporter_rebind_conflict_raises(self):
        service = VerificationService(workers=1, background_warm=False)
        exp = service.start_exporter()
        assert service.start_exporter() is exp  # idempotent default
        assert service.start_exporter(port=exp.port) is exp
        with pytest.raises(ValueError, match="already bound"):
            service.start_exporter(port=exp.port + 1)
        service.close()

    def test_generator_checks_are_not_silently_consumed(self):
        """A one-shot iterable of checks must not be exhausted by the
        signature walk, leaving a job that vacuously succeeds."""
        service = VerificationService(workers=1, background_warm=False)
        data = Dataset.from_dict({"id": [1, None, 3]})
        checks_gen = (
            c for c in [Check(CheckLevel.ERROR, "gen").is_complete("id")]
        )
        result = service.verify(data, checks_gen, timeout=120)
        assert result.status == CheckStatus.ERROR  # the check actually ran
        assert len(result.check_results) == 1
        service.close()

    def test_namespace_sanitizer_is_injective(self):
        from deequ_tpu.analyzers.state_provider import _sanitize_namespace_part

        assert _sanitize_namespace_part("a*b") != _sanitize_namespace_part("a_2ab")
        # multi-byte codepoints escape per UTF-8 byte at fixed width, so
        # '€' (0x20ac) cannot collide with ' ac' (0x20 + literal "ac")
        assert _sanitize_namespace_part("€") != _sanitize_namespace_part(" ac")
        assert _sanitize_namespace_part("..") not in (".", "..")
        assert _sanitize_namespace_part(".") not in (".", "..")
        assert _sanitize_namespace_part("safe-name.v1") == "safe-name.v1"
        # uppercase escapes, so "Team" vs "team" stay distinct even on
        # case-insensitive filesystems (macOS APFS, Windows)
        team_upper = _sanitize_namespace_part("Team")
        assert team_upper != _sanitize_namespace_part("team")
        assert team_upper == team_upper.lower()

    def test_empty_namespace_segments_stay_distinct(self, tmp_path):
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        root = str(tmp_path)
        a = FileSystemStateProvider(root, namespace="a//b")
        b = FileSystemStateProvider(root, namespace="a/b")
        assert a.path != b.path

    def test_session_results_are_bounded(self):
        service = VerificationService(workers=1, background_warm=False)
        session = service.session(
            "a", "bounded", [Check(CheckLevel.ERROR, "c")],
            required_analyzers=[Size()], keep_results=2,
        )
        for i in range(4):
            session.ingest(Dataset.from_dict({"id": np.arange(10) + i * 10}))
        assert session.batches_ingested == 4
        assert len(session.results) == 2  # only the freshest results kept
        assert session.latest.metrics[Size()].value.get() == 40.0
        service.close()

    def test_aged_out_warmth_reads_cold_and_can_rewarm(self):
        """Warmth evidence is LRU-bounded alongside the engine's program
        cache: once it ages out, decide() must answer cold again AND a new
        background warm must be schedulable (no permanent _warming claim)."""
        metrics = ServiceMetrics()
        router = PlacementRouter(metrics, background_warm=False)
        sig = battery_signature([Mean("router_ageout_col_vv")])
        router.note_ran(sig, worker_id=0, placement="device")
        assert router.decide(sig) is None  # warm
        # simulate LRU churn evicting the warmth record
        router._ran.clear()
        assert router.decide(sig) == "host"  # cold again, honestly
        assert sig not in router._warming or True  # background_warm off
        router.close()

    def test_failed_warm_is_counted_and_logged(self, caplog):
        import logging

        metrics = ServiceMetrics()
        router = PlacementRouter(metrics, background_warm=True)
        sig = battery_signature([Mean("router_warmfail_col_ww")])

        def broken_warm():
            raise RuntimeError("injected warm crash")

        with caplog.at_level(logging.WARNING, logger="deequ_tpu.service.placement"):
            assert router.decide(sig, warm=broken_warm) == "host"
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if metrics.counter_value("deequ_service_warm_failures_total"):
                    break
                time.sleep(0.01)
        assert metrics.counter_value("deequ_service_warm_failures_total") == 1
        assert any(
            "background warm failed" in r.getMessage() for r in caplog.records
        )
        assert router.decide(sig) == "host"  # still honestly cold
        router.close()

    def test_json_snapshot_escapes_label_joiners(self):
        m = ServiceMetrics()
        m.inc("deequ_join_total", tenant="team-a,outcome=success")
        m.inc("deequ_join_total", tenant="team-a", outcome="success")
        snap = m.json_snapshot()["counters"]["deequ_join_total"]
        assert len(snap) == 2, "distinct label sets must not collide"

    def test_worker_affinity_bookkeeping(self):
        router = PlacementRouter(ServiceMetrics(), background_warm=False)
        sig = battery_signature([Mean("affinity_col")])
        assert router.preferred_workers(sig) == set()
        router.note_ran(sig, 2)
        router.note_ran(sig, 0)
        assert router.preferred_workers(sig) == {0, 2}
        router.close()

    def test_warmth_is_shape_qualified(self):
        """jit compiles per batch shape: warmth at one shape must not route
        a different shape to the device tier's cold compile."""
        from deequ_tpu.service import shape_qualified_signature

        router = PlacementRouter(ServiceMetrics(), background_warm=False)
        analyzers = [Mean("router_shape_col_ss")]
        small = shape_qualified_signature(analyzers, 1024)
        large = shape_qualified_signature(analyzers, 4096)
        router.note_ran(small, 0, placement="device")
        assert router.decide(small) is None  # warm at 1024
        assert router.decide(large) == "host"  # still cold at 4096
        router.close()

    def test_no_warmer_shelters_one_job_then_allows_device(self):
        """With background warming OFF there is no warm mechanism at all:
        the battery shelters ONE job on the host tier and then takes the
        device tier (otherwise the device path would be unreachable)."""
        router = PlacementRouter(ServiceMetrics(), background_warm=False)
        sig = battery_signature([Mean("router_nochurn_col_nn")])
        assert router.decide(sig) == "host"
        assert router.decide(sig) is None  # next job may use the device
        router.close()

    def test_warm_capable_router_does_not_fake_warmth_for_warmless_jobs(self):
        """On a warm-capable service, a job arriving without a warm_fn
        (warmth raced eviction between submit and pickup) runs host WITHOUT
        marking warm — the next submission rebuilds a real warm_fn instead
        of the following job eating the inline device compile."""
        router = PlacementRouter(ServiceMetrics(), background_warm=True)
        sig = battery_signature([Mean("router_raced_col_mm")])
        assert router.decide(sig) == "host"
        assert sig not in router._warming  # nothing useless queued
        assert router.decide(sig) == "host"  # still honestly cold
        router.close()

    def test_decide_after_router_close_does_not_raise(self):
        """A worker asking for placement while the service is draining must
        never die on the shut-down warmer executor (a dead worker leaves
        its job's handle unresolved forever)."""
        from deequ_tpu.runners.engine import warm_fused_program

        router = PlacementRouter(ServiceMetrics(), background_warm=True)
        router.close()  # executor shut down, jobs may still be draining
        sig = battery_signature([Mean("router_closed_col_cc")])
        data = Dataset.from_dict(
            {"router_closed_col_cc": np.arange(8, dtype=np.float64)}
        )
        placement = router.decide(
            sig, warm=lambda: warm_fused_program(sig, data=data)
        )
        assert placement == "host"  # still a safe answer, no exception
        assert sig not in router._warming  # slot not leaked

    def test_signature_dedupes_and_filters(self):
        sig = battery_signature(
            [Mean("x"), Mean("x"), Size(), Uniqueness(["x"])]
        )
        # duplicates collapse; the grouping analyzer is not scan-shareable
        assert sig == (Mean("x"), Size())

    def test_empty_battery_has_empty_shape_signature(self):
        """Grouping/host-only check sets have nothing to warm: the shape
        qualifier must not turn the empty battery into a phantom-cold
        signature that miscounts misses and schedules pointless warms."""
        from deequ_tpu.service import shape_qualified_signature
        from deequ_tpu.service.placement import make_warm_fn

        sig = shape_qualified_signature([Uniqueness(["x"])], 2048)
        assert sig == ()
        router = PlacementRouter(ServiceMetrics(), background_warm=True)
        assert router.decide(sig) is None  # no battery, no routing opinion
        data = Dataset.from_dict({"x": [1, 2]})
        assert make_warm_fn(router, [Uniqueness(["x"])], None, data, 2048) is None
        router.close()

    def test_close_without_wait_does_not_drop_queued_folds(self):
        """close(wait=False) must leave sessions open so queued pipelined
        ingests still fold (daemon workers keep draining); closing them
        would silently drop admitted batches."""
        service = VerificationService(workers=1, max_queue_depth=16,
                                      background_warm=False)
        session = service.session(
            "a", "nodrop", [Check(CheckLevel.ERROR, "c")],
            required_analyzers=[Size()],
        )
        handles = [
            session.ingest(
                Dataset.from_dict({"id": np.arange(10) + i * 10}), wait=False
            )
            for i in range(3)
        ]
        service.close(wait=False)
        for h in handles:
            h.result(120)  # every admitted batch folded, none SessionClosed
        assert session.batches_ingested == 3


class TestExportPlane:
    def test_prometheus_text_format(self):
        m = ServiceMetrics()
        m.describe("deequ_test_total", "A test counter.")
        m.inc("deequ_test_total", 2, tenant="a")
        m.inc("deequ_test_total", tenant="b")
        m.set_gauge_fn("deequ_test_gauge", lambda: 7, "A test gauge.")
        text = m.prometheus_text()
        assert "# HELP deequ_test_total A test counter." in text
        assert "# TYPE deequ_test_total counter" in text
        assert 'deequ_test_total{tenant="a"} 2' in text
        assert 'deequ_test_total{tenant="b"} 1' in text
        assert "# TYPE deequ_test_gauge gauge" in text
        assert "deequ_test_gauge 7" in text

    def test_every_series_has_help_and_type_lines(self):
        """Prometheus exposition completeness: scrapers and `promtool
        check metrics` expect a # HELP and # TYPE line for EVERY series,
        described or not — pin the format."""
        m = ServiceMetrics()
        m.describe("deequ_documented_total", "Documented.")
        m.inc("deequ_documented_total", tenant="a")
        m.inc("deequ_undocumented_total")  # never describe()d
        m.set_gauge_fn("deequ_undocumented_gauge", lambda: 1.0)
        lines = m.prometheus_text().splitlines()
        series_names = set()
        for line in lines:
            if line.startswith("#"):
                continue
            series_names.add(line.split("{")[0].split(" ")[0])
        for name in series_names:
            assert f"# TYPE {name} " in "\n".join(lines), name
            assert any(
                ln.startswith(f"# HELP {name} ") for ln in lines
            ), f"missing HELP for {name}"
        # HELP/TYPE precede the first sample of their series
        help_i = next(
            i for i, ln in enumerate(lines)
            if ln.startswith("# HELP deequ_undocumented_total")
        )
        assert help_i < lines.index("deequ_undocumented_total 1")

    def test_label_values_are_escaped(self):
        m = ServiceMetrics()
        m.inc("deequ_escape_total", tenant='team"a\\b\nc')
        text = m.prometheus_text()
        assert 'tenant="team\\"a\\\\b\\nc"' in text
        assert "\nc\"" not in text  # no raw newline inside a label value

    def test_infinite_gauge_renders_inf_not_crash(self):
        m = ServiceMetrics()
        m.set_gauge_fn("deequ_inf_gauge", lambda: float("inf"))
        m.set_gauge_fn("deequ_ninf_gauge", lambda: float("-inf"))
        text = m.prometheus_text()  # must not raise OverflowError
        assert "deequ_inf_gauge +Inf" in text
        assert "deequ_ninf_gauge -Inf" in text
        snap = json.loads(m.json_text())  # JSON stays strictly parseable
        assert snap["gauges"]["deequ_inf_gauge"] is None

    def test_poisoned_gauge_skipped_counted_and_rest_served(self):
        """Export hardening: a gauge callable that RAISES must not kill the
        exposition — its series is skipped, the failure is counted under
        deequ_service_export_errors_total, and every other series keeps
        serving (both Prometheus and JSON)."""
        m = ServiceMetrics()
        m.inc("deequ_alive_total", 2, tenant="a")
        m.set_gauge_fn("deequ_live_gauge", lambda: 7)

        def dead():
            raise RuntimeError("gone")

        m.set_gauge_fn("deequ_dead_gauge", dead)
        text = m.prometheus_text()
        # the gauge SERIES is skipped (no sample, no TYPE header) — the
        # name only survives as the error counter's label
        assert not any(
            line.startswith("deequ_dead_gauge")
            or line.startswith("# TYPE deequ_dead_gauge")
            for line in text.splitlines()
        )
        assert "deequ_live_gauge 7" in text
        assert 'deequ_alive_total{tenant="a"} 2' in text
        assert (
            'deequ_service_export_errors_total{gauge="deequ_dead_gauge"} 1'
            in text
        )
        snap = json.loads(m.json_text())
        assert "deequ_dead_gauge" not in snap["gauges"]
        assert snap["gauges"]["deequ_live_gauge"] == 7
        # two expositions -> two counted failures (monotonic counter)
        assert (
            snap["counters"]["deequ_service_export_errors_total"][
                "gauge=deequ_dead_gauge"
            ]
            == 2
        )

    def test_returned_nan_gauge_still_renders_nan(self):
        """A gauge that RETURNS NaN (as opposed to raising) is a value,
        not an export error: Prometheus renders the NaN literal, JSON maps
        it to null to stay strictly parseable."""
        m = ServiceMetrics()
        m.set_gauge_fn("deequ_nan_gauge", lambda: float("nan"))
        assert "deequ_nan_gauge NaN" in m.prometheus_text()
        snap = json.loads(m.json_text())
        assert snap["gauges"]["deequ_nan_gauge"] is None
        assert m.counter_value("deequ_service_export_errors_total") == 0

    def test_json_snapshot_structure(self):
        m = ServiceMetrics()
        m.inc("deequ_jobs_total", 3, outcome="success")
        m.inc("deequ_plain_total")
        m.set_gauge_fn("deequ_depth", lambda: 4)
        snap = m.json_snapshot()
        assert snap["counters"]["deequ_jobs_total"] == {"outcome=success": 3}
        assert snap["counters"]["deequ_plain_total"] == 1
        assert snap["gauges"]["deequ_depth"] == 4
        json.dumps(snap)  # JSON-able end to end

    def test_http_exporter_serves_both_endpoints(self):
        m = ServiceMetrics()
        m.inc("deequ_http_test_total", 5)
        exporter = MetricsExporter(m)
        try:
            base = f"http://127.0.0.1:{exporter.port}"
            text = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert "deequ_http_test_total 5" in text
            snap = json.loads(
                urllib.request.urlopen(f"{base}/metrics.json").read()
            )
            assert snap["counters"]["deequ_http_test_total"] == 5
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{base}/other")
        finally:
            exporter.close()

    def test_service_snapshot_reflects_job_counts(self):
        service = VerificationService(workers=1, background_warm=False)
        check = Check(CheckLevel.ERROR, "c").is_complete("id")
        data = Dataset.from_dict({"id": [1, 2, 3]})
        assert service.verify(data, [check], timeout=120).status == (
            CheckStatus.SUCCESS
        )
        snap = service.json_snapshot()
        submitted = snap["counters"]["deequ_service_jobs_submitted_total"]
        assert submitted == {"tenant=default": 1}
        assert "deequ_service_phase_seconds_total" in snap["counters"]
        prom = service.prometheus_text()
        assert 'deequ_service_jobs_completed_total{outcome="success"' in prom
        service.close()
