"""Per-tenant SLO histograms + the unified /statusz snapshot (ISSUE 20).

The acceptance contract this file pins:

- the pow2-edge histogram algebra: fixed shared edges, observation
  bucketing, MERGE BY VECTOR ADD (associative + commutative), upper-edge
  quantiles (None on empty, +Inf in overflow) and the achieved-fraction
  primitive the SLO evaluator runs on;
- valid Prometheus exposition: cumulative ``_bucket{le=...}`` lines with
  the ``+Inf`` bucket equal to ``_count``, plus ``_sum``/``_count``, all
  under one ``# TYPE ... histogram`` header;
- the live service observes fold latency and admission wait per
  tenant x priority, and fleetwatch carries default burn-rate
  objectives over both;
- ``SloEvaluator`` burn rates: 0 when idle, 1 at exactly budget,
  ``1/(1-objective)`` on total violation;
- ``/statusz``: versioned document, last-wins registration, sick-plane
  degradation to ``{"error": ...}``, ``validate_statusz`` schema gating,
  all six ``REQUIRED_PLANES`` on a live service, and HTTP serving (404
  on an exporter built without a statusz callable).
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.service import VerificationService
from deequ_tpu.service.metrics import (
    HISTOGRAM_EDGES,
    MetricsExporter,
    ServiceMetrics,
    SloEvaluator,
    histogram_fraction_le,
    histogram_quantile,
    merge_histogram_states,
)
from deequ_tpu.service.statusz import (
    PLANE_REQUIRED_KEYS,
    REQUIRED_PLANES,
    STATUSZ_VERSION,
    StatuszRegistry,
    validate_statusz,
)

pytestmark = pytest.mark.trace


def _checks():
    return [
        Check(CheckLevel.ERROR, "statusz battery")
        .has_size(lambda n: n > 0)
        .is_complete("x")
    ]


def _empty_state():
    return {
        "counts": [0] * (len(HISTOGRAM_EDGES) + 1), "sum": 0.0, "count": 0,
    }


# ---------------------------------------------------------------------------
# histogram algebra
# ---------------------------------------------------------------------------


class TestHistogramAlgebra:
    def test_edges_are_shared_pow2(self):
        assert HISTOGRAM_EDGES[0] == 2.0 ** -20
        assert HISTOGRAM_EDGES[-1] == 64.0
        assert all(
            b == a * 2.0
            for a, b in zip(HISTOGRAM_EDGES, HISTOGRAM_EDGES[1:])
        )

    def test_observe_accumulates_state(self):
        m = ServiceMetrics()
        for v in (0.001, 0.002, 0.004, 5.0):
            m.observe("lat_seconds", v, tenant="a")
        state = m.histogram_state("lat_seconds", tenant="a")
        assert state["count"] == 4
        assert state["sum"] == pytest.approx(5.007)
        assert sum(state["counts"]) == 4

    def test_overflow_bucket(self):
        m = ServiceMetrics()
        m.observe("lat_seconds", 100.0)  # past the 64 s top edge
        state = m.histogram_state("lat_seconds")
        assert state["counts"][-1] == 1

    def test_nan_observation_dropped(self):
        m = ServiceMetrics()
        m.observe("lat_seconds", float("nan"))
        assert m.histogram_state("lat_seconds") is None

    def test_merge_is_commutative_vector_add(self):
        m = ServiceMetrics()
        m.observe("lat_seconds", 0.01, tenant="a")
        m.observe("lat_seconds", 0.02, tenant="b")
        m.observe("lat_seconds", 0.5, tenant="b")
        a = m.histogram_state("lat_seconds", tenant="a")
        b = m.histogram_state("lat_seconds", tenant="b")
        ab = merge_histogram_states(a, b)
        assert ab == merge_histogram_states(b, a)
        assert ab["count"] == 3
        assert ab["sum"] == pytest.approx(0.53)
        assert ab["counts"] == [
            x + y for x, y in zip(a["counts"], b["counts"])
        ]
        # the no-filter family merge is the same vector add
        assert m.histogram_merged("lat_seconds") == ab
        # label-subset filter merges only the matching cells
        assert m.histogram_merged("lat_seconds", tenant="b")["count"] == 2

    def test_quantile_is_upper_edge(self):
        m = ServiceMetrics()
        for _ in range(99):
            m.observe("lat_seconds", 0.01)
        m.observe("lat_seconds", 10.0)
        state = m.histogram_state("lat_seconds")
        # 0.01 s buckets under the 2^-6 edge; 10 s under the 16 s edge
        assert histogram_quantile(state, 0.5) == 2.0 ** -6
        assert histogram_quantile(state, 0.999) == 16.0

    def test_quantile_empty_and_overflow(self):
        assert histogram_quantile(_empty_state(), 0.99) is None
        m = ServiceMetrics()
        m.observe("lat_seconds", 100.0)
        assert histogram_quantile(
            m.histogram_state("lat_seconds"), 0.5
        ) == float("inf")

    def test_fraction_le(self):
        m = ServiceMetrics()
        for _ in range(9):
            m.observe("lat_seconds", 0.01)
        m.observe("lat_seconds", 10.0)
        state = m.histogram_state("lat_seconds")
        assert histogram_fraction_le(state, 1.0) == pytest.approx(0.9)
        # no traffic violates no objective
        assert histogram_fraction_le(_empty_state(), 1.0) == 1.0


# ---------------------------------------------------------------------------
# Prometheus + JSON rendering
# ---------------------------------------------------------------------------


class TestHistogramRendering:
    def test_prometheus_exposition(self):
        m = ServiceMetrics()
        m.describe_histogram("deequ_test_latency_seconds", "Test latency.")
        for v in (0.001, 0.01, 0.1, 100.0):
            m.observe("deequ_test_latency_seconds", v, tenant="a")
        text = m.prometheus_text()
        assert "# HELP deequ_test_latency_seconds Test latency." in text
        assert "# TYPE deequ_test_latency_seconds histogram" in text
        buckets = [
            line for line in text.splitlines()
            if line.startswith("deequ_test_latency_seconds_bucket")
        ]
        assert len(buckets) == len(HISTOGRAM_EDGES) + 1  # finite + +Inf
        counts = [int(line.rsplit(" ", 1)[1]) for line in buckets]
        assert counts == sorted(counts)  # cumulative, non-decreasing
        assert counts[-1] == 4  # +Inf bucket == _count
        assert buckets[-1].startswith(
            'deequ_test_latency_seconds_bucket{tenant="a",le="+Inf"}'
        )
        assert 'deequ_test_latency_seconds_count{tenant="a"} 4' in text
        assert 'deequ_test_latency_seconds_sum{tenant="a"}' in text

    def test_json_snapshot_carries_histograms(self):
        m = ServiceMetrics()
        m.observe("lat_seconds", 0.01, tenant="a")
        snap = m.json_snapshot()
        state = snap["histograms"]["lat_seconds"]["tenant=a"]
        assert state["count"] == 1
        assert sum(state["counts"]) == 1


# ---------------------------------------------------------------------------
# SLO evaluator burn rates
# ---------------------------------------------------------------------------


class TestSloEvaluator:
    def _pair(self):
        m = ServiceMetrics()
        slo = SloEvaluator(m)
        slo.add_objective(
            "lat", "lat_seconds", threshold_s=0.1, objective=0.99,
            window_s=300.0,
        )
        return m, slo

    def test_idle_window_is_zero(self):
        _, slo = self._pair()
        assert slo.burn_rate("lat", now=0.0) == 0.0
        assert slo.burn_rate("lat", now=1.0) == 0.0

    def test_all_good_zero_burn(self):
        m, slo = self._pair()
        slo.burn_rate("lat", now=0.0)  # baseline sample
        for _ in range(100):
            m.observe("lat_seconds", 0.01)
        assert slo.burn_rate("lat", now=1.0) == 0.0

    def test_total_violation_burns_at_full_rate(self):
        m, slo = self._pair()
        slo.burn_rate("lat", now=0.0)
        for _ in range(10):
            m.observe("lat_seconds", 10.0)
        # (1 - 0) / (1 - 0.99) = 100
        assert slo.burn_rate("lat", now=1.0) == pytest.approx(100.0)

    def test_burning_exactly_at_budget_is_one(self):
        m, slo = self._pair()
        slo.burn_rate("lat", now=0.0)
        for _ in range(99):
            m.observe("lat_seconds", 0.01)
        m.observe("lat_seconds", 10.0)
        assert slo.burn_rate("lat", now=1.0) == pytest.approx(1.0)

    def test_unknown_slug_raises(self):
        _, slo = self._pair()
        with pytest.raises(KeyError):
            slo.burn_rate("nope")


# ---------------------------------------------------------------------------
# live service instrumentation
# ---------------------------------------------------------------------------


class TestServiceInstrumentation:
    def test_fold_latency_and_admission_wait_per_tenant(self):
        with VerificationService(
            workers=2, background_warm=False
        ) as svc:
            sess = svc.session("acme", "d", _checks())
            sess.ingest({
                "x": np.arange(64.0), "y": np.ones(64),
            })
            fold = svc.metrics.histogram_merged(
                "deequ_service_fold_latency_seconds", tenant="acme"
            )
            assert fold["count"] >= 1
            wait = svc.metrics.histogram_merged(
                "deequ_service_admission_wait_seconds", tenant="acme"
            )
            assert wait["count"] >= 1
            # the cells are labeled tenant x priority
            cells = svc.metrics.histogram_cells(
                "deequ_service_fold_latency_seconds"
            )
            labels = dict(cells[0][0])
            assert labels["tenant"] == "acme"
            assert "priority" in labels

    def test_fleetwatch_default_slo_objectives(self):
        with VerificationService(
            workers=1, background_warm=False
        ) as svc:
            slugs = svc.fleetwatch.slo.objectives()
            assert "fold_latency" in slugs
            assert "admission_wait" in slugs
            rates = svc.fleetwatch.slo.burn_rates()
            assert set(rates) == set(slugs)
            # burn-rate gauges render on the export plane
            text = svc.metrics.prometheus_text()
            assert 'deequ_service_slo_burn_rate{slo="fold_latency"}' in text


# ---------------------------------------------------------------------------
# /statusz: registry, validation, live service, HTTP
# ---------------------------------------------------------------------------


def _valid_doc():
    planes = {
        "scheduler": {"queue_depth": 0, "active_jobs": 0, "shed_total": 0,
                      "quota_shed_total": 0},
        "tuning": {"enabled": False},
        "cluster": {"attached": False},
        "catalog": {"enabled": False},
        "fleetwatch": {"quarantined_sessions": [], "watched_series": 0},
        "partition_store": {"attached": False},
    }
    return {
        "statusz_version": STATUSZ_VERSION,
        "generated_unix_s": 1.0,
        "planes": planes,
    }


class TestStatuszRegistry:
    def test_snapshot_is_versioned(self):
        reg = StatuszRegistry()
        reg.register("tuning", lambda: {"enabled": True})
        doc = reg.snapshot()
        assert doc["statusz_version"] == STATUSZ_VERSION
        assert isinstance(doc["generated_unix_s"], float)
        assert doc["planes"]["tuning"] == {"enabled": True}

    def test_registration_is_last_wins(self):
        reg = StatuszRegistry()
        reg.register("cluster", lambda: {"attached": False})
        reg.register("cluster", lambda: {"attached": True, "host": "w0"})
        assert reg.snapshot()["planes"]["cluster"]["attached"] is True
        assert reg.planes() == ["cluster"]

    def test_sick_plane_degrades_to_error_section(self):
        reg = StatuszRegistry()

        def boom():
            raise RuntimeError("plane down")

        reg.register("tuning", boom)
        reg.register("cluster", lambda: {"attached": False})
        doc = reg.snapshot()
        assert doc["planes"]["tuning"] == {
            "error": "RuntimeError: plane down"
        }
        # the healthy plane still reports
        assert doc["planes"]["cluster"] == {"attached": False}
        assert any(
            "tuning" in p and "errored" in p
            for p in validate_statusz(doc)
        )


class TestValidateStatusz:
    def test_valid_document_passes(self):
        assert validate_statusz(_valid_doc()) == []

    def test_version_mismatch(self):
        doc = _valid_doc()
        doc["statusz_version"] = STATUSZ_VERSION + 1
        assert any("statusz_version" in p for p in validate_statusz(doc))

    def test_missing_plane(self):
        doc = _valid_doc()
        del doc["planes"]["fleetwatch"]
        assert any("fleetwatch" in p for p in validate_statusz(doc))

    def test_missing_required_key(self):
        doc = _valid_doc()
        del doc["planes"]["scheduler"]["queue_depth"]
        problems = validate_statusz(doc)
        assert any(
            "scheduler" in p and "queue_depth" in p for p in problems
        )

    def test_every_required_plane_has_a_key_contract(self):
        assert set(PLANE_REQUIRED_KEYS) == set(REQUIRED_PLANES)

    def test_non_object_document(self):
        assert validate_statusz(None) != []
        assert validate_statusz([1, 2]) != []


class TestLiveStatusz:
    def test_service_snapshot_covers_all_planes(self):
        with VerificationService(
            workers=1, background_warm=False
        ) as svc:
            doc = svc.statusz.snapshot()
            assert validate_statusz(doc) == []
            assert set(REQUIRED_PLANES) <= set(doc["planes"])

    def test_http_statusz_round_trip(self):
        with VerificationService(
            workers=1, background_warm=False
        ) as svc:
            exporter = svc.start_exporter()
            url = (
                f"http://{exporter.host}:{exporter.port}/statusz"
            )
            with urllib.request.urlopen(url, timeout=10) as resp:
                assert resp.status == 200
                doc = json.loads(resp.read())
            assert validate_statusz(doc) == []

    def test_exporter_without_statusz_serves_404(self):
        exporter = MetricsExporter(ServiceMetrics())
        try:
            url = (
                f"http://{exporter.host}:{exporter.port}/statusz"
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(url, timeout=10)
            assert err.value.code == 404
        finally:
            exporter.close()
