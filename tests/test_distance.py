"""Distance: L-inf between KLL sketches / categorical count maps with the
small-sample correction (reference `analyzers/Distance.scala:19-88`).
Golden values are hand-computed CDF distances."""

import numpy as np
import pytest

from deequ_tpu.analyzers import Distance, KLLSketch
from deequ_tpu.data import Dataset
from deequ_tpu.ops.kll_host import HostKLL
from deequ_tpu.runners import AnalysisRunner


def _kll(buffers):
    return HostKLL.from_buffers(buffers, sketch_size=2048, shrinking_factor=0.64)


class TestNumericalDistance:
    def test_hand_computed_cdf_distance(self):
        # s1 holds {1,2,3}, s2 holds {2,3,4}, all weight 1. CDFs evaluated
        # at union {1,2,3,4}: s1 -> 1/3, 2/3, 1, 1 ; s2 -> 0, 1/3, 2/3, 1.
        # L-inf = 1/3.
        s1 = _kll([[1.0, 2.0, 3.0]])
        s2 = _kll([[2.0, 3.0, 4.0]])
        d = Distance.numerical_distance(s1, s2, correct_for_low_number_of_samples=True)
        assert d == pytest.approx(1 / 3)

    def test_weighted_levels(self):
        # s1: items 1 (w1) and 2 (w2) -> total 3; cdf(1)=1/3, cdf(2)=1
        # s2: item 2 (w1)             -> total 1; cdf(1)=0,   cdf(2)=1
        s1 = _kll([[1.0], [2.0]])
        s2 = _kll([[2.0]])
        d = Distance.numerical_distance(s1, s2, correct_for_low_number_of_samples=True)
        assert d == pytest.approx(1 / 3)

    def test_identical_sketches_distance_zero(self):
        s = _kll([[1.0, 5.0, 9.0]])
        assert Distance.numerical_distance(s, s, True) == 0.0

    def test_small_sample_correction_floors_at_zero(self):
        # linf 1/3 with n=m=3: correction 1.8*sqrt(6/9) ~ 1.47 > 1/3 -> 0
        s1 = _kll([[1.0, 2.0, 3.0]])
        s2 = _kll([[2.0, 3.0, 4.0]])
        assert Distance.numerical_distance(s1, s2) == 0.0

    def test_from_analyzer_states(self):
        rng = np.random.default_rng(0)
        a = KLLSketch("x")
        same1 = Dataset.from_dict({"x": rng.normal(size=20_000)})
        same2 = Dataset.from_dict({"x": rng.normal(size=20_000)})
        shifted = Dataset.from_dict({"x": rng.normal(loc=3.0, size=20_000)})
        states = {}
        for name, data in (("a", same1), ("b", same2), ("c", shifted)):
            from deequ_tpu.analyzers.state_provider import InMemoryStateProvider

            sp = InMemoryStateProvider()
            AnalysisRunner.do_analysis_run(data, [a], save_states_with=sp)
            states[name] = sp.load(a)
        near = Distance.numerical_distance(states["a"], states["b"], True)
        far = Distance.numerical_distance(states["a"], states["c"], True)
        assert near < 0.05
        assert far > 0.5  # N(0,1) vs N(3,1): L-inf CDF distance ~ 0.87

    def test_robust_variant_keeps_large_distances(self):
        rng = np.random.default_rng(1)
        s1 = _kll([sorted(rng.normal(size=1000))])
        s2 = _kll([sorted(rng.normal(loc=3.0, size=1000))])
        d = Distance.numerical_distance(s1, s2)
        assert d > 0.7


class TestCategoricalDistance:
    def test_hand_computed(self):
        s1 = {"a": 5, "b": 5}
        s2 = {"a": 2, "b": 8}
        # per-key mass: |0.5-0.2| = 0.3, |0.5-0.8| = 0.3 -> 0.3
        d = Distance.categorical_distance(s1, s2, correct_for_low_number_of_samples=True)
        assert d == pytest.approx(0.3)

    def test_disjoint_keys(self):
        d = Distance.categorical_distance(
            {"a": 10}, {"b": 10}, correct_for_low_number_of_samples=True
        )
        assert d == pytest.approx(1.0)

    def test_small_sample_correction(self):
        s1 = {"a": 5, "b": 5}
        s2 = {"a": 2, "b": 8}
        # 0.3 - 1.8*sqrt(20/100) < 0 -> floored at 0
        assert Distance.categorical_distance(s1, s2) == 0.0

    def test_large_sample_correction_small(self):
        s1 = {"a": 50_000, "b": 50_000}
        s2 = {"a": 20_000, "b": 80_000}
        d = Distance.categorical_distance(s1, s2)
        assert d == pytest.approx(0.3 - 1.8 * np.sqrt(2e5 / 1e10), rel=1e-9)

    def test_pandas_series_counts(self):
        import pandas as pd

        s1 = pd.Series({"a": 5, "b": 5})
        s2 = pd.Series({"a": 2, "b": 8})
        d = Distance.categorical_distance(s1, s2, correct_for_low_number_of_samples=True)
        assert d == pytest.approx(0.3)


class TestEmptySamples:
    def test_empty_categorical_sample_robust_is_zero(self):
        assert Distance.categorical_distance({}, {"a": 1}) == 0.0
        assert Distance.categorical_distance({"a": 1}, {}) == 0.0

    def test_empty_sketch_robust_is_zero(self):
        empty = _kll([[]])
        full = _kll([[1.0, 2.0]])
        assert Distance.numerical_distance(empty, full) == 0.0
