"""Partition-aware incremental verification (ISSUE 13): the
PartitionStateStore, the delta planner, and the grow->verify scenarios —
the port of the reference's incremental/aggregated-state behavior
(`AnalysisRunner.runOnAggregatedStates` + StateLoader/StatePersister over
partitioned tables, SURVEY L3/L4).

Parity convention: "bit-exact against the full re-scan" holds when the
full scan's batch boundaries align with the partition boundaries (the
merges then associate identically); sketches (KLL, HLL) are exact-equal
too in that case, and otherwise hold within their documented envelopes.
"""

import glob
import os

import numpy as np
import pyarrow as pa
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    KLLSketch,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import CorruptStateError
from deequ_tpu.repository.partition_store import (
    PartitionStateStore,
    partition_bucket,
)
from deequ_tpu.runners.engine import RunMonitor
from deequ_tpu.runners.incremental import (
    PartitionInput,
    analyzer_key,
    contract_fingerprint,
    dataset_content_checksum,
    plan_delta,
    run_incremental,
)
from deequ_tpu.verification import VerificationSuite

ROWS = 2048


def _part(seed: int, rows: int = ROWS) -> Dataset:
    rng = np.random.default_rng(seed)
    return Dataset.from_dict(
        {
            "id": np.arange(rows, dtype=np.int64) + seed * 1_000_000,
            "v": rng.normal(10.0, 2.0, rows),
            "cat": np.array(["a", "b", "c", "d"])[rng.integers(0, 4, rows)],
        }
    )


def _concat(*seeds: int) -> Dataset:
    return Dataset.from_arrow(
        pa.concat_tables([_part(s).arrow for s in seeds])
    )


def _analyzers():
    return [
        Size(), Completeness("v"), Mean("v"), Sum("v"), Minimum("v"),
        Maximum("v"), StandardDeviation("v"), ApproxCountDistinct("cat"),
        Uniqueness(["id"]), KLLSketch("v"),
    ]


def _checks():
    return [
        Check(CheckLevel.ERROR, "incremental battery")
        .has_size(lambda n: n > 0)
        .is_complete("v")
        .has_mean("v", lambda m: 5.0 < m < 15.0)
        .has_uniqueness(["id"], lambda u: u == 1.0)
        .has_approx_count_distinct("cat", lambda c: c >= 4)
    ]


class TestPartitionStore:
    def test_commit_get_roundtrip(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        store.commit(
            "ds", "2026-01-03", fingerprint="fp", content_checksum="cc",
            num_rows=7, analyzer_keys=["A", "B"],
            schema=[("x", "Integral")],
        )
        m = store.get("ds", "2026-01-03")
        assert m.fingerprint == "fp" and m.content_checksum == "cc"
        assert m.num_rows == 7 and m.covers(["A"]) and m.covers(["A", "B"])
        assert not m.covers(["A", "C"])
        assert m.schema == (("x", "Integral"),)

    def test_get_never_committed_is_none(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        assert store.get("ds", "nope") is None

    def test_time_partitioned_listing_and_window(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        names = [f"2026-{m:02d}-01" for m in range(1, 7)] + ["adhoc-load"]
        for n in names:
            store.commit("ds", n, fingerprint="fp", content_checksum="c",
                         num_rows=1, analyzer_keys=[])
        assert store.list_partitions("ds") == sorted(names)
        # window listing: only month buckets intersecting the window are
        # walked for date names; hash-bucket names always list
        win = store.list_partitions("ds", after="2026-03", before="2026-05")
        assert win == ["2026-03-01", "2026-04-01", "2026-05-01"]
        # the layout really is month-bucketed on disk
        assert partition_bucket("2026-03-01") == "2026-03"
        assert os.path.isdir(
            os.path.join(str(tmp_path), "ds-ds", "2026-03")
        )
        assert partition_bucket("adhoc-load").startswith("x")

    def test_default_window_knob(self, tmp_path, monkeypatch):
        from deequ_tpu.repository.partition_store import PARTITION_WINDOW_ENV

        store = PartitionStateStore(str(tmp_path))
        for m in range(1, 7):
            store.commit("ds", f"2026-{m:02d}-01", fingerprint="f",
                         content_checksum="c", num_rows=1, analyzer_keys=[])
        store.commit("ds", "hashnamed", fingerprint="f",
                     content_checksum="c", num_rows=1, analyzer_keys=[])
        monkeypatch.setenv(PARTITION_WINDOW_ENV, "2")
        listed = store.list_partitions("ds")
        # the two most recent month buckets + the non-date partition
        assert listed == ["2026-05-01", "2026-06-01", "hashnamed"]
        # warn-and-fallback: unparseable keeps the unlimited default
        monkeypatch.setenv(PARTITION_WINDOW_ENV, "banana")
        assert len(store.list_partitions("ds")) == 7

    def test_delete_and_invalidate(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        store.commit("ds", "p1", fingerprint="f", content_checksum="c",
                     num_rows=1, analyzer_keys=[])
        store.invalidate("ds", "p1")
        assert store.get("ds", "p1") is None
        store.commit("ds", "p2", fingerprint="f", content_checksum="c",
                     num_rows=1, analyzer_keys=[])
        assert store.delete("ds", "p2") is True
        assert store.list_partitions("ds") == []

    def test_corrupt_manifest_quarantines_typed(self, tmp_path):
        from deequ_tpu.repository.partition_store import (
            partition_quarantined_total,
        )

        store = PartitionStateStore(str(tmp_path))
        store.commit("ds", "p", fingerprint="f", content_checksum="c",
                     num_rows=1, analyzer_keys=[])
        [manifest] = glob.glob(
            str(tmp_path / "ds-ds" / "*" / "p-p" / "partition-manifest.json")
        )
        raw = open(manifest).read().replace('"numRows": 1', '"numRows": 2')
        open(manifest, "w").write(raw)
        before = partition_quarantined_total()
        with pytest.raises(CorruptStateError):
            store.get("ds", "p")
        assert partition_quarantined_total() == before + 1
        side = glob.glob(str(tmp_path) + ".quarantine/*")
        assert side, "corrupt manifest must be preserved in the sidecar"

    def test_weird_partition_names_roundtrip(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        names = ["UPPER/slash", "dots..", "ünïcode", "_underscore"]
        for n in names:
            store.commit("ds", n, fingerprint="f", content_checksum="c",
                         num_rows=1, analyzer_keys=[])
        assert store.list_partitions("ds") == sorted(names)


class TestContentChecksum:
    def test_different_slices_of_one_table_hash_differently(self):
        """A zero-copy slice's buffers() are the un-trimmed PARENT
        buffers; the digest carries each chunk's offset+length so two
        windows of one table can never alias (stale-state reuse)."""
        table = _part(9, rows=4096).arrow
        a = dataset_content_checksum(Dataset.from_arrow(table.slice(0, 1024)))
        b = dataset_content_checksum(
            Dataset.from_arrow(table.slice(1024, 1024))
        )
        assert a != b
        # and the digest is stable for the same window
        a2 = dataset_content_checksum(
            Dataset.from_arrow(table.slice(0, 1024))
        )
        assert a == a2

    def test_sliced_window_shift_invalidates(self, tmp_path):
        """End-to-end: a rolling window re-sliced from the same parent
        table must plan as content-changed, not reuse."""
        store = PartitionStateStore(str(tmp_path))
        table = _part(10, rows=4096).arrow
        analyzers = [Size(), Mean("v")]
        run_incremental(
            store, "tbl",
            {"w": Dataset.from_arrow(table.slice(0, 2048))}, analyzers,
        )
        ctx, rep = run_incremental(
            store, "tbl",
            {"w": Dataset.from_arrow(table.slice(2048, 2048))}, analyzers,
        )
        assert rep.plan.reasons.get("w") == "content-changed"


class TestMemoryStore:
    def test_memory_uri_roundtrip(self):
        """The store works over deequ_tpu.io URIs (memory:// here, the
        s3/gs stand-in)."""
        from fsspec.implementations.memory import MemoryFileSystem

        MemoryFileSystem.store.clear()
        try:
            store = PartitionStateStore("memory://pstore")
            analyzers = [Size(), Mean("v")]
            parts = {"p1": _part(81), "p2": _part(82)}
            ctx, rep = run_incremental(
                store, "tbl", parts, analyzers, batch_size=ROWS,
            )
            assert rep.plan.scan == ["p1", "p2"]
            assert store.list_partitions("tbl") == ["p1", "p2"]
            ctx2, rep2 = run_incremental(
                store, "tbl", parts, analyzers, batch_size=ROWS,
            )
            assert rep2.plan.fully_reused
            assert (
                ctx2.metric(Size()).value.get()
                == ctx.metric(Size()).value.get()
                == float(2 * ROWS)
            )
            assert store.delete("tbl", "p1") is True
            assert store.list_partitions("tbl") == ["p2"]
        finally:
            MemoryFileSystem.store.clear()


class TestDeltaPlanner:
    def _plan(self, store, parts, analyzers, checksums=None):
        inputs = [
            PartitionInput(name, payload, (checksums or {}).get(name))
            for name, payload in parts.items()
        ]
        schema = _part(1).schema
        return plan_delta(
            store, "ds", inputs, contract_fingerprint(schema),
            [analyzer_key(a) for a in analyzers],
        )

    def test_lifecycle_new_reuse_changed_dropped(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size(), Mean("v")]
        mon = RunMonitor()
        ctx, rep = run_incremental(
            store, "ds", {"p1": _part(1), "p2": _part(2)}, analyzers,
            monitor=mon,
        )
        assert rep.plan.scan == ["p1", "p2"] and rep.plan.reuse == []
        assert mon.partitions_scanned == 2 and mon.partitions_reused == 0

        # unchanged inputs: full reuse, zero rows touched
        mon2 = RunMonitor()
        ctx2, rep2 = run_incremental(
            store, "ds", {"p1": _part(1), "p2": _part(2)}, analyzers,
            monitor=mon2,
        )
        assert rep2.plan.fully_reused and rep2.rows_scanned == 0
        assert rep2.rows_total == 2 * ROWS
        assert mon2.partitions_reused == 2
        assert ctx.metric(Size()).value.get() == ctx2.metric(Size()).value.get()

        # p2's content changes -> invalidated + re-scanned; p1 reused
        mon3 = RunMonitor()
        ctx3, rep3 = run_incremental(
            store, "ds", {"p1": _part(1), "p2": _part(22)}, analyzers,
            monitor=mon3,
        )
        assert rep3.plan.scan == ["p2"] and rep3.plan.invalidated == ["p2"]
        assert rep3.plan.reasons["p2"] == "content-changed"
        assert mon3.partitions_invalidated == 1

        # p2 retired from the incoming set -> dropped, metrics re-merge
        ctx4, rep4 = run_incremental(
            store, "ds", {"p1": _part(1)}, analyzers, delete_dropped=True,
        )
        assert rep4.plan.dropped == ["p2"]
        assert ctx4.metric(Size()).value.get() == float(ROWS)
        assert store.list_partitions("ds") == ["p1"]

    def test_zero_data_touched_on_reuse(self, tmp_path):
        """A callable payload + explicit version token: the reuse run
        never materializes the payload — the zero-touch contract."""
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size(), Mean("v")]
        calls = []

        def loader():
            calls.append(1)
            return _part(3)

        run_incremental(
            store, "ds", {"p": PartitionInput("p", loader, "v7")}, analyzers,
        )
        assert calls, "first run must scan"
        calls.clear()
        ctx, rep = run_incremental(
            store, "ds", {"p": PartitionInput("p", loader, "v7")}, analyzers,
        )
        assert rep.plan.fully_reused
        assert calls == [], "reuse must not touch the payload"
        assert ctx.metric(Size()).value.get() == float(ROWS)
        # schema (and totals) came from the manifest, not the data
        assert rep.rows_total == ROWS

        # a new version token re-scans
        calls.clear()
        _, rep2 = run_incremental(
            store, "ds", {"p": PartitionInput("p", loader, "v8")}, analyzers,
        )
        assert calls and rep2.plan.reasons["p"] == "content-changed"

    def test_fingerprint_mismatch_invalidates(self, tmp_path):
        """A schema change (the contract fingerprint) invalidates every
        stored partition — states folded under another schema never
        merge with these."""
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size()]
        run_incremental(store, "ds", {"p": _part(1)}, analyzers)
        # same name, different schema
        renamed = Dataset.from_dict({"w": np.arange(ROWS, dtype=np.int64)})
        _, rep = run_incremental(
            store, "ds", {"p": renamed}, [Size()],
        )
        assert rep.plan.scan == ["p"]
        assert rep.plan.reasons["p"] == "stale-fingerprint"
        assert rep.plan.invalidated == ["p"]

    def test_battery_growth_rescans(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        run_incremental(store, "ds", {"p": _part(1)}, [Size()])
        _, rep = run_incremental(
            store, "ds", {"p": _part(1)}, [Size(), Mean("v")],
        )
        assert rep.plan.reasons["p"] == "battery-grew"
        # and a SHRUNK battery reuses the superset
        _, rep2 = run_incremental(store, "ds", {"p": _part(1)}, [Size()])
        assert rep2.plan.fully_reused

    def test_unversioned_payload_always_scans(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size()]
        run_incremental(
            store, "ds", {"p": PartitionInput("p", lambda: _part(1))},
            analyzers,
        )
        _, rep = run_incremental(
            store, "ds", {"p": PartitionInput("p", lambda: _part(1))},
            analyzers,
        )
        assert rep.plan.reasons["p"] == "unversioned"


class TestGrowVerifyParity:
    """grow -> verify -> grow -> verify, bit-exact against the full scan
    at partition-aligned batch boundaries — the reference's
    StateAggregation/runOnAggregatedStates scenarios over a store."""

    def test_incremental_equals_full_scan_bit_exact(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        checks = _checks()
        analyzers = _analyzers()
        seeds = [1, 2, 3]
        parts = {f"2026-07-{s:02d}": _part(s) for s in seeds}
        r1 = VerificationSuite.verify_partitioned(
            store, "tbl", parts, checks, analyzers, batch_size=ROWS,
        )
        assert r1.status == CheckStatus.SUCCESS

        for grown in ([1, 2, 3, 4], [1, 2, 3, 4, 5]):
            parts = {f"2026-07-{s:02d}": _part(s) for s in grown}
            r = VerificationSuite.verify_partitioned(
                store, "tbl", parts, checks, analyzers, batch_size=ROWS,
            )
            # only the one new partition scanned
            assert r.incremental.plan.scan == [f"2026-07-{grown[-1]:02d}"]
            assert r.incremental.rows_scanned == ROWS
            assert r.incremental.rows_total == ROWS * len(grown)
            full = VerificationSuite.do_verification_run(
                _concat(*grown), checks, analyzers, batch_size=ROWS,
            )
            assert r.status == full.status == CheckStatus.SUCCESS
            for a, metric in full.metrics.items():
                got = r.metrics[a]
                if a.name in ("KLLSketch",):
                    continue  # distribution object compared below
                assert got.value.get() == metric.value.get(), (
                    a, got.value.get(), metric.value.get(),
                )
            # KLL: aligned-partition merge associates identically with the
            # full scan's per-batch fold — exact bucket equality; the
            # general (unaligned) contract is the documented rank-error
            # envelope
            kll_full = full.metrics[KLLSketch("v")].value.get()
            kll_inc = r.metrics[KLLSketch("v")].value.get()
            assert kll_full.buckets == kll_inc.buckets

    def test_grouping_states_ride_the_store(self, tmp_path):
        """Uniqueness (value-keyed grouping states, persisted as
        parquet) merges across stored partitions exactly like the
        run_on_aggregated_states contract."""
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size(), Uniqueness(["cat"]), Uniqueness(["id"])]
        parts = {"p1": _part(11), "p2": _part(12)}
        ctx, rep = run_incremental(store, "tbl", parts, analyzers)
        full = VerificationSuite.do_verification_run(
            Dataset.from_arrow(
                pa.concat_tables([_part(11).arrow, _part(12).arrow])
            ),
            [], analyzers,
        )
        assert ctx.metric(Uniqueness(["id"])).value.get() == \
            full.metrics[Uniqueness(["id"])].value.get() == 1.0
        assert ctx.metric(Uniqueness(["cat"])).value.get() == \
            full.metrics[Uniqueness(["cat"])].value.get()
        # and they reuse on the next run
        ctx2, rep2 = run_incremental(store, "tbl", parts, analyzers)
        assert rep2.plan.fully_reused
        assert ctx2.metric(Uniqueness(["cat"])).value.get() == \
            ctx.metric(Uniqueness(["cat"])).value.get()

    def test_deletion_re_merge_consistency(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size(), Sum("v"), Mean("v")]
        parts = {f"p{s}": _part(s) for s in (1, 2, 3)}
        run_incremental(store, "tbl", parts, analyzers, batch_size=ROWS)
        del parts["p2"]
        ctx, rep = run_incremental(
            store, "tbl", parts, analyzers, batch_size=ROWS,
        )
        assert rep.plan.dropped == ["p2"] and rep.rows_scanned == 0
        oracle = VerificationSuite.do_verification_run(
            _concat(1, 3), [], analyzers, batch_size=ROWS,
        )
        for a in analyzers:
            assert ctx.metric(a).value.get() == oracle.metrics[a].value.get()


class TestRollupCache:
    """The persisted left-fold prefix: append-only growth folds
    rollup + suffix (O(1) state loads) bit-exact with the full
    partition fold."""

    def test_growth_uses_rollup_prefix(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size(), Mean("v"), Sum("v"), StandardDeviation("v")]
        parts = {"p1": _part(1), "p2": _part(2)}
        run_incremental(store, "tbl", parts, analyzers, batch_size=ROWS)
        assert store.rollup_get("tbl") is not None
        parts["p3"] = _part(3)
        mon = RunMonitor()
        ctx, rep = run_incremental(
            store, "tbl", parts, analyzers, batch_size=ROWS, monitor=mon,
        )
        # the two reused partitions were served by the rollup — their
        # state blobs were never touched
        assert mon.partitions_rolled_up == 2
        oracle = VerificationSuite.do_verification_run(
            _concat(1, 2, 3), [], analyzers, batch_size=ROWS,
        )
        for a in analyzers:
            assert ctx.metric(a).value.get() == oracle.metrics[a].value.get()
        # and the rollup advanced: a fully-reused re-run folds ONE state
        mon2 = RunMonitor()
        ctx2, _ = run_incremental(
            store, "tbl", parts, analyzers, batch_size=ROWS, monitor=mon2,
        )
        assert mon2.partitions_rolled_up == 3
        for a in analyzers:
            assert (
                ctx2.metric(a).value.get() == ctx.metric(a).value.get()
            )

    def test_changed_prefix_partition_rebuilds_rollup(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size(), Sum("v")]
        parts = {"p1": _part(1), "p2": _part(2), "p3": _part(3)}
        run_incremental(store, "tbl", parts, analyzers, batch_size=ROWS)
        parts["p1"] = _part(11)  # a PREFIX partition changes
        mon = RunMonitor()
        ctx, rep = run_incremental(
            store, "tbl", parts, analyzers, batch_size=ROWS, monitor=mon,
        )
        assert rep.plan.scan == ["p1"]
        assert mon.partitions_rolled_up == 0  # prefix broken -> rebuild
        oracle = VerificationSuite.do_verification_run(
            _concat(11, 2, 3), [], analyzers, batch_size=ROWS,
        )
        for a in analyzers:
            assert ctx.metric(a).value.get() == oracle.metrics[a].value.get()

    def test_corrupt_rollup_blob_falls_back_to_partitions(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size(), Mean("v")]
        parts = {"p1": _part(1), "p2": _part(2)}
        ctx0, _ = run_incremental(
            store, "tbl", parts, analyzers, batch_size=ROWS,
        )
        [blob] = glob.glob(
            str(tmp_path / "ds-tbl" / "rollup" / "Mean-*-state.npz")
        )
        raw = bytearray(open(blob, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(blob, "wb").write(bytes(raw))
        ctx, rep = run_incremental(
            store, "tbl", parts, analyzers, batch_size=ROWS,
        )
        assert rep.plan.fully_reused  # cache loss costs a re-merge only
        for a in analyzers:
            assert ctx.metric(a).value.get() == ctx0.metric(a).value.get()


class TestCorruptBlobRescue:
    def test_corrupt_state_blob_quarantines_and_rescans_one(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size(), Mean("v"), Sum("v")]
        parts = {"p1": _part(1), "p2": _part(2), "p3": _part(3)}
        run_incremental(store, "tbl", parts, analyzers, batch_size=ROWS)
        # drop the rollup cache so the merge actually reads the blobs
        # (with the cache intact the corruption below would simply be
        # masked — TestRollupCache pins that)
        store.rollup_invalidate("tbl")
        [blob] = glob.glob(
            str(tmp_path / "ds-tbl" / "*" / "p-p2" / "Mean-*-state.npz")
        )
        raw = bytearray(open(blob, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(blob, "wb").write(bytes(raw))

        mon = RunMonitor()
        ctx, rep = run_incremental(
            store, "tbl", parts, analyzers, batch_size=ROWS, monitor=mon,
        )
        # exactly the corrupt partition re-scanned; siblings reused
        assert rep.plan.reasons.get("p2") == "corrupt-state"
        assert sorted(rep.plan.reuse) == ["p1", "p3"]
        assert rep.rows_scanned == ROWS
        assert mon.corrupt_quarantined >= 1
        oracle = VerificationSuite.do_verification_run(
            _concat(1, 2, 3), [], analyzers, batch_size=ROWS,
        )
        for a in analyzers:
            assert ctx.metric(a).value.get() == oracle.metrics[a].value.get()

    def test_corrupt_blob_without_payload_surfaces_typed(self, tmp_path):
        """No payload to re-scan from -> the typed error reaches the
        caller (who holds the only remedy)."""
        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size(), Mean("v")]
        run_incremental(store, "tbl", {"p": _part(1)}, analyzers)
        store.rollup_invalidate("tbl")
        [blob] = glob.glob(
            str(tmp_path / "ds-tbl" / "*" / "p-p" / "Mean-*-state.npz")
        )
        raw = bytearray(open(blob, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(blob, "wb").write(bytes(raw))
        with pytest.raises((CorruptStateError, ValueError)):
            run_incremental(
                store, "tbl",
                {"p": PartitionInput("p", None, None)}, analyzers,
            )


class TestInjectedFaults:
    def test_partition_store_load_fault_site(self, tmp_path):
        """An injected corrupt at the partition_store_load site re-scans
        exactly the partition it hit (the stale-manifest degradation)."""
        from deequ_tpu.reliability import FaultSpec, inject

        store = PartitionStateStore(str(tmp_path))
        analyzers = [Size(), Mean("v")]
        parts = {"p1": _part(1), "p2": _part(2)}
        run_incremental(store, "tbl", parts, analyzers)
        with inject(FaultSpec(
            "partition_store_load", "corrupt", match="tbl/p1", count=1,
        )) as inj:
            ctx, rep = run_incremental(store, "tbl", parts, analyzers)
        assert inj.fired
        assert "p1" in rep.plan.scan and "p2" in rep.plan.reuse
        assert "corrupt-manifest" in rep.plan.reasons["p1"]
        assert ctx.metric(Size()).value.get() == float(2 * ROWS)


class TestServiceIntegration:
    def test_service_verify_partitioned_exports_counters(self, tmp_path):
        from deequ_tpu.service import VerificationService

        store = PartitionStateStore(str(tmp_path))
        checks = _checks()
        with VerificationService(
            workers=2, background_warm=False, partition_store=store,
        ) as svc:
            parts = {"p1": _part(1), "p2": _part(2)}
            r1 = svc.verify_partitioned("tbl", parts, checks, tenant="ten")
            assert r1.status == CheckStatus.SUCCESS
            assert r1.incremental.plan.scan == ["p1", "p2"]
            r2 = svc.verify_partitioned("tbl", parts, checks, tenant="ten")
            assert r2.incremental.plan.fully_reused
            counters = svc.json_snapshot()["counters"]
            assert counters["deequ_service_partitions_scanned_total"] == {
                "tenant=ten": 2.0
            }
            assert counters["deequ_service_partitions_reused_total"] == {
                "tenant=ten": 2.0
            }

    def test_session_close_flushes_partition(self, tmp_path):
        from deequ_tpu.service import VerificationService

        store = PartitionStateStore(str(tmp_path))
        checks = _checks()
        with VerificationService(
            workers=2, background_warm=False, partition_store=store,
        ) as svc:
            s = svc.session("ten", "streamed", checks)
            s.ingest(_part(31))
            s.ingest(_part(32))
            s.close()
            assert store.list_partitions("streamed") == ["session-ten"]
            m = store.get("streamed", "session-ten")
            assert m.num_rows == 2 * ROWS
            # the flushed partition merges with a NEW batch partition
            # through the ordinary incremental path — the session-
            # migration bridge
            ctx, rep = run_incremental(
                store, "streamed",
                {
                    "session-ten": PartitionInput(
                        "session-ten", None, m.content_checksum
                    ),
                    "day2": _part(33),
                },
                [Size(), Mean("v")], batch_size=ROWS,
            )
            assert rep.plan.reuse == ["session-ten"]
            assert rep.plan.scan == ["day2"]
            assert ctx.metric(Size()).value.get() == float(3 * ROWS)

    def test_fleet_submits_partition_scans_on_sub_mesh(self, tmp_path):
        """Fresh-partition scans ride the tenant's fleet sub-mesh (the
        leased ctx.mesh reaches the runner as sharding) with metrics
        equal to the single-chip run — exact-sum battery, so shard-split
        re-association cannot round."""
        import jax

        from deequ_tpu.service import VerificationService

        if len(jax.devices()) < 2:
            pytest.skip("needs the virtual multi-device conftest")
        store = PartitionStateStore(str(tmp_path / "fleet"))
        checks = [
            Check(CheckLevel.ERROR, "fleet")
            .has_size(lambda n: n > 0)
            .is_complete("v")
        ]
        parts = {"p1": _part(71), "p2": _part(72)}
        with VerificationService(
            workers=2, background_warm=False, fleet=True,
            partition_store=store,
        ) as svc:
            r = svc.verify_partitioned("tbl", parts, checks, tenant="ten")
            assert r.status == CheckStatus.SUCCESS
            leases = svc.metrics.counter_value(
                "deequ_service_fleet_leases_total"
            )
            assert leases and leases >= 1
        ref_store = PartitionStateStore(str(tmp_path / "ref"))
        ref = VerificationSuite.verify_partitioned(
            ref_store, "tbl", {"p1": _part(71), "p2": _part(72)}, checks,
        )
        assert r.metrics[Size()].value.get() == \
            ref.metrics[Size()].value.get() == float(2 * ROWS)

    def test_builder_entry_point(self, tmp_path):
        store = PartitionStateStore(str(tmp_path))
        result = (
            VerificationSuite.on_partitions(
                store, "tbl", {"p": _part(41)}
            )
            .add_checks(_checks())
            .with_batch_size(ROWS)
            .run()
        )
        assert result.status == CheckStatus.SUCCESS
        assert result.incremental.plan.scan == ["p"]


class TestProfilerAndSuggestionsOnStoredStates:
    def test_profile_partitioned_reuses_states(self, tmp_path):
        from deequ_tpu.runners.incremental import profile_partitioned

        store = PartitionStateStore(str(tmp_path))
        parts = {"p1": _part(51), "p2": _part(52)}
        profiles, rep = profile_partitioned(store, "tbl", parts)
        assert set(rep.plan.scan) == {"p1", "p2"}
        profiles2, rep2 = profile_partitioned(store, "tbl", parts)
        assert rep2.plan.fully_reused

        from deequ_tpu.profiles import ColumnProfilerRunner

        oracle = ColumnProfilerRunner.on_data(_concat(51, 52)).run()
        for name in ("id", "v", "cat"):
            a, b = profiles2[name], oracle[name]
            assert a.completeness == b.completeness
            assert (
                a.approximate_num_distinct_values
                == b.approximate_num_distinct_values
            )
            assert a.data_type == b.data_type
        # numeric stats reused (floating association may differ 1ulp
        # from the unaligned full scan; exact counts must not)
        assert profiles2["v"].mean == pytest.approx(
            oracle["v"].mean, rel=1e-12
        )
        assert profiles2["cat"].histogram is not None

    def test_suggest_partitioned_rides_same_states(self, tmp_path):
        from deequ_tpu.runners.incremental import suggest_partitioned
        from deequ_tpu.suggestions import Rules

        store = PartitionStateStore(str(tmp_path))
        parts = {"p1": _part(61), "p2": _part(62)}
        s1, rep1 = suggest_partitioned(store, "tbl", parts, Rules.DEFAULT)
        assert set(rep1.plan.scan) == {"p1", "p2"}
        s2, rep2 = suggest_partitioned(store, "tbl", parts, Rules.DEFAULT)
        assert rep2.plan.fully_reused
        assert sorted(s1.constraint_suggestions) == sorted(
            s2.constraint_suggestions
        )
        assert "v" in s2.constraint_suggestions
