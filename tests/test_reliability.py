"""Fault-tolerant verification engine: the acceptance proofs.

Pins the ISSUE-2 contract end to end with the deterministic fault harness
(`deequ_tpu/reliability/faults.py`):

- an injected device failure mid-pass -> `VerificationSuite.run()` still
  returns a complete result via host-tier failover;
- one injected analyzer fault in a 10-analyzer fused battery -> exactly
  that analyzer yields a typed Failure metric, the other 9 succeed;
- a run interrupted mid-ingest and resumed from the last StatePersister
  checkpoint produces metrics EQUAL to the uninterrupted run (device and
  host tiers, in-memory and filesystem providers);
- OOM -> batch bisection; poisoned host batch -> isolation rerun absorbs
  it; host accumulator faults knock out only themselves;
- the service's placement router learns device failures (probation) and
  the scheduler harvests reliability signals from RunMonitor;
- bench.py's per-stage hard deadline skips-and-records instead of letting
  one stage starve the rest (VERDICT r5 weak #1).
"""

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    KLLSketch,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.analyzers.state_provider import (
    FileSystemStateProvider,
    InMemoryStateProvider,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import (
    AnalyzerFaultException,
    DeviceFailureException,
    DeviceOOMException,
    PoisonedBatchException,
)
from deequ_tpu.reliability import (
    FaultSpec,
    IngestCheckpointer,
    classify_failure,
    inject,
)
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor
from deequ_tpu.verification import VerificationSuite


def _numeric_data(rows=8192, seed=0, with_group=False):
    rng = np.random.default_rng(seed)
    cols = {"x": rng.normal(size=rows), "y": rng.normal(5.0, 2.0, rows)}
    if with_group:
        cols["g"] = [f"id_{i}" for i in range(rows)]  # high-card: host accum
    return Dataset.from_dict(cols)


def _ten_analyzer_battery():
    return [
        Size(), Completeness("x"), Mean("x"), Sum("x"), Minimum("x"),
        Maximum("x"), StandardDeviation("x"), Mean("y"), Sum("y"),
        ApproxCountDistinct("x"),
    ]


class TestFaultInjector:
    def test_at_fires_on_exact_hit_once(self):
        with inject(FaultSpec("device_update", "device", at=3)) as inj:
            from deequ_tpu.reliability import fault_point

            fault_point("device_update", "a")
            fault_point("device_update", "b")
            with pytest.raises(DeviceFailureException):
                fault_point("device_update", "c")
            fault_point("device_update", "d")  # count=1 exhausted
        assert inj.fired == ["device_update:c:device"]

    def test_seeded_probability_is_deterministic(self):
        def run(seed):
            fired = []
            with inject(
                FaultSpec("worker", "worker_death", p=0.5, count=None),
                seed=seed,
            ) as inj:
                from deequ_tpu.reliability import fault_point

                for i in range(32):
                    try:
                        fault_point("worker", str(i))
                    except Exception:  # noqa: BLE001
                        pass
                fired = inj.fired
            return fired

        assert run(7) == run(7)
        assert run(7) != run(8)  # different seed, different plan

    def test_match_narrows_by_tag(self):
        target = repr(Mean("y"))
        with inject(
            FaultSpec("analyzer", "analyzer", match=target, count=None)
        ):
            from deequ_tpu.reliability import fault_point

            fault_point("analyzer", repr(Mean("x")))  # no match, no fire
            with pytest.raises(AnalyzerFaultException):
                fault_point("analyzer", target)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            FaultSpec("worker", "meteor_strike")

    def test_disarmed_fault_point_is_noop(self):
        from deequ_tpu.reliability import fault_point

        fault_point("device_update", "anything")  # must not raise


class TestClassification:
    def test_typed_taxonomy(self):
        assert classify_failure(DeviceOOMException("boom")) == "oom"
        assert classify_failure(DeviceFailureException("dead")) == "device"
        assert classify_failure(PoisonedBatchException(3)) == "data"
        assert classify_failure(ValueError("nope")) == "data"

    def test_xla_status_phrases(self):
        assert classify_failure(RuntimeError("RESOURCE_EXHAUSTED: oom")) == "oom"
        assert classify_failure(RuntimeError("INTERNAL: device lost")) == "device"


class TestAnalyzerIsolation:
    def test_one_faulty_analyzer_in_ten_degrades_alone(self):
        """ISSUE acceptance: 1 injected analyzer fault in a 10-analyzer
        fused battery -> exactly that analyzer fails typed, 9 succeed."""
        analyzers = _ten_analyzer_battery()
        target = Mean("y")
        monitor = RunMonitor()
        with inject(
            FaultSpec("analyzer", "analyzer", match=repr(target), count=None)
        ):
            ctx = AnalysisRunner.do_analysis_run(
                _numeric_data(), analyzers, batch_size=1024, monitor=monitor
            )
        failures = {
            a: m for a, m in ctx.metric_map.items() if m.value.is_failure
        }
        assert set(failures) == {target}
        assert isinstance(failures[target].value.exception, AnalyzerFaultException)
        successes = [m for m in ctx.metric_map.values() if m.value.is_success]
        assert len(successes) == 9
        assert monitor.isolation_reruns > 0
        assert any("Mean" in tag for tag in monitor.degraded)

    def test_isolated_values_match_clean_run(self):
        analyzers = _ten_analyzer_battery()
        clean = AnalysisRunner.do_analysis_run(
            _numeric_data(), analyzers, batch_size=1024
        )
        target = Sum("x")
        with inject(
            FaultSpec("analyzer", "analyzer", match=repr(target), count=None)
        ):
            ctx = AnalysisRunner.do_analysis_run(
                _numeric_data(), analyzers, batch_size=1024
            )
        for analyzer in analyzers:
            if analyzer == target:
                continue
            assert ctx.metric_map[analyzer].value.get() == pytest.approx(
                clean.metric_map[analyzer].value.get()
            )

    def test_poisoned_batch_absorbed_by_rerun(self):
        """A once-poisoned host batch costs isolation reruns, never a
        metric: the re-pass sees clean data and completes."""
        monitor = RunMonitor()
        with inject(FaultSpec("host_partial", "poison", at=3)) as inj:
            ctx = AnalysisRunner.do_analysis_run(
                _numeric_data(), [Mean("x"), Sum("x")], batch_size=1024,
                placement="host", monitor=monitor,
            )
        assert inj.fired == ["host_partial:2:poison"]
        assert all(m.value.is_success for m in ctx.metric_map.values())
        assert monitor.isolation_reruns > 0

    def test_pass_level_failure_short_circuits_bisection(self):
        """A failure every partition reproduces identically (corrupt input,
        dead tier) must cost ~log2(N) re-passes, not ~2N: once a >1-member
        subtree fully fails with the parent's signature, the sibling
        degrades without further re-runs."""
        analyzers = [
            Completeness("x"), Mean("x"), Sum("x"), Minimum("x"),
            Maximum("x"), StandardDeviation("x"), Mean("y"), Sum("y"),
        ]
        monitor = RunMonitor()
        # state_fetch fires once per pass with a tag-free (identical)
        # message — the signature every partition shares
        with inject(FaultSpec("state_fetch", "analyzer", count=None)):
            ctx = AnalysisRunner.do_analysis_run(
                _numeric_data(), analyzers, batch_size=2048, monitor=monitor
            )
        assert all(m.value.is_failure for m in ctx.metric_map.values())
        # 8-battery chain: attempts at 8, 4, 2, 1, 1 — never the ~15 of
        # full bisection
        assert monitor.passes == 5, monitor.passes
        assert monitor.isolation_reruns == 3

    def test_single_fault_never_trips_short_circuit(self):
        """The wholesale-degradation rule must not fire for one faulty
        analyzer: its clean siblings succeed, so no >1 subtree fully
        fails — all 7 clean analyzers still complete."""
        analyzers = [
            Completeness("x"), Mean("x"), Sum("x"), Minimum("x"),
            Maximum("x"), StandardDeviation("x"), Mean("y"), Sum("y"),
        ]
        target = Completeness("x")  # FIRST member: left chain fails deepest
        with inject(
            FaultSpec("analyzer", "analyzer", match=repr(target), count=None)
        ):
            ctx = AnalysisRunner.do_analysis_run(
                _numeric_data(), analyzers, batch_size=2048
            )
        failures = [a for a, m in ctx.metric_map.items() if m.value.is_failure]
        assert failures == [target]

    def test_host_accumulator_knockout_spares_battery(self, monkeypatch):
        from deequ_tpu.analyzers import grouping as grouping_mod

        # pin the grouping set onto the HOST accumulator tier whose
        # knockout path this test exercises — by default the set rides the
        # device frequency table engine and the poison never fires
        monkeypatch.setenv("DEEQU_TPU_DEVICE_FREQ", "0")
        calls = {"n": 0}
        original = grouping_mod.FrequenciesAndNumRows.update

        def poisoned(self, batch):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("poisoned frequency table")
            return original(self, batch)

        monitor = RunMonitor()
        grouping_mod.FrequenciesAndNumRows.update = poisoned
        try:
            ctx = AnalysisRunner.do_analysis_run(
                _numeric_data(with_group=True),
                [Mean("x"), Uniqueness(("g",))],
                batch_size=1024, monitor=monitor,
            )
        finally:
            grouping_mod.FrequenciesAndNumRows.update = original
        assert ctx.metric_map[Mean("x")].value.is_success
        assert ctx.metric_map[Uniqueness(("g",))].value.is_failure
        assert monitor.passes == 1  # knockout, not a re-pass
        assert any(tag.startswith("host:") for tag in monitor.degraded)


class TestTierFailover:
    def test_device_failure_fails_over_to_host(self):
        """ISSUE acceptance: an injected device failure on pass batch 2 ->
        VerificationSuite.run() still returns a complete result."""
        check = (
            Check(CheckLevel.ERROR, "failover")
            .has_size(lambda n: n == 8192)
            .has_mean("x", lambda m: abs(m) < 1)
            .is_complete("y")
        )
        monitor = RunMonitor()
        with inject(FaultSpec("device_update", "device", at=2)) as inj:
            result = (
                VerificationSuite.on_data(_numeric_data())
                .add_check(check)
                .with_batch_size(1024)
                .with_monitor(monitor)
                .run()
            )
        assert inj.fired == ["device_update:2:device"]
        assert result.status == CheckStatus.SUCCESS
        assert all(m.value.is_success for m in result.metrics.values())
        assert monitor.device_failovers == 1
        assert monitor.placement == "host"  # the completing tier

    def test_failover_values_match_device_run(self):
        analyzers = [Mean("x"), Sum("x"), StandardDeviation("x")]
        clean = AnalysisRunner.do_analysis_run(
            _numeric_data(), analyzers, batch_size=1024
        )
        with inject(FaultSpec("device_update", "device", at=1)):
            failed_over = AnalysisRunner.do_analysis_run(
                _numeric_data(), analyzers, batch_size=1024
            )
        for analyzer in analyzers:
            assert failed_over.metric_map[analyzer].value.get() == pytest.approx(
                clean.metric_map[analyzer].value.get(), rel=1e-12
            )

    def test_oom_triggers_batch_bisection(self):
        monitor = RunMonitor()
        with inject(FaultSpec("device_update", "oom", at=1)):
            ctx = AnalysisRunner.do_analysis_run(
                _numeric_data(), [Mean("x"), Sum("x")], batch_size=4096,
                monitor=monitor,
            )
        assert all(m.value.is_success for m in ctx.metric_map.values())
        assert monitor.batch_bisections == 1
        assert monitor.device_failovers == 0  # bisection sufficed

    def test_persistent_oom_falls_through_to_host(self):
        monitor = RunMonitor()
        with inject(FaultSpec("device_update", "oom", count=None)):
            ctx = AnalysisRunner.do_analysis_run(
                _numeric_data(), [Mean("x"), Sum("x")], batch_size=4096,
                monitor=monitor,
            )
        assert all(m.value.is_success for m in ctx.metric_map.values())
        assert monitor.batch_bisections >= 1
        assert monitor.device_failovers == 1
        assert monitor.placement == "host"


class TestResumableIngest:
    def _battery(self):
        return [
            Completeness("x"), Mean("x"), Sum("x"), Minimum("x"),
            Maximum("x"), StandardDeviation("x"), KLLSketch("x"),
        ]

    def _assert_equal_contexts(self, got, want):
        for analyzer, metric in want.metric_map.items():
            other = got.metric_map[analyzer]
            if analyzer.name == "KLLSketch":
                assert repr(other.value.get().buckets) == repr(
                    metric.value.get().buckets
                )
            else:
                assert other.value.get() == metric.value.get(), analyzer

    def test_device_path_resume_equals_uninterrupted(self):
        """ISSUE acceptance: interrupt mid-ingest, resume from the last
        StatePersister checkpoint, metrics EQUAL the uninterrupted run."""
        data = _numeric_data(rows=16 * 1024)
        analyzers = self._battery()
        uninterrupted = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=1024
        )
        checkpointer = IngestCheckpointer(InMemoryStateProvider(), every=4)
        with inject(FaultSpec("device_update", "interrupt", at=11)):
            with pytest.raises(KeyboardInterrupt):
                AnalysisRunner.do_analysis_run(
                    data, analyzers, batch_size=1024, checkpointer=checkpointer
                )
        assert [index for index, _ in checkpointer.saves] == [4, 8]
        monitor = RunMonitor()
        resumed = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=1024, checkpointer=checkpointer,
            monitor=monitor,
        )
        assert monitor.resumed_at_batch == 8
        assert monitor.batches == 8  # 16 total, 8 replayed
        self._assert_equal_contexts(resumed, uninterrupted)

    def test_completion_clears_checkpoint(self):
        data = _numeric_data(rows=8 * 1024)
        analyzers = self._battery()
        checkpointer = IngestCheckpointer(InMemoryStateProvider(), every=2)
        AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=1024, checkpointer=checkpointer
        )
        monitor = RunMonitor()
        AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=1024, checkpointer=checkpointer,
            monitor=monitor,
        )
        assert monitor.resumed_at_batch is None  # fresh, not resumed
        assert monitor.batches == 8

    def test_shape_mismatch_ignores_checkpoint(self):
        data = _numeric_data(rows=8 * 1024)
        analyzers = self._battery()
        checkpointer = IngestCheckpointer(InMemoryStateProvider(), every=2)
        with inject(FaultSpec("device_update", "interrupt", at=5)):
            with pytest.raises(KeyboardInterrupt):
                AnalysisRunner.do_analysis_run(
                    data, analyzers, batch_size=1024, checkpointer=checkpointer
                )
        assert checkpointer.saves
        monitor = RunMonitor()
        AnalysisRunner.do_analysis_run(  # DIFFERENT batch size: no resume
            data, analyzers, batch_size=2048, checkpointer=checkpointer,
            monitor=monitor,
        )
        assert monitor.resumed_at_batch is None

    def test_host_tier_resume_equals_uninterrupted(self, monkeypatch):
        from deequ_tpu.runners.engine import HOST_TIER_WORKERS_ENV

        monkeypatch.setenv(HOST_TIER_WORKERS_ENV, "2")
        rows = 80 * 512
        rng = np.random.default_rng(3)
        data = Dataset.from_dict(
            {
                "x": rng.normal(size=rows),
                "g": [f"id_{i}" for i in range(rows)],  # host accumulator
            }
        )
        analyzers = [Mean("x"), Sum("x"), KLLSketch("x"), Uniqueness(("g",))]
        uninterrupted = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=512, placement="host"
        )
        checkpointer = IngestCheckpointer(InMemoryStateProvider(), every=8)
        with inject(FaultSpec("host_partial", "interrupt", at=75)):
            with pytest.raises(KeyboardInterrupt):
                AnalysisRunner.do_analysis_run(
                    data, analyzers, batch_size=512, placement="host",
                    checkpointer=checkpointer,
                )
        # host-tier checkpoints land on chunk (32-batch) boundaries
        assert [index for index, _ in checkpointer.saves] == [32, 64]
        monitor = RunMonitor()
        resumed = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=512, placement="host",
            checkpointer=checkpointer, monitor=monitor,
        )
        assert monitor.resumed_at_batch == 64
        assert monitor.batches == 16
        self._assert_equal_contexts(resumed, uninterrupted)

    def test_filesystem_provider_checkpoint_roundtrip(self, tmp_path):
        """Meta + states survive a PROCESS boundary: a fresh checkpointer
        over the same directory resumes (the real interruption story)."""
        data = _numeric_data(rows=8 * 1024)
        analyzers = [Completeness("x"), Mean("x"), Sum("x")]
        uninterrupted = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=1024
        )
        store = str(tmp_path / "ckpt")
        checkpointer = IngestCheckpointer(
            FileSystemStateProvider(store), every=2
        )
        with inject(FaultSpec("device_update", "interrupt", at=6)):
            with pytest.raises(KeyboardInterrupt):
                AnalysisRunner.do_analysis_run(
                    data, analyzers, batch_size=1024, checkpointer=checkpointer
                )
        fresh = IngestCheckpointer(FileSystemStateProvider(store), every=2)
        monitor = RunMonitor()
        resumed = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=1024, checkpointer=fresh,
            monitor=monitor,
        )
        assert monitor.resumed_at_batch == 4
        self._assert_equal_contexts(resumed, uninterrupted)

    def test_checkpointer_via_suite_builder(self):
        data = _numeric_data(rows=4096)
        check = Check(CheckLevel.ERROR, "ck").has_mean("x", lambda m: abs(m) < 1)
        checkpointer = IngestCheckpointer(InMemoryStateProvider(), every=1)
        result = (
            VerificationSuite.on_data(data)
            .add_check(check)
            .with_batch_size(1024)
            .checkpoint_with(checkpointer)
            .run()
        )
        assert result.status == CheckStatus.SUCCESS
        assert len(checkpointer.saves) >= 3

    def test_torn_save_invalidates_resume(self):
        """Invalidate-first protocol: a save that crashes after clearing
        the meta (states possibly torn) must make the next run start
        FRESH — never pair old meta with newer states and double-fold."""
        data = _numeric_data(rows=8 * 1024)
        analyzers = [Completeness("x"), Mean("x"), Sum("x")]
        base = AnalysisRunner.do_analysis_run(data, analyzers, batch_size=1024)
        provider = InMemoryStateProvider()
        checkpointer = IngestCheckpointer(provider, every=2)
        with inject(FaultSpec("device_update", "interrupt", at=6)):
            with pytest.raises(KeyboardInterrupt):
                AnalysisRunner.do_analysis_run(
                    data, analyzers, batch_size=1024, checkpointer=checkpointer
                )
        assert checkpointer.saves  # a resume point exists...
        checkpointer._write_meta(None)  # ...until a later save tears
        monitor = RunMonitor()
        resumed = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=1024, checkpointer=checkpointer,
            monitor=monitor,
        )
        assert monitor.resumed_at_batch is None  # fresh, not corrupted
        assert monitor.batches == 8
        self._assert_equal_contexts(resumed, base)

    def test_workers_env_garbage_does_not_crash_host_tier(self, monkeypatch):
        from deequ_tpu.runners.engine import HOST_TIER_WORKERS_ENV

        monkeypatch.setenv(HOST_TIER_WORKERS_ENV, "banana")
        ctx = AnalysisRunner.do_analysis_run(
            _numeric_data(rows=4096), [Mean("x")], batch_size=1024,
            placement="host",
        )
        assert ctx.metric_map[Mean("x")].value.is_success

    def test_interval_validation(self):
        with pytest.raises(ValueError):
            IngestCheckpointer(InMemoryStateProvider(), every=0)
        with pytest.raises(TypeError):
            IngestCheckpointer(object())


class TestRouterLearning:
    def test_probation_routes_host_then_readmits(self):
        from deequ_tpu.service import (
            PlacementRouter,
            shape_qualified_signature,
        )

        router = PlacementRouter(background_warm=False)
        # shape-qualified: warmth rests purely on router evidence, so the
        # process-global program cache (warmed by other tests) cannot leak
        signature = shape_qualified_signature([Mean("x"), Sum("x")], 12345)
        router.note_ran(signature, worker_id=0, placement="device")
        assert router.decide(signature) is None  # warm -> device default
        router.note_device_failure(signature)
        for _ in range(router.SUSPECT_PROBATION_RUNS):
            assert router.decide(signature) == "host"
        # probation over AND warmth claim dropped: reads cold again
        assert router.decide(signature) == "host"
        router.note_ran(signature, worker_id=0, placement="device")
        assert router.decide(signature) is None
        router.close()

    def test_scheduler_harvests_device_failure(self):
        from deequ_tpu.service import VerificationService

        check = Check(CheckLevel.ERROR, "svc").has_mean("x", lambda m: abs(m) < 1)
        data = _numeric_data(rows=4096)
        with VerificationService(workers=2, background_warm=False) as service:
            # first run warms the battery so the router sends the second
            # to the DEVICE tier, where the injected fault fires
            service.verify(data, [check], timeout=120)
            with inject(FaultSpec("device_update", "device", at=1)) as inj:
                result = service.verify(data, [check], timeout=120)
            assert inj.fired  # the job really took the device path
            assert result.status == CheckStatus.SUCCESS
            snapshot = service.json_snapshot()["counters"]
            assert snapshot.get("deequ_service_device_failures_total", 0) >= 1

    def test_worker_crash_terminates_typed(self):
        from deequ_tpu.reliability import WorkerCrash
        from deequ_tpu.service import JobFailed, VerificationService

        check = Check(CheckLevel.ERROR, "crash").has_size(lambda n: n > 0)
        data = _numeric_data(rows=2048)
        with VerificationService(workers=2, background_warm=False) as service:
            with inject(FaultSpec("worker", "worker_death", count=None)):
                handle = service.submit_verification(
                    data, [check], max_retries=0
                )
                with pytest.raises(JobFailed) as info:
                    handle.result(timeout=120)
            assert isinstance(info.value.__cause__, WorkerCrash)


class TestBenchStageBudget:
    def test_deadline_skips_and_records(self, monkeypatch):
        import time as time_mod

        import bench

        monkeypatch.setenv(bench.STAGE_BUDGET_ENV, "1")

        def over_budget():
            time_mod.sleep(5)
            return {"never": True}

        result, status, seconds = bench.run_stage_with_deadline(
            "slow_stage", over_budget
        )
        assert result is None
        assert status == "skipped_deadline"
        assert seconds < 3

    def test_within_budget_passes_through(self, monkeypatch):
        import bench

        monkeypatch.setenv(bench.STAGE_BUDGET_ENV, "30")
        result, status, _ = bench.run_stage_with_deadline(
            "fast_stage", lambda: {"value": 7}
        )
        assert result == {"value": 7}
        assert status == "ok"
