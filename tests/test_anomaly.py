"""Anomaly detection tests — the analog of the reference
`anomalydetection/*Test.scala` plus the repository+anomaly-check integration
(`MetricsRepositoryAnomalyDetectionIntegrationTest.scala`)."""

import numpy as np
import pytest

from deequ_tpu.anomalydetection import (
    AbsoluteChangeStrategy,
    AnomalyDetector,
    BatchNormalStrategy,
    DataPoint,
    HoltWinters,
    MetricInterval,
    OnlineNormalStrategy,
    RelativeRateOfChangeStrategy,
    SeriesSeasonality,
    SimpleThresholdStrategy,
)


class TestSimpleThreshold:
    def test_bounds(self):
        s = SimpleThresholdStrategy(upper_bound=1.0, lower_bound=-1.0)
        data = [-2.0, -0.5, 0.0, 0.5, 2.0]
        found = s.detect(data, (0, len(data)))
        assert [i for i, _ in found] == [0, 4]

    def test_interval(self):
        s = SimpleThresholdStrategy(upper_bound=1.0)
        data = [5.0, 0.0, 5.0]
        assert [i for i, _ in s.detect(data, (1, 2))] == []
        assert [i for i, _ in s.detect(data, (2, 3))] == [2]

    def test_invalid_bounds(self):
        with pytest.raises(ValueError):
            SimpleThresholdStrategy(upper_bound=-1.0, lower_bound=1.0)


class TestChangeStrategies:
    def test_absolute_change(self):
        s = AbsoluteChangeStrategy(max_rate_decrease=-2.0, max_rate_increase=2.0)
        data = [1.0, 2.0, 3.0, 10.0, 11.0, 5.0]
        found = [i for i, _ in s.detect(data, (0, len(data)))]
        assert found == [3, 5]  # +7 jump and -6 drop

    def test_second_order(self):
        s = AbsoluteChangeStrategy(max_rate_increase=5.0, order=2)
        # second derivative: jump in slope
        data = [0.0, 1.0, 2.0, 3.0, 20.0, 37.0]
        found = [i for i, _ in s.detect(data, (0, len(data)))]
        assert found == [4]

    def test_relative_change(self):
        s = RelativeRateOfChangeStrategy(max_rate_increase=2.0)
        data = [1.0, 1.5, 6.0, 6.5]
        found = [i for i, _ in s.detect(data, (0, len(data)))]
        assert found == [2]  # 6/1.5 = 4 > 2

    def test_requires_a_bound(self):
        with pytest.raises(ValueError):
            AbsoluteChangeStrategy()


class TestNormalStrategies:
    def test_online_normal(self):
        rng = np.random.default_rng(0)
        data = list(rng.normal(10, 1, 100))
        data[70] = 50.0
        s = OnlineNormalStrategy()
        found = [i for i, _ in s.detect(data, (0, len(data)))]
        assert found == [70]

    def test_online_normal_excludes_anomalies_from_stats(self):
        rng = np.random.default_rng(1)
        data = list(rng.normal(0, 1, 60))
        data[30] = 100.0
        data[31] = 100.0
        s = OnlineNormalStrategy(ignore_anomalies=True)
        found = [i for i, _ in s.detect(data, (0, len(data)))]
        assert 30 in found and 31 in found

    def test_batch_normal_excludes_interval(self):
        rng = np.random.default_rng(2)
        data = list(rng.normal(5, 1, 50)) + [5.0, 30.0]
        s = BatchNormalStrategy()
        found = [i for i, _ in s.detect(data, (50, 52))]
        assert found == [51]

    def test_batch_normal_empty_basis_raises(self):
        s = BatchNormalStrategy()
        with pytest.raises(ValueError):
            s.detect([1.0, 2.0], (0, 2))


class TestHoltWinters:
    def test_detects_break_in_weekly_pattern(self):
        # 5 weeks of a clean weekly pattern, then a broken day
        pattern = [10.0, 12.0, 14.0, 13.0, 11.0, 5.0, 4.0]
        series = pattern * 5
        series[-2] = 50.0  # corrupt one point in the last (test) week
        hw = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        found = [i for i, _ in hw.detect(series, (28, 35))]
        assert found == [33]

    def test_needs_two_cycles(self):
        hw = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        with pytest.raises(ValueError):
            hw.detect([1.0] * 20, (10, 20))


class TestAnomalyDetector:
    def test_new_point_protocol(self):
        history = [DataPoint(t, 10.0) for t in range(10)]
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=15.0))
        ok = detector.is_new_point_anomalous(history, DataPoint(11, 12.0))
        assert ok.anomalies == ()
        bad = detector.is_new_point_anomalous(history, DataPoint(12, 20.0))
        assert len(bad.anomalies) == 1
        assert bad.anomalies[0][0] == 12  # keyed by timestamp

    def test_new_point_must_be_newer(self):
        history = [DataPoint(t, 10.0) for t in range(10)]
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=15.0))
        with pytest.raises(ValueError):
            detector.is_new_point_anomalous(history, DataPoint(5, 12.0))

    def test_missing_values_dropped(self):
        history = [DataPoint(0, 1.0), DataPoint(1, None), DataPoint(2, 1.0)]
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=15.0))
        result = detector.is_new_point_anomalous(history, DataPoint(3, 1.0))
        assert result.anomalies == ()


class TestAnomalyCheckIntegration:
    def test_add_anomaly_check(self, df_full):
        """Size history 4,4,4 -> new point 4 fine; threshold catches drift
        (the reference `VerificationRunBuilder.addAnomalyCheck` path)."""
        from deequ_tpu import CheckStatus, VerificationSuite
        from deequ_tpu.analyzers import Size
        from deequ_tpu.repository import InMemoryMetricsRepository, ResultKey
        from deequ_tpu.runners import AnalysisRunner

        repo = InMemoryMetricsRepository()
        for t in (1, 2, 3):
            ctx = AnalysisRunner.do_analysis_run(df_full, [Size()])
            repo.save(ResultKey(t), ctx)

        result = (
            VerificationSuite.on_data(df_full)
            .use_repository(repo)
            .add_anomaly_check(
                AbsoluteChangeStrategy(max_rate_decrease=-1.0, max_rate_increase=1.0),
                Size(),
            )
            .run()
        )
        assert result.status == CheckStatus.SUCCESS

        # drastically smaller dataset -> warning
        import pyarrow as pa

        from deequ_tpu.data import Dataset

        small = Dataset.from_arrow(pa.table({"item": pa.array(["1"])}))
        result2 = (
            VerificationSuite.on_data(small)
            .use_repository(repo)
            .add_anomaly_check(
                AbsoluteChangeStrategy(max_rate_decrease=-1.0, max_rate_increase=1.0),
                Size(),
            )
            .run()
        )
        assert result2.status == CheckStatus.WARNING

    def test_history_from_repository_with_tags(self, df_full):
        from deequ_tpu import CheckStatus, VerificationSuite
        from deequ_tpu.analyzers import Size
        from deequ_tpu.repository import InMemoryMetricsRepository, ResultKey
        from deequ_tpu.runners import AnalysisRunner
        from deequ_tpu.verification import AnomalyCheckConfig
        from deequ_tpu.checks import CheckLevel

        repo = InMemoryMetricsRepository()
        ctx = AnalysisRunner.do_analysis_run(df_full, [Size()])
        repo.save(ResultKey(1, {"env": "prod"}), ctx)
        repo.save(ResultKey(2, {"env": "test"}), ctx)

        config = AnomalyCheckConfig(
            CheckLevel.ERROR, "tagged anomaly check", with_tag_values={"env": "prod"}
        )
        result = (
            VerificationSuite.on_data(df_full)
            .use_repository(repo)
            .add_anomaly_check(
                SimpleThresholdStrategy(upper_bound=10.0), Size(), config
            )
            .run()
        )
        assert result.status == CheckStatus.SUCCESS


class TestFiniteSentinels:
    def test_one_sided_online_normal_constant_series(self):
        # a perfectly constant series must never be anomalous (stdDev 0:
        # MAX*0 stays 0, never NaN)
        s = OnlineNormalStrategy(lower_deviation_factor=None)
        assert s.detect([1.0] * 10, (1, 10)) == []

    def test_one_sided_batch_normal_catches_outlier(self):
        s = BatchNormalStrategy(upper_deviation_factor=None)
        found = s.detect([1.0, 1.0, 1.0, 1.0, -100.0], (4, 5))
        assert [i for i, _ in found] == [4]


class TestBatchedOnlineNormal:
    """The array-shaped batched scoring core (ROADMAP item 5, first step):
    N series score in ONE vectorized call, element-for-element identical
    to the one-series path."""

    def _series_fleet(self, n=32, seed=9):
        rng = np.random.default_rng(seed)
        fleet = []
        for _ in range(n):
            s = rng.normal(10, 2, int(rng.integers(15, 90))).tolist()
            for j in rng.integers(4, len(s), 3):
                s[int(j)] += float(rng.choice([-1, 1])) * 40
            fleet.append(s)
        return fleet

    def test_batch_matches_single_series_exactly(self):
        for strat in (
            OnlineNormalStrategy(),
            OnlineNormalStrategy(ignore_anomalies=False),
            OnlineNormalStrategy(
                lower_deviation_factor=None, upper_deviation_factor=2.5,
                ignore_start_percentage=0.2,
            ),
        ):
            fleet = self._series_fleet()
            for interval in [(0, 2 ** 63 - 1), (5, 40), (10, 20)]:
                batched = strat.detect_batch(fleet, interval)
                assert len(batched) == len(fleet)
                for series, got in zip(fleet, batched):
                    want = strat.detect(series, interval)
                    assert [i for i, _ in got] == [i for i, _ in want]
                    for (_, ga), (_, wa) in zip(got, want):
                        assert float(ga.value) == float(wa.value)
                        assert ga.detail == wa.detail

    def test_batch_stats_core_is_vectorized_shape(self):
        strat = OnlineNormalStrategy()
        m = np.vstack([np.ones(20), np.arange(20, dtype=float)])
        means, stds, flags = strat.compute_stats_batch(m)
        assert means.shape == stds.shape == flags.shape == (2, 20)
        # a constant series is never anomalous
        assert not flags[0].any()

    def test_batch_ragged_lengths_ignore_padding(self):
        strat = OnlineNormalStrategy(ignore_start_percentage=0.0)
        short = [10.0, 10.1, 9.9, 10.0, 50.0]
        long = [10.0] * 40 + [90.0] + [10.0] * 10
        batched = strat.detect_batch([short, long], (0, 2 ** 63 - 1))
        assert [i for i, _ in batched[0]] == [
            i for i, _ in strat.detect(short, (0, 2 ** 63 - 1))
        ]
        assert [i for i, _ in batched[1]] == [
            i for i, _ in strat.detect(long, (0, 2 ** 63 - 1))
        ]

    def test_batch_empty_and_validation(self):
        strat = OnlineNormalStrategy()
        assert strat.detect_batch([], (0, 10)) == []
        with pytest.raises(ValueError):
            strat.detect_batch([[1.0]], (5, 2))
