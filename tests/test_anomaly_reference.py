"""Reference anomaly-suite ports + serial-vs-batched parity pins
(ISSUE 15): the scenarios of `AnomalyDetectorTest.scala`,
`RateOfChangeStrategyTest.scala`, `OnlineNormalStrategyTest.scala` and the
`HoltWintersTest.scala` detection scenarios, each doubled with the
batched ``detect_batch`` twin — flag indices, values AND messages must
match element-for-element, including ragged fleets, per-series search
intervals, and the anomaly-exclusion rollback subtlety."""

import numpy as np
import pytest

from deequ_tpu.anomalydetection import (
    AbsoluteChangeStrategy,
    Anomaly,
    AnomalyDetector,
    BatchNormalStrategy,
    DataPoint,
    HoltWinters,
    MetricInterval,
    OnlineNormalStrategy,
    RateOfChangeStrategy,
    RelativeRateOfChangeStrategy,
    SeriesSeasonality,
    SimpleThresholdStrategy,
)


def assert_batched_matches_serial(strategy, fleet, intervals):
    """The parity pin: one batched call == per-series serial calls,
    element for element (indices, values, messages)."""
    batched = strategy.detect_batch(fleet, intervals)
    assert len(batched) == len(fleet)
    if isinstance(intervals, tuple):
        intervals = [intervals] * len(fleet)
    for series, interval, got in zip(fleet, intervals, batched):
        want = strategy.detect(series, interval)
        assert [i for i, _ in got] == [i for i, _ in want]
        for (_, ga), (_, wa) in zip(got, want):
            assert float(ga.value) == float(wa.value)
            assert ga.detail == wa.detail


def ragged_fleet(n=24, seed=11, lo=15, hi=90):
    rng = np.random.default_rng(seed)
    fleet = []
    for _ in range(n):
        s = list(rng.normal(10, 2, int(rng.integers(lo, hi))))
        for j in rng.integers(4, len(s), 3):
            s[int(j)] += float(rng.choice([-1, 1])) * 40
        fleet.append(s)
    return fleet


class TestAnomalyDetectorReference:
    """`AnomalyDetectorTest.scala` scenarios."""

    def test_history_must_not_be_empty(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=1.0))
        with pytest.raises(ValueError):
            detector.is_new_point_anomalous([], DataPoint(1, 1.0))

    def test_new_point_must_be_after_history_range(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=1.0))
        history = [DataPoint(t, 0.0) for t in range(5)]
        with pytest.raises(ValueError):
            detector.is_new_point_anomalous(history, DataPoint(4, 0.0))

    def test_detects_only_in_search_interval(self):
        """The reference feeds unsorted points and expects detection keyed
        by TIMESTAMP, only inside the time interval."""
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=1.0))
        points = [DataPoint(t, 5.0) for t in (4, 1, 3, 0, 2)]
        result = detector.detect_anomalies_in_history(points, (2, 4))
        assert [t for t, _ in result.anomalies] == [2, 3]

    def test_none_metric_values_are_dropped(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=1.0))
        points = [
            DataPoint(0, 0.0), DataPoint(1, None), DataPoint(2, 5.0),
        ]
        result = detector.detect_anomalies_in_history(points)
        assert [t for t, _ in result.anomalies] == [2]

    def test_interval_start_after_end_raises(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=1.0))
        with pytest.raises(ValueError):
            detector.detect_anomalies_in_history(
                [DataPoint(0, 0.0)], (5, 2)
            )

    def test_anomaly_equality_ignores_detail(self):
        """`DetectionResult.scala`: anomalies compare by value +
        confidence, not message."""
        assert Anomaly(1.0, 1.0, "a") == Anomaly(1.0, 1.0, "b")
        assert Anomaly(1.0, 1.0) != Anomaly(2.0, 1.0)


class TestRateOfChangeReference:
    """`RateOfChangeStrategyTest.scala` scenarios (RateOfChange is the
    deprecated alias of AbsoluteChange)."""

    DATA = [1.0, 2.0, 4.0, 1.0, 2.0, 8.0, 8.5, 9.0]

    def test_detects_changes_beyond_both_bounds(self):
        s = RateOfChangeStrategy(max_rate_decrease=-2.0, max_rate_increase=2.0)
        found = [i for i, _ in s.detect(self.DATA, (0, len(self.DATA)))]
        assert found == [3, 5]  # -3 drop and +6 jump

    def test_upper_bound_only(self):
        s = RateOfChangeStrategy(max_rate_increase=2.0)
        found = [i for i, _ in s.detect(self.DATA, (0, len(self.DATA)))]
        assert found == [5]

    def test_lower_bound_only(self):
        s = RateOfChangeStrategy(max_rate_decrease=-2.0)
        found = [i for i, _ in s.detect(self.DATA, (0, len(self.DATA)))]
        assert found == [3]

    def test_search_interval_restricts_detection(self):
        s = RateOfChangeStrategy(max_rate_decrease=-2.0, max_rate_increase=2.0)
        assert [i for i, _ in s.detect(self.DATA, (4, 8))] == [5]

    def test_order_two_derivative(self):
        s = AbsoluteChangeStrategy(max_rate_increase=4.0, order=2)
        data = [0.0, 1.0, 2.0, 3.0, 10.0, 17.0]
        # second difference jumps by 6 at index 4
        assert [i for i, _ in s.detect(data, (0, len(data)))] == [4]

    def test_requires_some_bound(self):
        with pytest.raises(ValueError):
            RateOfChangeStrategy()

    def test_bounds_must_be_ordered(self):
        with pytest.raises(ValueError):
            RateOfChangeStrategy(max_rate_decrease=2.0, max_rate_increase=-2.0)

    def test_batched_parity_shared_interval(self):
        s = RateOfChangeStrategy(max_rate_decrease=-2.0, max_rate_increase=2.0)
        fleet = ragged_fleet(seed=21)
        assert_batched_matches_serial(s, fleet, (0, 2 ** 62))

    def test_batched_parity_per_series_intervals_and_orders(self):
        for order in (1, 2):
            s = AbsoluteChangeStrategy(
                max_rate_decrease=-5.0, max_rate_increase=5.0, order=order
            )
            fleet = ragged_fleet(seed=22 + order)
            intervals = [(max(order, len(f) // 2), len(f)) for f in fleet]
            assert_batched_matches_serial(s, fleet, intervals)

    def test_relative_rate_batched_parity(self):
        s = RelativeRateOfChangeStrategy(max_rate_increase=1.5)
        fleet = ragged_fleet(seed=31)
        intervals = [(1, len(f)) for f in fleet]
        assert_batched_matches_serial(s, fleet, intervals)

    def test_relative_rate_order_zero_raises_batched_too(self):
        s = RelativeRateOfChangeStrategy(max_rate_increase=1.5, order=0)
        with pytest.raises(ValueError):
            s.detect([1.0, 2.0], (0, 2))
        with pytest.raises(ValueError):
            s.detect_batch([[1.0, 2.0]], (0, 2))


class TestOnlineNormalReference:
    """`OnlineNormalStrategyTest.scala` scenarios, incl. the
    anomaly-exclusion rollback and the search-interval non-rollback
    subtlety."""

    def _series(self, seed=0, n=100):
        rng = np.random.default_rng(seed)
        data = list(rng.normal(10.0, 1.0, n))
        data[20] = 45.0
        data[70] = -30.0
        return data

    def test_detects_planted_outliers(self):
        s = OnlineNormalStrategy()
        found = [i for i, _ in s.detect(self._series(), (0, 100))]
        assert found == [20, 70]

    def test_exclusion_rollback_keeps_later_points_detectable(self):
        """With ignore_anomalies=True a flagged point is EXCLUDED from the
        running stats (mean/variance roll back), so a back-to-back pair of
        outliers both flag; without the rollback the first outlier widens
        the band."""
        rng = np.random.default_rng(1)
        data = list(rng.normal(0.0, 1.0, 80))
        data[40] = 100.0
        data[41] = 100.0
        with_rollback = OnlineNormalStrategy(ignore_anomalies=True)
        found = [i for i, _ in with_rollback.detect(data, (0, 80))]
        assert 40 in found and 41 in found
        without = OnlineNormalStrategy(ignore_anomalies=False)
        found_no = [i for i, _ in without.detect(data, (0, 80))]
        # the un-rolled-back stats absorb the outliers into the band
        assert len(found_no) <= len(found)

    def test_points_outside_search_interval_never_roll_back(self):
        """An out-of-interval outlier is neither FLAGGED nor excluded from
        the stats — the stats at the interval's first point already
        absorbed it (the reference's searchInterval contract)."""
        data = [10.0] * 30 + [100.0] + [10.0] * 30
        s = OnlineNormalStrategy(ignore_start_percentage=0.0)
        full = s.compute_stats_and_anomalies(data, (0, len(data)))
        windowed = s.compute_stats_and_anomalies(data, (40, len(data)))
        assert full[30][2] and not windowed[30][2]  # flagged only in-window
        # the windowed run's stats at index 31 INCLUDE the outlier (no
        # rollback happened), so they differ from the full run's
        assert windowed[31][0] != full[31][0]

    def test_ignore_start_percentage(self):
        data = [1000.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0]
        s = OnlineNormalStrategy(ignore_start_percentage=0.2)
        found = [i for i, _ in s.detect(data, (0, len(data)))]
        assert 0 not in found and 1 not in found

    def test_factor_validation(self):
        with pytest.raises(ValueError):
            OnlineNormalStrategy(
                lower_deviation_factor=None, upper_deviation_factor=None
            )
        with pytest.raises(ValueError):
            OnlineNormalStrategy(lower_deviation_factor=-1.0)
        with pytest.raises(ValueError):
            OnlineNormalStrategy(ignore_start_percentage=1.5)

    def test_batched_parity_ragged_fleet_all_variants(self):
        fleet = ragged_fleet(seed=41)
        for strat in (
            OnlineNormalStrategy(),
            OnlineNormalStrategy(ignore_anomalies=False),
            OnlineNormalStrategy(
                lower_deviation_factor=None, upper_deviation_factor=2.0,
                ignore_start_percentage=0.25,
            ),
        ):
            assert_batched_matches_serial(strat, fleet, (0, 2 ** 62))

    def test_batched_parity_per_series_newest_point_intervals(self):
        """The fleet-watch shape: every series judged at its OWN newest
        index — including the rollback bookkeeping up to that point."""
        fleet = ragged_fleet(seed=42)
        intervals = [(len(f) - 1, len(f)) for f in fleet]
        assert_batched_matches_serial(
            OnlineNormalStrategy(), fleet, intervals
        )

    def test_batched_rollback_pins_exact_stats(self):
        """Rollback parity at the STATS level: the batched recurrence's
        mean/std after an excluded anomaly equals the scalar path's,
        bitwise."""
        data = [10.0] * 20 + [90.0] + [10.0] * 20
        s = OnlineNormalStrategy(ignore_start_percentage=0.0)
        scalar = s.compute_stats_and_anomalies(data, (0, len(data)))
        means, stds, flags = s.compute_stats_batch(
            np.asarray(data)[None, :], search_interval=(0, len(data))
        )
        for k, (mean, std, flagged) in enumerate(scalar):
            assert means[0, k] == mean
            assert stds[0, k] == std
            assert bool(flags[0, k]) == flagged


class TestBatchNormalReference:
    def test_basis_excludes_search_interval(self):
        rng = np.random.default_rng(2)
        data = list(rng.normal(5.0, 1.0, 50)) + [5.0, 30.0]
        s = BatchNormalStrategy()
        assert [i for i, _ in s.detect(data, (50, 52))] == [51]

    def test_include_interval_uses_whole_series(self):
        data = [1.0, 1.0, 1.0, 1.0, 100.0]
        found = BatchNormalStrategy(include_interval=True).detect(data, (4, 5))
        assert [i for i, _ in found] == []

    def test_batched_parity(self):
        fleet = ragged_fleet(seed=51)
        intervals = [(len(f) // 2, len(f)) for f in fleet]
        assert_batched_matches_serial(BatchNormalStrategy(), fleet, intervals)
        assert_batched_matches_serial(
            BatchNormalStrategy(include_interval=True), fleet, intervals
        )

    def test_batched_empty_series_raises_like_serial(self):
        with pytest.raises(ValueError):
            BatchNormalStrategy().detect_batch([[]], (0, 1))


class TestSimpleThresholdBatched:
    def test_batched_parity(self):
        fleet = ragged_fleet(seed=61)
        intervals = [(0, len(f)) for f in fleet]
        assert_batched_matches_serial(
            SimpleThresholdStrategy(upper_bound=12.0, lower_bound=8.0),
            fleet, intervals,
        )

    def test_interval_validation_matches_serial(self):
        s = SimpleThresholdStrategy(upper_bound=1.0)
        with pytest.raises(ValueError):
            s.detect_batch([[1.0]], (5, 2))
        with pytest.raises(ValueError):
            s.detect_batch([[1.0], [1.0]], [(0, 1), (5, 2)])


class TestHoltWintersReference:
    """`HoltWintersTest.scala` detection scenarios + the batched twin."""

    @staticmethod
    def weekly_series(weeks=6, seed=3, noise=0.2):
        rng = np.random.default_rng(seed)
        pattern = [10.0, 12.0, 14.0, 13.0, 11.0, 5.0, 4.0]
        return [
            v + float(rng.normal(0, noise))
            for _ in range(weeks)
            for v in pattern
        ]

    def test_detects_break_in_weekly_pattern(self):
        series = self.weekly_series()
        series[-2] += 30.0
        hw = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        found = [i for i, _ in hw.detect(series, (35, 42))]
        assert len(series) - 2 in found

    def test_break_flags_only_with_the_break(self):
        """The broken day flags; the same series WITHOUT the break does
        not flag that day (the clean-vs-corrupt pair the reference
        scenario pins — small-noise days may flag either way, the break
        day is the discriminator)."""
        clean = self.weekly_series(seed=4)
        broken = list(clean)
        broken[-2] += 30.0
        hw = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        day = len(clean) - 2
        assert day in [i for i, _ in hw.detect(broken, (35, 42))]
        assert day not in [i for i, _ in hw.detect(clean, (35, 42))]

    def test_yearly_monthly_periodicity(self):
        rng = np.random.default_rng(5)
        series = [
            50.0 + 10 * np.sin(2 * np.pi * (i % 12) / 12)
            + float(rng.normal(0, 0.3))
            for i in range(48)
        ]
        series[-1] += 60.0
        hw = HoltWinters(MetricInterval.MONTHLY, SeriesSeasonality.YEARLY)
        found = [i for i, _ in hw.detect(series, (47, 48))]
        assert found == [47]

    def test_unsupported_period_combo_raises(self):
        with pytest.raises(ValueError):
            HoltWinters(MetricInterval.MONTHLY, SeriesSeasonality.WEEKLY)

    def test_validations(self):
        hw = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        with pytest.raises(ValueError):
            hw.detect([], (0, 10))
        with pytest.raises(ValueError):
            hw.detect([1.0] * 30, (20, 10))
        with pytest.raises(ValueError):
            hw.detect([1.0] * 30, (-1, 10))
        with pytest.raises(ValueError):
            hw.detect([1.0] * 30, (7, 20))  # < two full cycles of training

    def test_batched_parity_ragged_fleet(self):
        """Ragged fleets with per-series newest-week intervals: flags,
        values and messages element-identical to serial (the fitted
        parameters come from the same per-series optimizer calls; the
        RECURRENCES are what batch)."""
        rng = np.random.default_rng(6)
        hw = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        fleet = []
        for k in range(8):
            weeks = int(rng.integers(4, 7))
            s = self.weekly_series(weeks=weeks, seed=100 + k)
            if k % 2 == 0:
                s[-1] += 25.0
            fleet.append(s)
        intervals = [(len(s) - 7, len(s)) for s in fleet]
        assert_batched_matches_serial(hw, fleet, intervals)

    def test_batched_accepts_cached_params(self):
        hw = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        fleet = [self.weekly_series(seed=7), self.weekly_series(seed=8)]
        fleet[0][-1] += 30.0
        intervals = [(len(s) - 7, len(s)) for s in fleet]
        params = hw.fit_batch(fleet, intervals)
        got = hw.detect_batch(fleet, intervals, params=params)
        want = hw.detect_batch(fleet, intervals)
        assert [[i for i, _ in rows] for rows in got] == [
            [i for i, _ in rows] for rows in want
        ]
        assert (len(fleet[0]) - 1) in [i for i, _ in got[0]]

    def test_batch_core_matches_scalar_recurrence(self):
        """`additive_holt_winters_batch` == `additive_holt_winters`
        bitwise on forecasts AND residuals, across parameter corners and
        ragged training lengths."""
        from deequ_tpu.anomalydetection.seasonal import (
            additive_holt_winters,
            additive_holt_winters_batch,
        )

        rng = np.random.default_rng(9)
        m = 7
        trainings = [
            list(rng.normal(20, 3, int(rng.integers(2 * m, 6 * m))))
            for _ in range(10)
        ]
        params = [
            (float(a), float(b), float(g))
            for a, b, g in rng.uniform(0.01, 0.99, (10, 3))
        ]
        nfs = [int(rng.integers(1, 8)) for _ in range(10)]
        tl = np.array([len(t) for t in trainings])
        width = int(tl.max())
        mat = np.zeros((10, width))
        for i, t in enumerate(trainings):
            mat[i, : len(t)] = t
        res = additive_holt_winters_batch(
            mat, tl, m, np.array(nfs),
            np.array([p[0] for p in params]),
            np.array([p[1] for p in params]),
            np.array([p[2] for p in params]),
        )
        for i, training in enumerate(trainings):
            want = additive_holt_winters(training, m, nfs[i], *params[i])
            got_fc = res.forecasts[i, : nfs[i]]
            assert got_fc.tolist() == pytest.approx(want.forecasts, abs=0.0)
            got_res = res.residuals[i, : len(training)]
            assert got_res.tolist() == pytest.approx(want.residuals, abs=0.0)


class TestDefaultDetectBatch:
    def test_any_strategy_is_batchable_via_the_base_loop(self):
        """A strategy with no specialized override still batches (the
        fleet watch's contract: every bundle makes ONE call)."""

        from deequ_tpu.anomalydetection import AnomalyDetectionStrategy

        class EveryThird(AnomalyDetectionStrategy):
            def detect(self, data_series, search_interval):
                start, end = search_interval
                return [
                    (i, Anomaly(data_series[i], 1.0))
                    for i in range(start, min(end, len(data_series)))
                    if i % 3 == 0
                ]

        fleet = [[1.0] * 7, [2.0] * 4]
        got = EveryThird().detect_batch(fleet, [(0, 7), (1, 4)])
        assert [[i for i, _ in rows] for rows in got] == [[0, 3, 6], [3]]
