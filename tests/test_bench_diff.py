"""Perf-regression gate tests (ISSUE 12 satellite): `tools.bench_diff`
must flag an artificially degraded run against the committed trajectory
and pass a clean re-run — the acceptance drill, run against the REAL
committed artifacts so the gate and the trajectory can never drift."""

from __future__ import annotations

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.fleet


def _committed():
    from tools.bench_diff import _latest_artifact, _metrics_of

    path = _latest_artifact(REPO, "BENCH_r*.json")
    assert path is not None, "a committed BENCH_r*.json must parse"
    with open(path) as fh:
        return _metrics_of(json.load(fh))


def test_clean_rerun_passes():
    from tools.bench_diff import diff_metrics

    committed = _committed()
    result = diff_metrics(json.loads(json.dumps(committed)), committed)
    assert result["ok"], result["regressions"]
    assert not result["regressions"]


def test_degraded_run_flags_named_stages():
    from tools.bench_diff import diff_metrics, render_report

    committed = _committed()
    bad = json.loads(json.dumps(committed))
    bad["grouping_rows_per_sec"] = committed["grouping_rows_per_sec"] / 2
    bad["grouping_peak_rss_gb"] = committed["grouping_peak_rss_gb"] * 2
    bad["stages"]["scan"]["compiles"] = (
        committed["stages"]["scan"].get("compiles", 0) + 3
    )
    result = diff_metrics(bad, committed)
    assert not result["ok"]
    flagged = {(r["stage"], r["kind"]) for r in result["regressions"]}
    assert ("grouping", "throughput") in flagged
    assert ("grouping", "rss") in flagged
    assert ("scan", "compiles") in flagged
    report = render_report(result)
    assert "grouping" in report and "PERF REGRESSION" in report


def test_small_wobble_stays_inside_the_band():
    from tools.bench_diff import diff_metrics

    committed = _committed()
    wobbly = json.loads(json.dumps(committed))
    for key in ("grouping_rows_per_sec", "ingest_mb_per_s"):
        if key in wobbly:
            wobbly[key] = committed[key] * 0.9  # -10%: inside the 25% band
    assert diff_metrics(wobbly, committed)["ok"]


def test_substrate_change_skips_mesh_points_instead_of_lying():
    from tools.bench_diff import diff_metrics

    committed = _committed()
    fresh = json.loads(json.dumps(committed))
    fresh["mesh_substrate"] = {"substrate": "accelerator"}
    committed = json.loads(json.dumps(committed))
    committed["mesh_substrate"] = {"substrate": "cpu-virtual"}
    # an accelerator mesh is 10x the virtual-CPU points — that must be
    # SKIPPED (incomparable), not reported as a 10x improvement
    fresh["mesh_scaling_rows_per_sec"] = {
        k: v * 10 for k, v in committed["mesh_scaling_rows_per_sec"].items()
    }
    result = diff_metrics(fresh, committed)
    assert result["ok"]
    skipped = [s for s in result["skipped"] if s["stage"] == "mesh_scaling"]
    assert skipped, "substrate-mismatched mesh points must be skipped"


def test_missing_mesh_point_is_reported_not_silently_green():
    from tools.bench_diff import diff_metrics

    committed = _committed()
    fresh = json.loads(json.dumps(committed))
    # the fresh run produced no 8-device point (deadline / fewer devices)
    fresh["mesh_scaling_rows_per_sec"].pop("8")
    result = diff_metrics(fresh, committed)
    assert any(
        s["metric"] == "mesh_scaling_rows_per_sec[8]"
        and s["reason"] == "missing from fresh run"
        for s in result["skipped"]
    ), result["skipped"]


def test_skipped_fresh_stage_is_reported_not_compared():
    from tools.bench_diff import diff_metrics

    committed = _committed()
    fresh = json.loads(json.dumps(committed))
    fresh["stages"]["grouping"] = {"status": "skipped_deadline"}
    fresh["grouping_rows_per_sec"] = 1.0  # stale garbage must not compare
    result = diff_metrics(fresh, committed)
    assert all(
        r["stage"] != "grouping" or r["kind"] == "compiles"
        for r in result["regressions"]
    )
    assert any(s["stage"] == "grouping" for s in result["skipped"])


def test_knee_trajectory_gates_streaming_headline():
    from tools.bench_diff import _latest_artifact, diff_metrics

    committed = _committed()
    knee_path = _latest_artifact(REPO, "KNEE_r*.json")
    assert knee_path is not None
    with open(knee_path) as fh:
        knee = json.load(fh)
    fresh = json.loads(json.dumps(committed))
    fresh["streaming_knee_sessions_per_s"] = (
        knee["headline_sessions_per_s"] / 3
    )
    result = diff_metrics(fresh, committed, knee=knee)
    assert any(
        "KNEE" in r["metric"] for r in result["regressions"]
    ), result


def test_cli_exit_codes(tmp_path):
    from tools.bench_diff import main

    committed = _committed()
    clean = tmp_path / "clean.json"
    clean.write_text(json.dumps(committed))
    assert main([str(clean)]) == 0
    bad_doc = json.loads(json.dumps(committed))
    bad_doc["grouping_rows_per_sec"] = 1.0
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(bad_doc))
    assert main([str(bad)]) == 1
    missing = tmp_path / "nope.json"
    assert main([str(missing)]) == 2
