"""Session migration + host-loss recovery, in-process (ISSUE 16).

The tentpole's fold-boundary migration contract: a session moves hosts
as flush-on-old / adopt-on-new through the shared partition store,
carrying BOTH its cumulative algebraic states and its checksummed
schema contract (satellite 2's pin — a drifted producer must be
challenged identically pre- and post-migration). Plus the front tier's
loss path: ring re-hash, adoption, journal replay, typed counters."""

import numpy as np
import pytest

from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.cluster import (
    FrontTier,
    HeartbeatMembership,
    HostLossError,
    LocalWorker,
)
from deequ_tpu.exceptions import SchemaDriftError
from deequ_tpu.service import VerificationService

pytestmark = pytest.mark.cluster


def make_check():
    return Check(CheckLevel.ERROR, "mig").is_complete("id").has_size(
        lambda n: n > 0
    )


def batch(i, rows=16):
    base = i * rows
    return {
        "id": np.arange(base, base + rows, dtype=np.float64),
        "v": np.ones(rows, dtype=np.float64),
    }


def metric_map(result):
    return {
        (type(a).__name__, str(getattr(a, "column", "")), m.name): m.value
        for a, m in result.metrics.items()
    }


@pytest.fixture()
def store_root(tmp_path):
    return str(tmp_path / "store")


def make_worker(host_id, store_root, hb_root=None, ttl_s=5.0):
    service = VerificationService(
        workers=1, background_warm=False, partition_store=store_root
    )
    membership = None
    if hb_root is not None:
        membership = HeartbeatMembership(
            hb_root, host_id=host_id, heartbeat_period_s=0.1, ttl_s=ttl_s
        )
    return LocalWorker(host_id, service, membership=membership)


class TestContractMigration:
    def test_flush_writes_contract_beside_partition_states(
        self, tmp_path, store_root
    ):
        """Satellite 2's mechanism: the flush that moves states into the
        partition store writes the checksummed schema contract beside
        them."""
        import os

        worker = make_worker("w0", store_root)
        session = worker.open_session("t", "events", [make_check()])
        session.ingest(batch(0))
        name = worker.flush("t", "events")
        assert name == "session-t"
        store = worker.service.partition_store
        provider = store.provider("events", name)
        contract_path = os.path.join(provider.path, "schema-contract.json")
        assert os.path.exists(contract_path)
        worker.close()

    def test_migrated_session_enforces_original_contract(self, store_root):
        """THE PIN: a session adopted on a new host must reject a batch
        whose schema drifted from the ORIGINAL session's contract — the
        re-opened session loads the migrated contract instead of
        recapturing one from the drifted producer's first batch."""
        source = make_worker("w0", store_root)
        source.open_session("t", "events", [make_check()])
        source.ingest("t", "events", batch(0))
        assert source.release("t", "events") == "session-t"
        source.close()

        target = make_worker("w1", store_root)
        adopted = target.adopt_session("t", "events", [make_check()])
        assert adopted._contract is not None  # loaded, not recaptured
        drifted = {
            "id": np.arange(16, dtype=np.float64)
            # column "v" dropped: hard drift vs the migrated contract
        }
        with pytest.raises(SchemaDriftError):
            adopted.ingest(drifted)
        # the original schema still folds fine — and resumes the counts
        adopted.ingest(batch(1))
        assert adopted.batches_ingested == 1
        size = [
            m for a, m in adopted.current().metrics.items()
            if type(a).__name__ == "Size"
        ][0]
        assert size.value.get() == 32.0  # 16 pre-migration + 16 post
        target.close()


class TestFrontTierMigration:
    def test_graceful_migration_preserves_metrics(self, store_root, tmp_path):
        front = FrontTier()
        front.add_worker(make_worker("w0", store_root))
        front.add_worker(make_worker("w1", store_root))
        front.open_session("t", "events", [make_check()])
        for i in range(3):
            front.ingest("t", "events", batch(i))
        placed = front.placement("t", "events")
        other = [h for h in front.workers if h != placed][0]
        before = metric_map(
            front.workers[placed].service.get_session("t", "events").current()
        )
        # drain the placed host: its sessions must move gracefully
        front.remove_worker(placed)
        assert front.placement("t", "events") == other
        after = metric_map(
            front.workers[other].service.get_session("t", "events").current()
        )
        assert after == before
        assert front.metrics.counter_value(
            "deequ_service_cluster_migrations_total"
        ) >= 1
        front.close()

    def test_host_loss_recovers_by_salvage_plus_replay(self, store_root):
        """Loss recovery parity: last-flush states from the store + the
        journaled post-flush folds replayed equals the lost session,
        fold for fold — proven by the same metrics as a never-lost
        oracle, and by the typed cluster counters."""
        front = FrontTier()
        front.add_worker(make_worker("w0", store_root))
        front.add_worker(make_worker("w1", store_root))
        front.open_session("t", "events", [make_check()])
        for i in range(2):
            front.ingest("t", "events", batch(i))
        front.flush("t", "events")  # fold boundary: journal clears
        for i in range(2, 5):
            front.ingest("t", "events", batch(i))  # journaled, unflushed

        victim = front.placement("t", "events")
        recovered = front.handle_host_loss(victim)
        assert recovered == [("t", "events")]
        survivor = front.placement("t", "events")
        assert survivor != victim

        oracle = VerificationService(workers=1, background_warm=False)
        session = oracle.session("t", "oracle", [make_check()])
        for i in range(5):
            session.ingest(batch(i))
        want = metric_map(session.current())
        got = metric_map(
            front.workers[survivor].service.get_session(
                "t", "events"
            ).current()
        )
        assert got == want
        m = front.metrics
        assert m.counter_value(
            "deequ_service_cluster_host_losses_total") == 1
        assert m.counter_value(
            "deequ_service_cluster_sessions_recovered_total") == 1
        assert m.counter_value(
            "deequ_service_cluster_replayed_folds_total") == 3
        oracle.close()
        front.close()

    def test_loss_with_no_survivors_raises_typed(self, store_root):
        front = FrontTier()
        front.add_worker(make_worker("w0", store_root))
        front.open_session("t", "events", [make_check()])
        with pytest.raises(HostLossError):
            front.handle_host_loss("w0")

    def test_membership_sweep_drives_recovery(self, store_root, tmp_path):
        """End to end inside one process: a worker that stops beating is
        declared lost by the TTL scan and its sessions recover."""
        hb = str(tmp_path / "hb")
        front = FrontTier(
            membership=HeartbeatMembership(hb, ttl_s=0.4)
        )
        w0 = make_worker("w0", store_root, hb_root=hb, ttl_s=0.4)
        w1 = make_worker("w1", store_root, hb_root=hb, ttl_s=0.4)
        front.add_worker(w0)
        front.add_worker(w1)
        front.open_session("t", "events", [make_check()])
        front.ingest("t", "events", batch(0))
        victim_id = front.placement("t", "events")
        victim = front.workers[victim_id]
        victim.membership.stop()  # the "crash": beats stop, service lives
        import time

        time.sleep(0.8)  # let the TTL lapse
        handled = front.check_membership()
        assert handled == [victim_id]
        assert front.placement("t", "events") != victim_id
        # the survivor replays the only (journaled, never-flushed) fold
        assert front.metrics.counter_value(
            "deequ_service_cluster_replayed_folds_total") == 1
        front.close()
        victim.service.close()


class TestJournalBound:
    def test_force_flush_bounds_replay_memory(self, store_root, monkeypatch):
        """ISSUE 17 satellite: a producer that never calls flush() must
        not grow the replay journal one payload per fold forever — at
        DEEQU_TPU_CLUSTER_JOURNAL_MAX_FOLDS the front tier force-flushes
        the session (AFTER the fold commits) and clears it."""
        monkeypatch.setenv("DEEQU_TPU_CLUSTER_JOURNAL_MAX_FOLDS", "2")
        front = FrontTier()
        for name in ("w0", "w1"):
            front.add_worker(make_worker(name, store_root))
        front.open_session("t", "events", [make_check()])
        for i in range(5):
            front.ingest("t", "events", batch(i))
        # folds 2 and 4 hit the bound and flushed; only fold 5 is journaled
        assert len(front._journal[("t", "events")]) == 1
        assert front.metrics.counter_value(
            "deequ_service_cluster_journal_flushes_total") == 2
        # a host loss now replays ONE fold on top of the flushed states —
        # and recovers all 80 rows exactly
        victim = front.placement("t", "events")
        front.handle_host_loss(victim)
        survivor = front.workers[front.placement("t", "events")]
        session = survivor.service.get_session("t", "events")
        assert front.metrics.counter_value(
            "deequ_service_cluster_replayed_folds_total") == 1
        result = session.current()
        sizes = [m.value.get() for a, m in result.metrics.items()
                 if type(a).__name__ == "Size"]
        assert sizes == [80.0]  # flushed states + replay = every fold
        front.close()

    def test_default_bound_via_config_reexport(self):
        from deequ_tpu.config import CLUSTER_JOURNAL_MAX_FOLDS_ENV
        from deequ_tpu.cluster import (
            DEFAULT_CLUSTER_JOURNAL_MAX_FOLDS,
            cluster_journal_max_folds,
        )

        assert CLUSTER_JOURNAL_MAX_FOLDS_ENV == (
            "DEEQU_TPU_CLUSTER_JOURNAL_MAX_FOLDS"
        )
        assert cluster_journal_max_folds() == (
            DEFAULT_CLUSTER_JOURNAL_MAX_FOLDS
        )
