"""KLL sketch tests: probabilistic rank-error bounds (the reference
`KLL/KLLProbTest.scala` analog), merge = recompute algebra, bucket
distribution semantics, ApproxQuantile(s) accuracy."""

import numpy as np
import pytest

import jax.numpy as jnp

from deequ_tpu.analyzers import (
    ApproxQuantile,
    ApproxQuantiles,
    KLLParameters,
    KLLSketch,
)
from deequ_tpu.data import Dataset
from deequ_tpu.ops.kll import kll_init, kll_merge, kll_update
from deequ_tpu.ops.kll_host import HostKLL
from deequ_tpu.runners import AnalysisRunner


def run(data, *analyzers, **kwargs):
    return AnalysisRunner.do_analysis_run(data, list(analyzers), **kwargs)


def value_of(context, analyzer):
    metric = context.metric(analyzer)
    assert metric is not None, f"no metric for {analyzer}"
    assert metric.value.is_success, f"failure: {metric.value}"
    return metric.value.get()


def fold(values, k=2048, batch=4096):
    state = kll_init(k)
    values = np.asarray(values, dtype=np.float64)
    for start in range(0, len(values), batch):
        chunk = values[start : start + batch]
        padded = np.full(batch, 0.0)
        mask = np.zeros(batch, dtype=bool)
        padded[: len(chunk)] = chunk
        mask[: len(chunk)] = True
        state = kll_update(state, jnp.asarray(padded), jnp.asarray(mask))
    return state


class TestKLLKernel:
    def test_exact_when_small(self):
        vals = np.arange(100, dtype=np.float64)
        state = fold(vals, k=256)
        sketch = HostKLL.from_state(state)
        assert sketch.total_weight == 100
        assert sketch.quantile(0.0) == 0.0
        assert sketch.quantile(1.0) == 99.0
        assert abs(sketch.quantile(0.5) - 49.0) <= 1.0

    def test_count_min_max(self):
        rng = np.random.default_rng(0)
        vals = rng.normal(0, 1, 50000)
        state = fold(vals)
        assert int(state.count) == 50000
        assert float(state.g_min) == vals.min()
        assert float(state.g_max) == vals.max()

    @pytest.mark.parametrize("dist", ["uniform", "normal", "lognormal"])
    def test_rank_error_bound(self, dist):
        rng = np.random.default_rng(42)
        n = 200000
        if dist == "uniform":
            vals = rng.uniform(0, 1, n)
        elif dist == "normal":
            vals = rng.normal(0, 1, n)
        else:
            vals = rng.lognormal(0, 1, n)
        state = fold(vals, k=2048, batch=8192)
        sketch = HostKLL.from_state(state)
        svals = np.sort(vals)
        max_err = 0.0
        for q in [0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99]:
            est = sketch.quantile(q)
            # true rank of the estimate
            true_rank = np.searchsorted(svals, est, side="right") / n
            max_err = max(max_err, abs(true_rank - q))
        # k=2048 should give well under 1% rank error
        assert max_err < 0.01, f"max rank error {max_err} for {dist}"

    def test_merge_matches_union(self):
        rng = np.random.default_rng(1)
        a_vals = rng.normal(0, 1, 30000)
        b_vals = rng.normal(5, 2, 30000)
        sa = fold(a_vals, k=1024)
        sb = fold(b_vals, k=1024)
        merged = kll_merge(sa, sb)
        assert int(merged.count) == 60000
        union = np.sort(np.concatenate([a_vals, b_vals]))
        sketch = HostKLL.from_state(merged)
        for q in [0.1, 0.5, 0.9]:
            est = sketch.quantile(q)
            true_rank = np.searchsorted(union, est, side="right") / 60000
            assert abs(true_rank - q) < 0.02

    def test_weights_approximate_count(self):
        rng = np.random.default_rng(2)
        vals = rng.uniform(0, 1, 100000)
        state = fold(vals, k=1024, batch=4096)
        sketch = HostKLL.from_state(state)
        # total item weight tracks the exact count within subsampling slack
        assert abs(sketch.total_weight - 100000) / 100000 < 0.02

    def test_nan_excluded(self):
        vals = np.array([1.0, np.nan, 2.0, np.nan, 3.0])
        state = fold(vals, k=256)
        assert int(state.count) == 3
        assert float(state.g_max) == 3.0


class TestKLLSketchAnalyzer:
    def test_bucket_distribution(self):
        vals = np.concatenate([np.zeros(50), np.ones(50) * 10])
        data = Dataset.from_dict({"col": vals})
        a = KLLSketch("col", KLLParameters(1024, 0.64, 2))
        dist = value_of(run(data, a), a)
        assert len(dist.buckets) == 2
        assert dist.buckets[0].low_value == 0.0
        assert dist.buckets[-1].high_value == 10.0
        assert dist.buckets[0].count == 50
        assert dist.buckets[1].count == 50
        assert sum(b.count for b in dist.buckets) == 100

    def test_default_params(self, df_numeric):
        a = KLLSketch("att1")
        dist = value_of(run(df_numeric, a), a)
        assert dist.parameters == [0.64, 2048.0]
        assert len(dist.buckets) == 100
        assert sum(b.count for b in dist.buckets) == 6

    def test_compute_percentiles_roundtrip(self, df_numeric):
        a = KLLSketch("att1")
        dist = value_of(run(df_numeric, a), a)
        pcts = dist.compute_percentiles()
        assert len(pcts) == 100
        assert pcts[0] == 1.0
        assert pcts[-1] == 6.0

    def test_too_many_buckets_fails(self, df_numeric):
        a = KLLSketch("att1", KLLParameters(1024, 0.64, 101))
        m = run(df_numeric, a).metric(a)
        assert m.value.is_failure

    def test_non_numeric_fails(self, df_full):
        a = KLLSketch("att1")
        m = run(df_full, a).metric(a)
        assert m.value.is_failure

    def test_incremental_merge_via_states(self):
        from deequ_tpu.analyzers import InMemoryStateProvider

        rng = np.random.default_rng(5)
        vals = rng.normal(0, 1, 20000)
        d1 = Dataset.from_dict({"col": vals[:10000]})
        d2 = Dataset.from_dict({"col": vals[10000:]})
        a = KLLSketch("col")
        s1, s2 = InMemoryStateProvider(), InMemoryStateProvider()
        run(d1, a, save_states_with=s1)
        run(d2, a, save_states_with=s2)
        merged = a.merge_states(s1.load(a), s2.load(a))
        dist = a.compute_metric_from(merged).value.get()
        assert sum(b.count for b in dist.buckets) == pytest.approx(20000, rel=0.02)


class TestApproxQuantile:
    def test_median_exactish(self):
        data = Dataset.from_dict({"col": np.arange(1, 1001, dtype=np.float64)})
        a = ApproxQuantile("col", 0.5)
        est = value_of(run(data, a), a)
        assert abs(est - 500) <= 10

    def test_error_bound(self):
        rng = np.random.default_rng(9)
        vals = rng.normal(100, 15, 100000)
        data = Dataset.from_dict({"col": vals})
        svals = np.sort(vals)
        for q in [0.1, 0.5, 0.9]:
            a = ApproxQuantile("col", q, relative_error=0.01)
            est = value_of(run(data, a), a)
            true_rank = np.searchsorted(svals, est, side="right") / len(vals)
            assert abs(true_rank - q) <= 0.01

    def test_invalid_quantile(self, df_numeric):
        a = ApproxQuantile("att1", 1.5)
        assert run(df_numeric, a).metric(a).value.is_failure

    def test_where(self, df_numeric):
        a = ApproxQuantile("att1", 0.5, where="att1 <= 3")
        est = value_of(run(df_numeric, a), a)
        assert est in (1.0, 2.0)

    def test_empty(self):
        data = Dataset.from_dict({"col": np.array([], dtype=np.float64)})
        a = ApproxQuantile("col", 0.5)
        assert run(data, a).metric(a).value.is_failure


class TestApproxQuantiles:
    def test_keyed_metric(self):
        data = Dataset.from_dict({"col": np.arange(1, 101, dtype=np.float64)})
        a = ApproxQuantiles("col", (0.25, 0.5, 0.75))
        vals = value_of(run(data, a), a)
        assert set(vals) == {"0.25", "0.5", "0.75"}
        assert abs(vals["0.5"] - 50) <= 2

    def test_flatten(self):
        data = Dataset.from_dict({"col": np.arange(1, 101, dtype=np.float64)})
        a = ApproxQuantiles("col", (0.5,))
        metric = run(data, a).metric(a)
        flat = metric.flatten()
        assert flat[0].name == "ApproxQuantiles-0.5"
