"""Streaming schema-drift drills (ISSUE 4 acceptance): a session fed a
retyped column rejects/coerces/degrades per policy, with persisted states
untouched on reject; widenings coerce with fold parity; the
batch-count/column-name mismatch that used to silently mis-fold is an
immediate typed error."""

import numpy as np
import pytest

from deequ_tpu.analyzers import Completeness, Mean, Size, Sum
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import SchemaDriftError
from deequ_tpu.service import SchemaContract, VerificationService


def _batch(rows=64, x_dtype=np.int64, with_y=True, y_values=None, extra=False):
    cols = {"x": np.arange(rows, dtype=x_dtype)}
    if with_y:
        cols["y"] = (
            y_values if y_values is not None
            else np.arange(rows, dtype=np.float64)
        )
    if extra:
        cols["z"] = np.ones(rows)
    return Dataset.from_dict(cols)


def _checks():
    return [
        Check(CheckLevel.ERROR, "drift battery")
        .has_size(lambda n: n > 0)
        .has_mean("y", lambda m: m >= 0)
        .is_complete("x"),
    ]


@pytest.fixture
def service():
    with VerificationService(workers=2, background_warm=False) as svc:
        yield svc


def _state_snapshot(session):
    """Every persisted state's leaves as host numpy (order-stable)."""
    import jax

    out = {}
    for analyzer in session.provider.analyzers():
        leaves = jax.tree_util.tree_leaves(session.provider.load(analyzer))
        out[repr(analyzer)] = [np.asarray(l).copy() for l in leaves]
    return out


def _assert_states_equal(a, b):
    assert a.keys() == b.keys()
    for key in a:
        for la, lb in zip(a[key], b[key]):
            np.testing.assert_array_equal(la, lb)


class TestContractUnit:
    def test_capture_records_names_dtypes_encoding(self):
        import pyarrow as pa

        data = Dataset.from_arrow(
            pa.table(
                {
                    "n": pa.array(np.arange(8, dtype=np.int32)),
                    "s": pa.array(["a", "b"] * 4),
                    "d": pa.DictionaryArray.from_arrays(
                        pa.array([0, 1] * 4, type=pa.int32()),
                        pa.array(["u", "v"]),
                    ),
                }
            )
        )
        contract = SchemaContract.capture(data)
        by_name = {c.name: c for c in contract.columns}
        assert by_name["n"].dtype == "int32" and not by_name["n"].dictionary
        assert by_name["s"].dtype == "string"
        assert by_name["d"].dictionary and by_name["d"].dtype == "string"

    def test_reordered_columns_are_not_drift(self):
        first = Dataset.from_dict(
            {"a": np.arange(4, dtype=np.int64), "b": np.ones(4)}
        )
        contract = SchemaContract.capture(first)
        reordered = Dataset.from_dict(
            {"b": np.ones(4), "a": np.arange(4, dtype=np.int64)}
        )
        report = contract.validate(reordered)
        assert report.table is None and not report.coercions

    def test_widening_coerces_under_every_policy(self):
        contract = SchemaContract.capture(
            Dataset.from_dict({"a": np.arange(4, dtype=np.int64)})
        )
        narrow = Dataset.from_dict({"a": np.arange(4, dtype=np.int32)})
        for policy in ("reject", "coerce", "degrade"):
            report = contract.validate(narrow, policy=policy)
            assert report.coercions == ["a: int32 -> int64"]
            assert str(report.table.schema.field("a").type) == "int64"

    def test_narrowing_is_drift_not_widening(self):
        contract = SchemaContract.capture(
            Dataset.from_dict({"a": np.arange(4, dtype=np.int32)})
        )
        wide = Dataset.from_dict({"a": np.arange(4, dtype=np.int64)})
        with pytest.raises(SchemaDriftError, match="retyped"):
            contract.validate(wide)

    def test_int_to_float_is_not_a_widening(self):
        contract = SchemaContract.capture(
            Dataset.from_dict({"a": np.arange(4, dtype=np.float64)})
        )
        ints = Dataset.from_dict({"a": np.arange(4, dtype=np.int64)})
        with pytest.raises(SchemaDriftError, match="retyped"):
            contract.validate(ints)

    def test_coerce_rejects_unrepresentable_values(self):
        contract = SchemaContract.capture(
            Dataset.from_dict({"a": np.arange(4, dtype=np.int64)})
        )
        words = Dataset.from_dict({"a": ["not", "a", "number", "!"]})
        with pytest.raises(SchemaDriftError, match="cannot be coerced"):
            contract.validate(words, policy="coerce")

    def test_coerce_casts_castable_retypes(self):
        contract = SchemaContract.capture(
            Dataset.from_dict({"a": np.arange(4, dtype=np.int64)})
        )
        digits = Dataset.from_dict({"a": ["0", "1", "2", "3"]})
        report = contract.validate(digits, policy="coerce")
        assert str(report.table.schema.field("a").type) == "int64"
        assert report.table["a"].to_pylist() == [0, 1, 2, 3]

    def test_dropped_column_never_coercible(self):
        contract = SchemaContract.capture(
            Dataset.from_dict({"a": np.ones(4), "b": np.ones(4)})
        )
        missing = Dataset.from_dict({"a": np.ones(4)})
        for policy in ("reject", "coerce"):
            with pytest.raises(SchemaDriftError, match="dropped"):
                contract.validate(missing, policy=policy)

    def test_dictionary_flip_is_drift(self):
        import pyarrow as pa

        contract = SchemaContract.capture(
            Dataset.from_arrow(
                pa.table(
                    {
                        "d": pa.DictionaryArray.from_arrays(
                            pa.array([0, 1] * 4, type=pa.int32()),
                            pa.array(["u", "v"]),
                        )
                    }
                )
            )
        )
        plain = Dataset.from_arrow(
            pa.table({"d": pa.array(["u", "v"] * 4)}),
        )
        # a plain column where a dictionary was promised: reject raises,
        # coerce re-encodes
        if plain.arrow.schema.field("d").type == "string":
            with pytest.raises(SchemaDriftError, match="dictionary"):
                contract.validate(plain)
            report = contract.validate(plain, policy="coerce")
            import pyarrow as pa2

            assert pa2.types.is_dictionary(report.table.schema.field("d").type)

    def test_invalid_policy_rejected(self):
        contract = SchemaContract.capture(Dataset.from_dict({"a": np.ones(2)}))
        with pytest.raises(ValueError, match="drift_policy"):
            contract.validate(
                Dataset.from_dict({"a": np.ones(2)}), policy="panic"
            )


class TestSessionDriftGuard:
    def test_column_name_mismatch_is_immediate_typed_error(self, service):
        """The PR-4 satellite bugfix: the session used to only STORE the
        first schema and silently mis-fold renamed/added columns."""
        session = service.session("t", "names", _checks())
        session.ingest(_batch())
        renamed = Dataset.from_dict(
            {"x": np.arange(64, dtype=np.int64), "y2": np.ones(64)}
        )
        with pytest.raises(SchemaDriftError) as err:
            session.ingest(renamed)
        assert "dropped" in str(err.value) and "added" in str(err.value)
        assert session.batches_ingested == 1  # nothing folded

    def test_reject_leaves_persisted_states_bit_exact(self, service):
        session = service.session("t", "reject", _checks())
        session.ingest(_batch(rows=128))
        session.ingest(_batch(rows=64))
        before = _state_snapshot(session)
        retyped = _batch(
            rows=64, y_values=np.array([f"s{i}" for i in range(64)])
        )
        with pytest.raises(SchemaDriftError, match="retyped"):
            session.ingest(retyped)
        _assert_states_equal(before, _state_snapshot(session))
        assert session.batches_ingested == 2

    def test_widened_fold_parity_with_native_batches(self, service):
        """Folding an int32 batch into an int64 session equals folding the
        same values natively int64 — the coercion is exact."""
        a = service.session("t", "widen-a", _checks())
        b = service.session("t", "widen-b", _checks())
        a.ingest(_batch(rows=128))
        b.ingest(_batch(rows=128))
        a.ingest(_batch(rows=64, x_dtype=np.int32))  # widened
        b.ingest(_batch(rows=64, x_dtype=np.int64))  # native
        assert a.drift_coercions == 1 and b.drift_coercions == 0
        _assert_states_equal(_state_snapshot(a), _state_snapshot(b))

    def test_degrade_folds_the_rest_and_fails_affected(self, service):
        session = service.session(
            "t", "degrade", _checks(), drift_policy="degrade"
        )
        session.ingest(_batch(rows=128))
        retyped = _batch(
            rows=64, y_values=np.array([f"s{i}" for i in range(64)])
        )
        result = session.ingest(retyped)
        assert result.status != CheckStatus.SUCCESS
        statuses = {
            type(a).__name__: m.value.is_success
            for a, m in result.metrics.items()
        }
        assert statuses["Mean"] is False        # over the drifted column
        assert statuses["Size"] is True         # kept folding
        assert statuses["Completeness"] is True
        assert session.drift_degraded_batches == 1
        assert session.batches_ingested == 2
        # the unaffected analyzers' states ADVANCED to 128 + 64 rows
        size_state = session.provider.load(Size())
        assert int(np.asarray(size_state.num_matches)) == 192

    def test_contract_commits_only_after_first_fold_succeeds(self, service):
        """A first batch whose fold RAISES never folded — its schema must
        not pin the session (a wrong-schema first batch would otherwise
        reject every corrected batch after it)."""
        from deequ_tpu.reliability import FaultSpec, WorkerCrash, inject
        from deequ_tpu.service import JobFailed

        session = service.session("t", "firstfail", _checks())
        with inject(FaultSpec("stream_fold", "worker_death", at=1)):
            with pytest.raises(JobFailed):
                session.ingest(_batch(rows=32, x_dtype=np.int32))
        assert session._contract is None  # nothing folded, nothing pinned
        # a DIFFERENT schema now captures cleanly as the contract
        r = session.ingest(_batch(rows=64))
        assert r.status == CheckStatus.SUCCESS
        assert {c.dtype for c in session._contract.columns} == {
            "int64", "double"
        }

    def test_degrade_surfaces_added_column_on_counters(self, service):
        """An added column under `degrade` folds without it, but the drift
        must still surface (counter + warning), not vanish silently."""
        session = service.session(
            "t", "deg-add", _checks(), drift_policy="degrade"
        )
        session.ingest(_batch(rows=128))
        r = session.ingest(_batch(rows=64, extra=True))
        assert r.status == CheckStatus.SUCCESS  # no analyzer was affected
        assert session.drift_degraded_batches == 1
        counters = service.json_snapshot()["counters"]
        assert (
            counters["deequ_service_drift_degraded_total"][
                "dataset=deg-add,tenant=t"
            ]
            == 1.0
        )

    def test_coerce_drops_added_columns_and_folds(self, service):
        session = service.session(
            "t", "coerce", _checks(), drift_policy="coerce"
        )
        session.ingest(_batch(rows=128))
        result = session.ingest(_batch(rows=64, extra=True))
        assert result.status == CheckStatus.SUCCESS
        assert session.batches_ingested == 2
        # the repaired hard drift is VISIBLE, not silently consumed
        assert session.drift_repaired_batches == 1
        counters = service.json_snapshot()["counters"]
        assert (
            counters["deequ_service_drift_repairs_total"][
                "dataset=coerce,tenant=t"
            ]
            == 1.0
        )

    def test_contract_survives_process_restart(self, tmp_path):
        """A durably-backed session persists its contract beside the
        states: a NEW session (new process in real life) over the same
        store validates its FIRST batch against the old contract instead
        of letting a drifted producer re-capture it."""
        from deequ_tpu.service import VerificationService

        root = str(tmp_path / "states")
        with VerificationService(
            workers=2, background_warm=False, state_root=root
        ) as svc:
            s = svc.session("t", "durable", _checks())
            s.ingest(_batch(rows=128))
            assert s._contract is not None
        # "restart": a fresh service + session over the same state root
        with VerificationService(
            workers=2, background_warm=False, state_root=root
        ) as svc:
            s2 = svc.session("t", "durable", _checks())
            assert s2._contract is not None  # loaded, not None
            retyped = _batch(
                rows=64, y_values=np.array([f"s{i}" for i in range(64)])
            )
            with pytest.raises(SchemaDriftError, match="retyped"):
                s2.ingest(retyped)  # FIRST post-restart batch: rejected
            assert s2.batches_ingested == 0
            # a conforming batch still folds
            r = s2.ingest(_batch(rows=64))
            assert r.status == CheckStatus.SUCCESS

    def test_corrupt_contract_file_recaptures(self, tmp_path):
        from deequ_tpu.service import VerificationService

        root = str(tmp_path / "states")
        with VerificationService(
            workers=2, background_warm=False, state_root=root
        ) as svc:
            s = svc.session("t", "durable", _checks())
            s.ingest(_batch(rows=128))
            path = s._contract_path()
        raw = open(path).read()
        i = raw.index("int64") + 1
        open(path, "w").write(raw[:i] + "X" + raw[i + 1:])
        with VerificationService(
            workers=2, background_warm=False, state_root=root
        ) as svc:
            s2 = svc.session("t", "durable", _checks())
            assert s2._contract is None  # corrupt file -> recapture
            r = s2.ingest(_batch(rows=64))
            assert r.status == CheckStatus.SUCCESS

    def test_drift_metrics_exported(self, service):
        session = service.session("t", "metrics", _checks())
        session.ingest(_batch())
        with pytest.raises(SchemaDriftError):
            session.ingest(_batch(extra=True))
        counters = service.json_snapshot()["counters"]
        assert (
            counters["deequ_service_drift_rejections_total"][
                "dataset=metrics,tenant=t"
            ]
            == 1.0
        )


@pytest.mark.chaos
class TestInjectedDrift:
    def test_stream_fold_drift_injection_rejects_before_fold(self, service):
        from deequ_tpu.reliability import FaultSpec, inject

        session = service.session("t", "chaos-drift", _checks())
        session.ingest(_batch())
        before = _state_snapshot(session)
        with inject(FaultSpec("stream_fold", "drift", at=1)) as inj:
            with pytest.raises(SchemaDriftError):
                session.ingest(_batch())
            # the injected drift consumed its budget; the next ingest folds
            result = session.ingest(_batch())
        assert inj.fired == ["stream_fold:t/chaos-drift#1:drift"]
        assert result.status == CheckStatus.SUCCESS
        assert session.batches_ingested == 2
        # the rejected ingest mutated nothing: states advanced exactly one
        # batch past the snapshot
        size_state = session.provider.load(Size())
        assert int(np.asarray(size_state.num_matches)) == 128
        assert before  # snapshot sanity
