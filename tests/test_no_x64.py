"""32-bit accumulator mode (DEEQU_TPU_NO_X64=1).

The engine's default is f64 accumulators for ±1e-6 Spark parity; the
documented opt-out (`config.py`) falls back to f32/int32. That mode also
takes the OTHER branch of the packed-carry int vector (int32 slots — the
reason floats and ints pack separately, see engine.PackedScanProgram), so
it needs coverage even though parity-focused CI runs x64. jax pins x64 at
import time, so the 32-bit run happens in a subprocess.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np

_PROG = r"""
import os, json
os.environ["DEEQU_TPU_NO_X64"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.analyzers import (
    ApproxCountDistinct, Completeness, Maximum, Mean, Minimum, Size,
    StandardDeviation, Sum,
)

rng = np.random.default_rng(11)
n = 20_000
x = rng.normal(50.0, 4.0, n)
data = Dataset.from_dict({"x": x, "y": rng.integers(0, 500, n)})
analyzers = [
    Size(), Completeness("x"), Mean("x"), Sum("x"), Minimum("x"),
    Maximum("x"), StandardDeviation("x"), ApproxCountDistinct("y"),
]
ctx = AnalysisRunner.do_analysis_run(data, analyzers, batch_size=4096,
                                     placement="device")
out = {}
for a, m in ctx.metric_map.items():
    assert m.value.is_success, (a.name, m.value)
    out[a.name] = m.value.get()
out["__oracle_mean__"] = float(x.mean())
out["__oracle_sum__"] = float(x.sum())
out["__oracle_std__"] = float(x.std())
print(json.dumps(out))
"""


class TestNoX64Mode:
    def test_engine_runs_and_approximates_in_f32(self):
        env = dict(os.environ)
        env.pop("DEEQU_TPU_PLACEMENT", None)
        proc = subprocess.run(
            [sys.executable, "-c", _PROG],
            capture_output=True,
            text=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        vals = json.loads(proc.stdout.strip().splitlines()[-1])

        assert vals["Size"] == 20_000.0
        assert vals["Completeness"] == 1.0
        # f32 accumulation over 20k values of magnitude ~50: relative error
        # bounded by ~sqrt(n)*eps_f32 with batched reduction — 1e-4 is loose
        for key, want in (
            ("Mean", vals["__oracle_mean__"]),
            ("Sum", vals["__oracle_sum__"]),
            ("StandardDeviation", vals["__oracle_std__"]),
        ):
            got = vals[key]
            assert abs(got - want) <= 1e-4 * max(1.0, abs(want)), (key, got, want)
        # HLL registers are integer state: estimate must stay in the normal
        # 5%-relativeSD envelope regardless of accumulator width
        assert abs(vals["ApproxCountDistinct"] - 500) <= 0.2 * 500
        # the mode must have ACTUALLY taken effect: an f32-accumulated min
        # is exactly f32-representable, while under a silently-still-f64
        # engine the minimum of 20k normal draws is f32-inexact with
        # near-certainty (P[53-bit value hits a 24-bit grid point] ~ 2^-29)
        assert vals["Minimum"] == float(np.float32(vals["Minimum"]))
