"""Native C++ host kernels: bit-exact equivalence with the pure-Python
fallbacks (hashing must also match Spark's XxHash64 semantics, which the
python reference implementation in ops/hashing.py encodes)."""

import numpy as np
import pytest

pytest.importorskip("deequ_tpu")

from deequ_tpu.ops.hashing import xxhash64_bytes


@pytest.fixture(scope="module")
def native():
    try:
        from deequ_tpu.native import lib
    except Exception as exc:  # noqa: BLE001
        pytest.skip(f"native lib unavailable: {exc}")
    return lib


@pytest.fixture(scope="module")
def sample_values():
    rng = np.random.default_rng(0)
    values = []
    for i in range(2000):
        kind = i % 8
        if kind == 0:
            values.append(None)
        elif kind == 1:
            values.append("")
        elif kind == 2:
            values.append(str(rng.integers(-10**9, 10**9)))
        elif kind == 3:
            values.append(f"{rng.normal():.6f}")
        elif kind == 4:
            values.append("true" if i % 2 else "false")
        elif kind == 5:
            values.append("héllo wörld ünïcode " * (i % 5 + 1))
        elif kind == 6:
            values.append("x" * (i % 100))
        else:
            values.append("- 5" if i % 2 else "+ 3.14")
    return np.array(values, dtype=object)


class TestNativeKernels:
    def test_xxhash64_matches_python(self, native, sample_values):
        out = native.native_xxhash64_strings(sample_values, 42)
        for i, v in enumerate(sample_values):
            expected = 42 if v is None else xxhash64_bytes(v.encode("utf-8"), 42)
            assert out[i] == expected, (i, v)

    def test_classify_matches_python(self, native, sample_values):
        import deequ_tpu.runners.features as feats
        from deequ_tpu.data import ColumnKind

        mask = np.array([v is not None for v in sample_values])
        got = native.native_classify_types(sample_values, mask)
        # pure-python path: temporarily disable the native hook
        orig = feats.classify_type_codes.__globals__  # noqa: F841
        import deequ_tpu.native as native_pkg

        saved = native_pkg.native_classify_types
        try:
            native_pkg.native_classify_types = None
            expected = feats.classify_type_codes(sample_values, mask, ColumnKind.STRING)
        finally:
            native_pkg.native_classify_types = saved
        np.testing.assert_array_equal(got, expected)

    def test_lengths_match_python(self, native, sample_values):
        mask = np.array([v is not None for v in sample_values])
        got = native.native_string_lengths(sample_values, mask)
        for i, v in enumerate(sample_values):
            assert got[i] == (len(v) if mask[i] else 0), (i, v)

    def test_wired_into_features(self, native):
        """After the native lib builds, the feature frontend uses it."""
        import importlib

        import deequ_tpu.native as native_pkg

        importlib.reload(native_pkg)
        assert native_pkg.native_xxhash64_strings is not None

    def test_hash_column_consistency(self, native):
        """End-to-end: ApproxCountDistinct over strings gives identical
        registers with and without the native path."""
        from deequ_tpu.analyzers import ApproxCountDistinct
        from deequ_tpu.data import Dataset
        from deequ_tpu.runners import AnalysisRunner
        import deequ_tpu.native as native_pkg

        data = Dataset.from_dict({"s": [f"value-{i}" for i in range(5000)]})
        a = ApproxCountDistinct("s")
        with_native = AnalysisRunner.do_analysis_run(data, [a]).metric(a).value.get()
        saved = native_pkg.native_xxhash64_strings
        try:
            native_pkg.native_xxhash64_strings = None
            without = AnalysisRunner.do_analysis_run(data, [a]).metric(a).value.get()
        finally:
            native_pkg.native_xxhash64_strings = saved
        assert with_native == without


class TestRegexSemantics:
    def test_java_regex_parity(self, native):
        """Trailing newline and unicode digits are STRING in both paths
        (Java Matcher semantics the reference uses)."""
        import deequ_tpu.native as native_pkg
        import deequ_tpu.runners.features as feats
        from deequ_tpu.data import ColumnKind

        tricky = np.array(["5\n", "٥", "１２", "5", "1.5"], dtype=object)
        mask = np.ones(5, dtype=bool)
        got_native = native.native_classify_types(tricky, mask)
        saved = native_pkg.native_classify_types
        try:
            native_pkg.native_classify_types = None
            got_python = feats.classify_type_codes(tricky, mask, ColumnKind.STRING)
        finally:
            native_pkg.native_classify_types = saved
        np.testing.assert_array_equal(got_native, got_python)
        # 5\n, arabic digit, fullwidth digits -> STRING; "5" -> INTEGRAL; "1.5" -> FRACTIONAL
        assert list(got_python) == [4, 4, 4, 2, 1]


class TestPythonFallbackArrowInputs:
    def test_xxhash64_strings_fallback_handles_arrow_nulls(self, monkeypatch):
        """The pure-python fallback must hash arrow-array inputs (the lazy
        dictionary payload) identically to object arrays — in particular a
        NULL entry hashes to the seed, not to the literal string 'None'."""
        import numpy as np
        import pyarrow as pa

        import deequ_tpu.native as native
        from deequ_tpu.ops import hashing

        monkeypatch.setattr(native, "native_xxhash64_strings", None)
        arr = pa.array(["a", None, "None", ""])
        obj = np.array(["a", None, "None", ""], dtype=object)
        got = hashing.xxhash64_strings(arr, 42)
        want = hashing.xxhash64_strings(obj, 42)
        np.testing.assert_array_equal(got, want)
        assert got[1] == 42  # null -> seed
        assert got[2] != 42  # a REAL "None" string must not collide
