"""Ports of two named reference test suites (VERDICT r5 ask #6).

- ``KLLProfileTest.scala`` (reference `src/test/scala/com/amazon/deequ/KLL/
  KLLProfileTest.scala`): column profiling with KLL sketches — default and
  custom parameters, bucket structure, end-anchored bounds, exact bucket
  counts on known data, and KLL absence on non-numeric columns.
- ``MetricsRepositoryMultipleResultsLoaderTest.scala`` (reference
  `src/test/scala/com/amazon/deequ/repository/
  MetricsRepositoryMultipleResultsLoaderTest.scala`): the multi-result
  query loader's filter combinations — tag values, analyzer subsets,
  after/before time windows, their compositions, and the
  DataFrame/JSON success-metric projections with tag columns.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    KLLParameters,
    Size,
)
from deequ_tpu.data import Dataset
from deequ_tpu.profiles import ColumnProfiler, NumericColumnProfile
from deequ_tpu.repository import (
    AnalysisResult,
    InMemoryMetricsRepository,
    ResultKey,
)
from deequ_tpu.runners import AnalysisRunner


# ---------------------------------------------------------------------------
# KLLProfileTest.scala analog
# ---------------------------------------------------------------------------


@pytest.fixture
def kll_profile_data():
    # 1..100 complete + a column with nulls + a plain string column
    vals = np.arange(1, 101, dtype=np.float64)
    with_nulls = vals.copy()
    import pyarrow as pa

    table = pa.table(
        {
            "att1": pa.array(vals),
            "att2": pa.array(with_nulls, mask=np.arange(100) % 4 == 0),
            "att3": pa.array([f"s{i % 7}" for i in range(100)]),
        }
    )
    return Dataset.from_arrow(table)


class TestKLLProfile:
    """`KLLProfileTest.scala` — "basic profile with KLL" scenarios."""

    def test_default_profile_attaches_kll_to_numeric_columns(self, kll_profile_data):
        profiles = ColumnProfiler.profile(kll_profile_data)
        p = profiles["att1"]
        assert isinstance(p, NumericColumnProfile)
        assert p.kll is not None
        assert p.approx_percentiles  # non-empty, sorted
        assert p.approx_percentiles == sorted(p.approx_percentiles)

    def test_custom_parameters_are_recorded_and_honored(self, kll_profile_data):
        params = KLLParameters(
            sketch_size=512, shrinking_factor=0.64, number_of_buckets=10
        )
        profiles = ColumnProfiler.profile(
            kll_profile_data, kll_parameters=params
        )
        kll = profiles["att1"].kll
        assert len(kll.buckets) == 10
        # parameters ride the distribution as [shrinkingFactor, sketchSize]
        # (reference KLLProfileTest asserts the same pair)
        assert kll.parameters == [0.64, 512.0]

    def test_bucket_bounds_anchor_at_global_min_max(self, kll_profile_data):
        params = KLLParameters(2048, 0.64, 4)
        profiles = ColumnProfiler.profile(
            kll_profile_data, kll_parameters=params
        )
        kll = profiles["att1"].kll
        assert kll.buckets[0].low_value == 1.0
        assert kll.buckets[-1].high_value == 100.0

    def test_exact_bucket_counts_on_uniform_data(self, kll_profile_data):
        # 100 distinct values 1..100, sketch far larger than the data: the
        # sketch is lossless, so 2 equi-width buckets split exactly 50/50
        # and telescope to the exact row count
        params = KLLParameters(2048, 0.64, 2)
        profiles = ColumnProfiler.profile(
            kll_profile_data, kll_parameters=params
        )
        kll = profiles["att1"].kll
        counts = [b.count for b in kll.buckets]
        assert sum(counts) == 100
        assert counts == [50, 50]

    def test_null_values_are_excluded_from_the_sketch(self, kll_profile_data):
        params = KLLParameters(2048, 0.64, 2)
        profiles = ColumnProfiler.profile(
            kll_profile_data, kll_parameters=params
        )
        kll = profiles["att2"].kll
        assert kll is not None
        assert sum(b.count for b in kll.buckets) == 75  # 25 of 100 are null

    def test_string_column_has_no_kll(self, kll_profile_data):
        profiles = ColumnProfiler.profile(kll_profile_data)
        assert not isinstance(profiles["att3"], NumericColumnProfile)

    def test_restricted_columns_only_profile_kll_where_asked(self, kll_profile_data):
        profiles = ColumnProfiler.profile(
            kll_profile_data, restrict_to_columns=["att1"]
        )
        assert set(profiles.profiles) == {"att1"}
        assert profiles["att1"].kll is not None


# ---------------------------------------------------------------------------
# MetricsRepositoryMultipleResultsLoaderTest.scala analog
# ---------------------------------------------------------------------------


@pytest.fixture
def filled_repository():
    """Two datasets' results under distinct tags and timestamps
    (reference fixture: two DataFrames saved under `DataSet -> train/test`
    tags at different dateTimes)."""
    data_train = Dataset.from_dict(
        {"item": ["1", "2", "3", "4"], "att1": ["a", "b", None, "d"]}
    )
    data_test = Dataset.from_dict(
        {"item": ["5", "6", "7", "8", "9"], "att1": ["x", None, None, "y", "z"]}
    )
    repo = InMemoryMetricsRepository()
    analyzers = [Size(), Completeness("att1"), ApproxCountDistinct("item")]
    key_train = ResultKey(1000, {"dataset": "train", "region": "eu"})
    key_test = ResultKey(2000, {"dataset": "test", "region": "eu"})
    for data, key in ((data_train, key_train), (data_test, key_test)):
        AnalysisRunner.do_analysis_run(
            data,
            analyzers,
            metrics_repository=repo,
            save_or_append_results_with_key=key,
        )
    return repo, key_train, key_test


class TestMetricsRepositoryMultipleResultsLoader:
    """`MetricsRepositoryMultipleResultsLoaderTest.scala` filter combos."""

    def test_get_all_results(self, filled_repository):
        repo, key_train, key_test = filled_repository
        results = repo.load().get()
        assert {r.result_key for r in results} == {key_train, key_test}
        for r in results:
            assert isinstance(r, AnalysisResult)
            assert r.analyzer_context.metric(Size()).value.is_success

    def test_filter_by_tag_values(self, filled_repository):
        repo, key_train, _ = filled_repository
        results = repo.load().with_tag_values({"dataset": "train"}).get()
        assert [r.result_key for r in results] == [key_train]
        # a shared tag matches both; an absent tag value matches none
        assert len(repo.load().with_tag_values({"region": "eu"}).get()) == 2
        assert repo.load().with_tag_values({"dataset": "holdout"}).get() == []

    def test_filter_for_analyzers(self, filled_repository):
        repo, _, _ = filled_repository
        results = repo.load().for_analyzers([Size()]).get()
        assert len(results) == 2
        for r in results:
            assert set(r.analyzer_context.metric_map) == {Size()}

    def test_after_and_before_time_windows(self, filled_repository):
        repo, key_train, key_test = filled_repository
        assert [
            r.result_key for r in repo.load().after(1500).get()
        ] == [key_test]
        assert [
            r.result_key for r in repo.load().before(1500).get()
        ] == [key_train]
        # bounds are inclusive (reference: getAllResults with after =
        # exact dateTime still returns that result)
        assert len(repo.load().after(1000).get()) == 2
        assert len(repo.load().before(2000).get()) == 2
        # combined window isolating nothing
        assert repo.load().after(1200).before(1800).get() == []

    def test_combined_tag_analyzer_time_filters(self, filled_repository):
        repo, _, key_test = filled_repository
        results = (
            repo.load()
            .after(1500)
            .with_tag_values({"dataset": "test"})
            .for_analyzers([Completeness("att1")])
            .get()
        )
        assert [r.result_key for r in results] == [key_test]
        (context,) = [r.analyzer_context for r in results]
        assert set(context.metric_map) == {Completeness("att1")}
        assert context.metric(Completeness("att1")).value.get() == pytest.approx(
            3 / 5
        )

    def test_success_metrics_as_records_with_tag_columns(self, filled_repository):
        repo, _, _ = filled_repository
        records = repo.load().get_success_metrics_as_records(
            with_tags=["dataset"]
        )
        assert {r["dataset"] for r in records} == {"train", "test"}
        size_rows = [r for r in records if r["name"] == "Size"]
        assert {r["value"] for r in size_rows} == {4.0, 5.0}
        for r in records:
            assert {"entity", "instance", "name", "value", "dataset_date"} <= set(r)

    def test_success_metrics_as_json_round_trips(self, filled_repository):
        repo, _, _ = filled_repository
        payload = json.loads(
            repo.load()
            .with_tag_values({"dataset": "train"})
            .get_success_metrics_as_json(with_tags=["dataset", "region"])
        )
        assert all(row["dataset"] == "train" for row in payload)
        assert all(row["region"] == "eu" for row in payload)
        assert {row["name"] for row in payload} == {
            "Size", "Completeness", "ApproxCountDistinct",
        }

    def test_data_frame_projection(self, filled_repository):
        repo, _, _ = filled_repository
        df = repo.load().get_success_metrics_as_data_frame(with_tags=["dataset"])
        assert set(df.columns) >= {"entity", "instance", "name", "value", "dataset"}
        assert len(df) == 6  # 3 analyzers x 2 results
