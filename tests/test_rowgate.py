"""Row-level ingest gating: the streaming promotion of the batch
RowLevelSchemaValidator onto the Arrow ingest path.

The reference `schema/RowLevelSchemaValidatorTest.scala` scenarios run
here against BOTH paths — the batch validator and the streaming gate —
and every scenario must produce the identical valid/invalid split: the
gate calls the exact conformance pass the validator uses, and this file
pins that they can never diverge."""

import numpy as np
import pyarrow as pa
import pytest

from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.data import Dataset
from deequ_tpu.ingest import (
    FrameQuarantinedError,
    QuarantineSidecar,
    RowGate,
)
from deequ_tpu.reliability import FaultSpec, inject
from deequ_tpu.schema import RowLevelSchema, RowLevelSchemaValidator
from deequ_tpu.service import VerificationService
from deequ_tpu.service.metrics import ServiceMetrics

pytestmark = pytest.mark.catalog


def _split_via_gate(data, schema, tmp_path=None):
    """Run one frame through a fresh RowGate; returns (accepted_dataset,
    rejected_table_or_None). A full rejection surfaces as (None, table)."""
    sidecar = QuarantineSidecar(str(tmp_path / "q")) if tmp_path else None
    gate = RowGate(schema, sidecar=sidecar, metrics=ServiceMetrics())
    try:
        accepted = gate.split(data, "t", "d")
    except FrameQuarantinedError:
        accepted = None
    rejected = sidecar.read_all("t", "d") if sidecar else None
    return accepted, rejected


#: the reference RowLevelSchemaValidatorTest scenarios: (columns,
#: schema builder, expected valid count). Each runs through the batch
#: validator AND the streaming gate, and both must agree row for row.
_SCENARIOS = [
    (
        "int_cast_non_nullable",
        {"id": ["1", "2", "not-a-number", "4", None],
         "name": list("abcde")},
        lambda s: s.with_int_column("id", is_nullable=False),
        3,
    ),
    (
        "int_bounds",
        {"v": ["5", "15", "25"]},
        lambda s: s.with_int_column("v", min_value=10, max_value=20),
        1,
    ),
    (
        "string_length_and_regex",
        {"code": ["AB", "ABC", "ABCD", "xy", None]},
        lambda s: s.with_string_column(
            "code", min_length=2, max_length=3, matches="^[A-Z]+$"
        ),
        3,
    ),
    (
        "non_nullable_string",
        {"x": ["a", None, "b"]},
        lambda s: s.with_string_column("x", is_nullable=False),
        2,
    ),
    (
        "decimal_precision_scale",
        {"d": ["12.34", "123456.7", "abc"]},
        lambda s: s.with_decimal_column("d", precision=6, scale=2),
        1,
    ),
    (
        "timestamp_mask",
        {"ts": ["2024-01-31 10:30:00", "not a date",
                "2024-13-99 99:99:99"]},
        lambda s: s.with_timestamp_column("ts", mask="yyyy-MM-dd HH:mm:ss"),
        1,
    ),
    (
        "multi_column_cnf",
        {"id": ["1", "2", "x"], "name": ["alice", "bob", "carol"]},
        lambda s: (s.with_int_column("id", is_nullable=False)
                   .with_string_column("name", max_length=5)),
        2,
    ),
]


class TestGateValidatorParity:
    @pytest.mark.parametrize(
        "name,columns,build,expected_valid",
        _SCENARIOS, ids=[s[0] for s in _SCENARIOS],
    )
    def test_identical_verdicts(
        self, name, columns, build, expected_valid, tmp_path
    ):
        data = Dataset.from_dict(columns)
        schema = build(RowLevelSchema())
        result = RowLevelSchemaValidator.validate(data, schema)
        assert result.num_valid_rows == expected_valid

        accepted, rejected = _split_via_gate(data, schema, tmp_path)
        gate_valid = 0 if accepted is None else accepted.num_rows
        gate_invalid = 0 if rejected is None else rejected.num_rows
        assert gate_valid == result.num_valid_rows
        assert gate_invalid == result.num_invalid_rows

    def test_cast_semantics_differ_by_design(self):
        """The VALIDATOR casts its valid side (string "1" becomes int 1,
        the reference's `castColumn`); the GATE keeps the original Arrow
        buffers untouched so clean rows fold bit-exact. Same verdicts,
        different output types — pinned so nobody 'fixes' one into the
        other."""
        data = Dataset.from_dict({"id": ["1", "2", "x"]})
        schema = RowLevelSchema().with_int_column("id")
        result = RowLevelSchemaValidator.validate(data, schema)
        assert list(result.valid_rows.to_pandas()["id"]) == [1, 2]

        accepted, _ = _split_via_gate(data, schema)
        assert accepted.arrow.column("id").to_pylist() == ["1", "2"]
        assert pa.types.is_string(
            accepted.arrow.schema.field("id").type
        ) or pa.types.is_large_string(accepted.arrow.schema.field("id").type)


class TestRowGate:
    def _schema(self):
        return (RowLevelSchema()
                .with_int_column("id", is_nullable=False)
                .with_string_column("s", max_length=3))

    def test_all_conforming_is_zero_copy_passthrough(self):
        data = Dataset.from_dict({"id": ["1", "2"], "s": ["ab", "cd"]})
        gate = RowGate(self._schema(), metrics=ServiceMetrics())
        assert gate.split(data, "t", "d") is data

    def test_quarantine_decodes_back_to_exact_rejects(self, tmp_path):
        data = Dataset.from_dict({
            "id": ["1", "nope", "3", "4"],
            "s": ["ok", "ok", "way-too-long", "ok"],
        })
        accepted, rejected = _split_via_gate(data, self._schema(), tmp_path)
        assert accepted.num_rows == 2
        assert accepted.arrow.column("id").to_pylist() == ["1", "4"]
        assert rejected.num_rows == 2
        assert sorted(rejected.column("id").to_pylist()) == ["3", "nope"]
        assert sorted(rejected.column("s").to_pylist()) == [
            "ok", "way-too-long"
        ]

    def test_full_rejection_raises_typed_and_counts(self, tmp_path):
        metrics = ServiceMetrics()
        sidecar = QuarantineSidecar(str(tmp_path / "q"))
        gate = RowGate(self._schema(), sidecar=sidecar, metrics=metrics)
        data = Dataset.from_dict({"id": ["x", "y"], "s": ["ab", "cd"]})
        with pytest.raises(FrameQuarantinedError) as exc_info:
            gate.split(data, "t", "d")
        assert exc_info.value.tenant == "t"
        assert exc_info.value.rows == 2
        assert metrics.counter_value(
            "deequ_service_rowgate_quarantined_frames_total",
            tenant="t", dataset="d",
        ) == 1
        assert sidecar.read_all("t", "d").num_rows == 2

    def test_quarantine_budget_drops_counted(self, tmp_path):
        metrics = ServiceMetrics()
        sidecar = QuarantineSidecar(str(tmp_path / "q"), max_rows=3)
        gate = RowGate(self._schema(), sidecar=sidecar, metrics=metrics)
        data = Dataset.from_dict({
            "id": ["bad"] * 5 + ["1"],
            "s": ["x"] * 6,
        })
        accepted = gate.split(data, "t", "d")
        assert accepted.num_rows == 1
        assert sidecar.rows_written == 3 and sidecar.rows_dropped == 2
        assert sidecar.read_all("t", "d").num_rows == 3
        assert metrics.counter_value(
            "deequ_service_rowgate_quarantine_dropped_rows_total",
            tenant="t", dataset="d",
        ) == 2
        assert metrics.counter_value(
            "deequ_service_rowgate_rejected_rows_total",
            tenant="t", dataset="d",
        ) == 5  # dropped rows still COUNT as rejected

    def test_row_gate_fault_site(self):
        gate = RowGate(self._schema(), metrics=ServiceMetrics())
        data = Dataset.from_dict({"id": ["1"], "s": ["ab"]})
        from deequ_tpu.exceptions import MetricCalculationRuntimeException

        with inject(FaultSpec("row_gate", "corrupt", at=1)) as inj:
            with pytest.raises(MetricCalculationRuntimeException):
                gate.split(data, "t", "d")
        assert inj.fired == ["row_gate:t/d:corrupt"]

    def test_gated_fold_bit_exact_with_prefiltered(self):
        """Folding the gate's accept side must equal folding a
        pre-filtered copy of the stream, metric for metric — the accept
        side is an Arrow filter of the ORIGINAL buffers, no pandas hop,
        no cast."""
        rng = np.random.default_rng(7)
        ids = np.arange(600)
        vals = rng.normal(10.0, 2.0, size=600)
        good = ids % 3 != 0  # a third of rows nonconforming (id < 0 gate)
        gated_ids = np.where(good, ids, -ids - 1)
        checks = [Check(CheckLevel.ERROR, "c")
                  .has_size(lambda n: n > 0)
                  .has_mean("v", lambda m: True)
                  .has_sum("v", lambda s: True)]
        schema = RowLevelSchema().with_int_column("id", min_value=0)
        gate = RowGate(schema, metrics=ServiceMetrics())
        with VerificationService(workers=2, background_warm=False) as svc:
            gated = svc.session("t", "gated", checks, row_gate=gate)
            plain = svc.session("t", "plain", checks)
            for lo in range(0, 600, 200):
                sl = slice(lo, lo + 200)
                gated.ingest({"id": gated_ids[sl], "v": vals[sl]})
                keep = good[sl]
                plain.ingest({
                    "id": gated_ids[sl][keep], "v": vals[sl][keep]
                })
            rg = gated.current()
            rp = plain.current()
            mg = {(a.name, a.instance): m.value.get()
                  for a, m in rg.metrics.items() if m.value.is_success}
            mp = {(a.name, a.instance): m.value.get()
                  for a, m in rp.metrics.items() if m.value.is_success}
            assert mg == mp  # bit-exact, not approx
            assert gated.rows_ingested == int(good.sum())


class TestSessionIntegration:
    def test_partial_reject_folds_clean_rows(self, tmp_path):
        schema = RowLevelSchema().with_int_column("id", min_value=0)
        with VerificationService(workers=2, background_warm=False) as svc:
            gate = RowGate(
                schema,
                sidecar=QuarantineSidecar(str(tmp_path / "q")),
                metrics=svc.metrics,
            )
            session = svc.session(
                "t", "d",
                [Check(CheckLevel.ERROR, "c").has_size(lambda n: n > 0)],
                row_gate=gate,
            )
            session.ingest({"id": np.array([1, -2, 3, -4, 5])})
            assert session.rows_ingested == 3
            assert svc.metrics.counter_value(
                "deequ_service_rowgate_rejected_rows_total",
                tenant="t", dataset="d",
            ) == 2
            q = gate.sidecar.read_all("t", "d")
            assert q.column("id").to_pylist() == [-2, -4]

    def test_reconfigure_swaps_gate_live(self):
        schema_strict = RowLevelSchema().with_int_column("id", min_value=0)
        with VerificationService(workers=2, background_warm=False) as svc:
            session = svc.session(
                "t", "d",
                [Check(CheckLevel.ERROR, "c").has_size(lambda n: n > 0)],
                row_gate=RowGate(schema_strict, metrics=svc.metrics),
            )
            with pytest.raises(FrameQuarantinedError):
                session.ingest({"id": np.array([-1, -2])})
            session.reconfigure(row_gate=None)  # explicit removal
            session.ingest({"id": np.array([-1, -2])})
            assert session.rows_ingested == 2
