"""tools/statlint — the invariant linter that machine-checks the contracts
the service plane is built on (ISSUE 14).

Three layers of pinning:

- the ZERO-FINDING GATE: the live tree must produce no non-baselined
  findings (this is the tier-1 wire — a PR that violates a checked
  contract fails here);
- per-check POSITIVE fixtures: each seeded violation in
  ``tools/statlint/fixtures`` must make the analyzer exit non-zero with
  the expected check id — a check that cannot catch its own seeded
  violation is not a check;
- the baseline round trip: grandfathered findings suppress exactly
  themselves, stale entries are reported, reason-less entries are
  rejected.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from tools.statlint import ModuleIndex, load_baseline, run_checks
from tools.statlint.__main__ import main
from tools.statlint.core import DEFAULT_BASELINE, REPO_ROOT

pytestmark = pytest.mark.lint

FIXTURES = os.path.join(REPO_ROOT, "tools", "statlint", "fixtures")
PACKAGE = os.path.join(REPO_ROOT, "deequ_tpu")


def _fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


# ---------------------------------------------------------------------------
# the zero-finding gate (the tier-1 wire)
# ---------------------------------------------------------------------------

def test_zero_finding_gate_over_live_tree():
    """`python -m tools.statlint` exits 0 on the tree: zero non-baselined
    findings. Run in-process so tier-1 pays one parse pass, not a
    subprocess interpreter start."""
    rc = main([])
    assert rc == 0


def test_gate_runs_inside_timing_budget():
    """The module-parse cache keeps the whole seven-check suite well under
    the 30s budget ISSUE 14 allots it."""
    import time

    t0 = time.monotonic()
    index = ModuleIndex([PACKAGE])
    findings = run_checks(index)
    elapsed = time.monotonic() - t0
    assert elapsed < 30, f"statlint took {elapsed:.1f}s"
    baseline = load_baseline(DEFAULT_BASELINE)
    new = [f for f in findings if f.fingerprint() not in baseline]
    assert new == [], "\n".join(f.render() for f in new)


def test_every_baseline_entry_still_fires():
    """No stale suppressions: every baselined fingerprint corresponds to a
    live finding (deleting the violation must force deleting the entry)."""
    index = ModuleIndex([PACKAGE])
    fired = {f.fingerprint() for f in run_checks(index)}
    baseline = load_baseline(DEFAULT_BASELINE)
    stale = sorted(set(baseline) - fired)
    assert stale == [], stale


# ---------------------------------------------------------------------------
# per-check positive fixtures: the seeded violation must fire
# ---------------------------------------------------------------------------

FIXTURE_EXPECTATIONS = [
    ("trace_purity_bad.py", "trace-purity", "wall-clock read"),
    ("lock_discipline_bad.py", "lock-discipline", "commit-inversion shape"),
    ("env_knobs_bad.py", "env-knob", "DEEQU_TPU_FIXTURE_KNOB"),
    ("failure_registry_bad.py", "failure-registry", "RogueSubsystemError"),
    ("export_help_bad.py", "export-help",
     "deequ_service_fixture_undescribed_total"),
    ("state_algebra_bad.py", "state-algebra", "no merge()"),
    ("dead_imports_bad.py", "dead-import", "'json'"),
    ("tuning_registry_bad.py", "tuning-registry", "FIXTURE_ROUTE_MIN_ROWS"),
    ("span_kinds_bad.py", "span-kind-registry", "freestyle_kind"),
]


def test_span_kind_check_ignores_foreign_kind_kwargs():
    """np.argsort(kind="stable") is someone else's API: the span-kind
    check must only fire on the trace call in the fixture, never on the
    numpy call beside it."""
    path = _fixture("span_kinds_bad.py")
    index = ModuleIndex([path])
    findings = [
        f for f in run_checks(index) if f.check == "span-kind-registry"
    ]
    assert len(findings) == 1, [f.message for f in findings]
    assert findings[0].key == "kind:freestyle_kind"


@pytest.mark.parametrize(
    "fixture,check,needle", FIXTURE_EXPECTATIONS,
    ids=[c for _, c, _ in FIXTURE_EXPECTATIONS],
)
def test_fixture_violation_fires(fixture, check, needle):
    path = _fixture(fixture)
    assert os.path.exists(path)
    rc = main([path])
    assert rc != 0, f"{fixture} should fail the gate"
    index = ModuleIndex([path])
    findings = [f for f in run_checks(index) if f.check == check]
    assert findings, f"no {check} finding fired on {fixture}"
    assert any(needle in f.message for f in findings), [
        f.message for f in findings
    ]


def test_cli_module_entry_point():
    """`python -m tools.statlint <fixture>` (the real CLI) exits non-zero —
    one subprocess to pin the module wiring; everything else runs
    in-process."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.statlint",
         _fixture("lock_discipline_bad.py")],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "lock-discipline" in proc.stdout


# ---------------------------------------------------------------------------
# the acceptance fixture: the PR 13 known-bug shape
# ---------------------------------------------------------------------------

def test_lock_check_reproduces_pr13_bug_shape():
    """The lock-discipline check must catch the PR 13 cross-key
    commit-inversion pattern: a shared-field write reachable with and
    without the owning lock — and name both paths."""
    index = ModuleIndex([_fixture("lock_discipline_bad.py")])
    findings = [
        f for f in run_checks(index)
        if f.check == "lock-discipline" and "unguarded-write" in f.key
    ]
    assert len(findings) == 1
    message = findings[0].message
    assert "commit" in message and "commit_unlocked" in message
    assert "_committed" in message


def test_lock_check_finds_acquisition_order_cycle():
    index = ModuleIndex([_fixture("lock_discipline_bad.py")])
    cycles = [
        f for f in run_checks(index)
        if f.check == "lock-discipline" and f.key.startswith("cycle:")
    ]
    assert len(cycles) == 1
    assert "AccountA._lock" in cycles[0].message
    assert "AccountB._lock" in cycles[0].message


def test_locked_helper_convention_not_flagged(tmp_path):
    """A `_foo_locked` helper whose every call site holds the lock —
    including transitively through another helper — is guarded; only a
    genuinely lockless path fires."""
    module = tmp_path / "helper_convention.py"
    module.write_text(
        "import threading\n"
        "class Fine:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._state = 0\n"
        "    def public(self):\n"
        "        with self._lock:\n"
        "            self._outer_locked()\n"
        "    def _outer_locked(self):\n"
        "        self._inner_locked()\n"
        "    def _inner_locked(self):\n"
        "        self._state += 1\n"
    )
    index = ModuleIndex([str(module)])
    findings = [f for f in run_checks(index) if f.check == "lock-discipline"]
    assert findings == [], [f.message for f in findings]


# ---------------------------------------------------------------------------
# baseline round trip
# ---------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    fixture = _fixture("env_knobs_bad.py")
    baseline = tmp_path / "baseline.json"
    assert main(["--write-baseline", str(baseline), fixture]) == 0
    payload = json.loads(baseline.read_text())
    assert payload["entries"], "write-baseline must capture the finding"
    # the written baseline suppresses exactly the captured findings
    assert main(["--baseline", str(baseline), fixture]) == 0
    # a clean module against the same baseline reports the entry as STALE
    clean = tmp_path / "clean.py"
    clean.write_text("VALUE = 1\n")
    assert main(["--baseline", str(baseline), str(clean)]) == 1


def test_unknown_check_id_is_an_error_not_a_silent_green(tmp_path):
    """A typo'd --checks scope must exit 2, never run zero checks and
    pass."""
    rc = main(["--checks", "lock_discipline",  # underscore typo
               _fixture("lock_discipline_bad.py")])
    assert rc == 2
    with pytest.raises(ValueError):
        run_checks(ModuleIndex([_fixture("lock_discipline_bad.py")]),
                   only=["nope"])


def test_scoped_run_does_not_report_foreign_baseline_as_stale():
    """--checks scoping must not flag unselected checks' baseline entries
    as stale (obeying 'delete it' would break the full run)."""
    rc = main(["--checks", "lock-discipline"])
    assert rc == 0  # live tree is lock-clean; env-knob entries untouched


def test_env_check_catches_bound_name_import_idiom(tmp_path):
    """`from os import environ` / `from os import getenv` reads must not
    evade the convention check."""
    module = tmp_path / "evader.py"
    module.write_text(
        "from os import environ, getenv\n"
        "A = environ.get('DEEQU_TPU_EVADED_A')\n"
        "B = getenv('DEEQU_TPU_EVADED_B')\n"
        "C = environ['DEEQU_TPU_EVADED_C']\n"
    )
    index = ModuleIndex([str(module)])
    found = {
        f.key for f in run_checks(index, only=["env-knob"])
    }
    assert found == {
        "direct:DEEQU_TPU_EVADED_A",
        "direct:DEEQU_TPU_EVADED_B",
        "direct:DEEQU_TPU_EVADED_C",
    }


def test_trace_ring_clamps_to_floor(monkeypatch):
    """DEEQU_TPU_TRACE_RING below the floor clamps to 16 (an operator
    capping trace memory must not silently get the 4096 default)."""
    # note: deequ_tpu.observability exports a FUNCTION named `recorder`
    # that shadows the submodule attribute, so resolve via importlib
    import importlib

    recorder_mod = importlib.import_module("deequ_tpu.observability.recorder")

    monkeypatch.setenv("DEEQU_TPU_TRACE_RING", "8")
    assert recorder_mod.ring_capacity() == 16
    monkeypatch.setenv("DEEQU_TPU_TRACE_RING", "64")
    assert recorder_mod.ring_capacity() == 64


def test_baseline_requires_reasons(tmp_path):
    baseline = tmp_path / "noreason.json"
    baseline.write_text(json.dumps(
        {"entries": [{"fingerprint": "env-knob:x:y", "reason": "  "}]}
    ))
    with pytest.raises(ValueError):
        load_baseline(str(baseline))
    assert main(["--baseline", str(baseline), _fixture("env_knobs_bad.py")]) == 2


# ---------------------------------------------------------------------------
# registry coherence pins (cheap spot checks on live invariants)
# ---------------------------------------------------------------------------

def test_fault_site_registry_matches_live_probes():
    from deequ_tpu.reliability.faults import KNOWN_FAULT_SITES

    assert "worker" in KNOWN_FAULT_SITES
    assert "coalesced_fold" in KNOWN_FAULT_SITES  # the drift ISSUE 14 caught


def test_subsystem_exceptions_import_lazily():
    import deequ_tpu.exceptions as exc

    assert exc.ExpressionError.__name__ == "ExpressionError"
    assert exc.SerializationError.__name__ == "SerializationError"
    assert exc.MeshExhaustedError.__name__ == "MeshExhaustedError"
    assert exc.FrequencyBudgetExceeded.__name__ == "FrequencyBudgetExceeded"
    with pytest.raises(AttributeError):
        exc.NoSuchThing


def test_env_helpers_follow_convention(monkeypatch):
    from deequ_tpu.utils import env_flag, env_str

    monkeypatch.delenv("DEEQU_TPU_TEST_FLAG", raising=False)
    assert env_flag("DEEQU_TPU_TEST_FLAG", True) is True
    monkeypatch.setenv("DEEQU_TPU_TEST_FLAG", "0")
    assert env_flag("DEEQU_TPU_TEST_FLAG", True) is False
    monkeypatch.setenv("DEEQU_TPU_TEST_FLAG", "1")
    assert env_flag("DEEQU_TPU_TEST_FLAG", False) is True
    monkeypatch.setenv("DEEQU_TPU_TEST_FLAG", "")
    assert env_flag("DEEQU_TPU_TEST_FLAG", True) is True  # empty = unset
    monkeypatch.setenv("DEEQU_TPU_TEST_STR", "s3://bucket")
    assert env_str("DEEQU_TPU_TEST_STR") == "s3://bucket"
    assert env_str("DEEQU_TPU_TEST_STR_MISSING", "dflt") == "dflt"
