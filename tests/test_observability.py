"""End-to-end tracing, flight recorder and cost attribution (ISSUE 5).

The acceptance contract this file pins:

- a chaos-injected run (device failover + watchdog stall) produces ONE
  connected trace: the failed device pass, the typed exception event and
  the host-tier re-run all share a ``trace_id``;
- the flight recorder dumps a correlated artifact for EVERY typed failure
  kind (DeviceFailure, ScanStallError, CorruptStateError, SchemaDriftError);
- ``cost_by_analyzer`` shares sum to the bundle's measured dispatch time
  within 1%;
- the Chrome trace artifact validates against the trace-event schema
  (fields present, timestamps monotonic, parent refs resolve), so exporter
  drift fails tier-1 fast.
"""

import json
import time

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    Completeness,
    Maximum,
    Mean,
    Minimum,
    StandardDeviation,
    Sum,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.observability import export as obs_export
from deequ_tpu.observability import trace
from deequ_tpu.observability.recorder import FlightRecorder, recorder
from deequ_tpu.reliability import FaultSpec, inject
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor
from deequ_tpu.verification import VerificationSuite
from deequ_tpu.reliability.watchdog import SCAN_DEADLINE_ENV, rate_tracker

pytestmark = pytest.mark.trace


@pytest.fixture(autouse=True)
def _clean_recorder():
    recorder().clear()
    yield
    recorder().clear()


def _data(rows=4096, seed=0):
    rng = np.random.default_rng(seed)
    return Dataset.from_dict(
        {"x": rng.normal(size=rows), "y": rng.normal(10, 2, size=rows)}
    )


BATTERY = [
    Completeness("x"), Mean("x"), Sum("x"), Minimum("x"), Maximum("x"),
    StandardDeviation("x"), Mean("y"), Sum("y"),
]


def _check():
    return (
        Check(CheckLevel.ERROR, "obs battery")
        .is_complete("x")
        .has_mean("y", lambda m: 5 < m < 15)
    )


class TestSpanBasics:
    def test_nesting_and_ids(self):
        with trace.span("outer", kind="test") as outer:
            assert trace.current_span() is outer
            with trace.span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
                inner.add_event("hello", n=1)
        assert trace.current_span() is None
        spans = recorder().spans()
        names = [s.name for s in spans]
        assert names == ["inner", "outer"]  # children finish first
        assert spans[0].events[0]["name"] == "hello"
        assert spans[0].end_ns >= spans[0].start_ns

    def test_disabled_env_suppresses_everything(self, monkeypatch):
        monkeypatch.setenv(trace.TRACE_ENV, "0")
        with trace.span("invisible") as sp:
            assert sp is trace.NULL
            assert trace.current_span() is None
            trace.add_event("nope")
        assert recorder().spans() == []

    def test_unsampled_root_suppresses_descendants(self, monkeypatch):
        # rate 0 < r < 1 with the deterministic counter: force the
        # "sampled out" branch by rate ~0 (first roots land unsampled)
        monkeypatch.setenv(trace.TRACE_ENV, "0.000001")
        with trace.span("root") as root:
            with trace.span("child") as child:
                # whatever the sampling decided, both agree
                assert (root is trace.NULL) == (child is trace.NULL)

    def test_cross_thread_attach(self):
        import threading

        seen = {}
        with trace.span("parent") as parent:
            ctx = trace.capture()

            def worker():
                with trace.attach(ctx):
                    with trace.span("on-thread") as sp:
                        seen["trace"] = sp.trace_id
                        seen["parent"] = sp.parent_id

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["trace"] == parent.trace_id
        assert seen["parent"] == parent.span_id

    def test_ring_is_bounded(self):
        ring = FlightRecorder(capacity=16)
        for i in range(64):
            sp = trace.start_span(f"s{i}", parent=None)
            ring.on_span_finish(sp)
        assert len(ring.spans()) == 16
        assert ring.spans()[-1].name == "s63"


class TestPhaseSpans:
    def test_phase_spans_match_phase_seconds(self):
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(
            _data(), BATTERY, batch_size=1024, monitor=mon, placement="device"
        )
        spans = recorder().spans()
        assert spans, "tracing is default-on"
        # one trace for the whole run
        assert len({s.trace_id for s in spans}) == 1
        phase_totals = {}
        for s in spans:
            if s.kind == "phase":
                phase_totals[s.name] = (
                    phase_totals.get(s.name, 0.0) + s.duration_s()
                )
        # every monitored phase that ran shows up span-backed, and the
        # span-summed duration equals the monitor's number (same clock)
        for phase in ("feature_build", "device_dispatch", "state_fetch"):
            assert phase in phase_totals
            assert phase_totals[phase] == pytest.approx(
                mon.phase_seconds[phase], rel=1e-6, abs=1e-9
            )
        # metric derivation joined the monitored phases
        assert "metric_derivation" in mon.phase_seconds
        assert "metric_derivation" in phase_totals

    def test_engine_pass_span_carries_tier(self):
        AnalysisRunner.do_analysis_run(
            _data(), BATTERY, batch_size=1024, placement="host"
        )
        passes = [s for s in recorder().spans() if s.name == "engine_pass"]
        assert passes and passes[0].attrs["tier"] == "host"


class TestCostAttribution:
    @pytest.mark.parametrize("placement", ["device", "host"])
    def test_shares_sum_to_measured_dispatch_time(self, placement):
        """Acceptance: cost_by_analyzer shares sum to the bundle's measured
        dispatch time within 1%."""
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(
            _data(16384), BATTERY, batch_size=1024, monitor=mon,
            placement=placement,
        )
        assert mon.cost_by_analyzer, "attribution must populate"
        total = sum(mon.cost_by_analyzer.values())
        assert mon.bundle_dispatch_seconds > 0
        assert total == pytest.approx(mon.bundle_dispatch_seconds, rel=0.01)
        # every scan analyzer got a share
        for a in BATTERY:
            assert repr(a) in mon.cost_by_analyzer

    def test_solo_probe_fires_periodically(self):
        mon = RunMonitor()
        # 80 batches of 512 rows: probe batches are folded==1 and
        # folded==65 — exactly 2 probes, regardless of bundle count
        AnalysisRunner.do_analysis_run(
            _data(80 * 512), BATTERY, batch_size=512, monitor=mon,
            placement="device",
        )
        assert mon.cost_probes == 2

    def test_verification_result_carries_cost_table(self):
        result = (
            VerificationSuite.on_data(_data())
            .add_check(_check())
            .with_batch_size(1024)
            .run()
        )
        assert result.cost_by_analyzer
        rows = json.loads(result.cost_by_analyzer_as_json())
        assert {r["analyzer"]: r["seconds"] for r in rows} == pytest.approx(
            result.cost_by_analyzer
        )

    def test_cost_series_reach_export_plane(self):
        from deequ_tpu.service import VerificationService

        with VerificationService(workers=2, background_warm=False) as svc:
            svc.verify(_data(), [_check()], timeout=120)
            text = svc.prometheus_text()
        assert "deequ_service_analyzer_cost_seconds_total{" in text


class TestConnectedDegradedTrace:
    def test_device_failover_is_one_connected_trace(self):
        """Acceptance: the failed device pass, the typed exception event
        and the host-tier re-run share one trace_id."""
        mon = RunMonitor()
        with inject(FaultSpec("device_update", "device", at=1)):
            ctx = AnalysisRunner.do_analysis_run(
                _data(), BATTERY, batch_size=1024, monitor=mon,
                placement="device",
            )
        assert mon.device_failovers == 1
        for metric in ctx.metric_map.values():
            assert metric.value.is_success
        spans = recorder().spans()
        passes = [s for s in spans if s.name == "engine_pass"]
        # the failed device pass and the host-tier re-pass, one trace
        assert len(passes) == 2
        assert len({s.trace_id for s in spans}) == 1
        assert passes[0].attrs["tier"] == "device"
        assert passes[0].status == "error"
        assert passes[1].attrs["tier"] == "host"
        assert passes[1].status == "ok"
        # the typed exception event rides the same trace
        events = [
            ev for s in spans for ev in s.events if ev["name"] == "failure"
        ]
        assert any(
            ev["attrs"]["type"] == "DeviceFailureException" for ev in events
        )
        assert any(
            ev["name"] == "device_failover"
            for s in spans for ev in s.events
        )

    @pytest.mark.chaos
    def test_watchdog_stall_joins_the_same_trace(self, monkeypatch):
        """Acceptance: device failover + watchdog stall in one chaos run ->
        ONE connected trace with the stall event and the host re-run."""
        # warm both tiers so the pinned 1s deadline only trips the stall
        for placement in ("device", "host"):
            AnalysisRunner.do_analysis_run(
                _data(), BATTERY, batch_size=1024, placement=placement
            )
        recorder().clear()
        monkeypatch.setenv(SCAN_DEADLINE_ENV, "1.0")
        mon = RunMonitor()
        with inject(FaultSpec("device_update", "stall", at=1, delay_s=30.0)):
            result = (
                VerificationSuite.on_data(_data())
                .add_check(_check())
                .with_monitor(mon)
                .with_batch_size(1024)
                .with_placement("device")
                .run()
            )
        assert mon.stalls == 1
        assert mon.device_failovers == 1
        assert result.status == CheckStatus.SUCCESS
        spans = recorder().spans()
        assert len({s.trace_id for s in spans}) == 1
        passes = [s for s in spans if s.name == "engine_pass"]
        tiers = [s.attrs["tier"] for s in passes]
        assert tiers.count("host") >= 1 and tiers.count("device") >= 1
        stall_events = [
            ev for s in spans for ev in s.events if ev["name"] == "scan_stall"
        ]
        assert stall_events and stall_events[0]["attrs"]["site"] == "device"
        failures = [
            ev["attrs"]["type"]
            for s in spans for ev in s.events if ev["name"] == "failure"
        ]
        assert "ScanStallError" in failures


class TestFlightRecorder:
    def test_dump_fires_for_every_typed_failure_kind(self, monkeypatch, tmp_path):
        """Acceptance: flight-recorder dump fires on every typed failure
        kind."""
        from deequ_tpu.observability.recorder import FLIGHT_DIR_ENV

        monkeypatch.setenv(FLIGHT_DIR_ENV, str(tmp_path))
        rec = recorder()

        # 1. DeviceFailure: injected device fault -> failover path
        with inject(FaultSpec("device_update", "device", at=1)):
            AnalysisRunner.do_analysis_run(
                _data(), BATTERY, batch_size=1024, placement="device"
            )

        # 2. ScanStallError: watchdog-cancelled stall
        monkeypatch.setenv(SCAN_DEADLINE_ENV, "0.2")
        with inject(FaultSpec("device_update", "stall", at=1, delay_s=10.0)):
            AnalysisRunner.do_analysis_run(
                _data(), BATTERY, batch_size=1024, placement="device"
            )
        monkeypatch.delenv(SCAN_DEADLINE_ENV)

        # 3. CorruptStateError: checksum trip inside a traced region
        from deequ_tpu.exceptions import CorruptStateError
        from deequ_tpu.integrity import checksum_bytes, verify_checksum

        with trace.span("corrupt-drill"):
            with pytest.raises(CorruptStateError):
                verify_checksum(b"payload", "bogus", "state blob", "mem://x")
        assert checksum_bytes(b"payload") != "bogus"

        # 4. SchemaDriftError: streaming session rejects a drifted batch
        from deequ_tpu.exceptions import SchemaDriftError
        from deequ_tpu.service import VerificationService

        with VerificationService(workers=1, background_warm=False) as svc:
            session = svc.session("t", "d", [_check()])
            session.ingest(_data(512), timeout=120)
            drifted = Dataset.from_dict(
                {"x": np.arange(8, dtype=np.float64)}
            )
            with pytest.raises(SchemaDriftError):
                session.ingest(drifted, timeout=120)

        for kind in (
            "DeviceFailureException", "ScanStallError", "CorruptStateError",
            "SchemaDriftError",
        ):
            assert rec.dump_counts.get(kind, 0) >= 1, kind
        # artifacts landed, each correlating a trace
        assert rec.dump_paths
        with open(rec.dump_paths[0]) as fh:
            header = json.loads(fh.readline())
        assert header["flight_record"] is True
        assert header["failures"]

    def test_dump_releases_on_unit_of_work_not_outer_root(
        self, monkeypatch, tmp_path
    ):
        """A typed failure under a LONG-LIVED caller span must dump when
        the run's own analysis_run span closes — waiting for the outer
        root would delay the artifact past ring eviction (and a poller's
        root may never close while the service runs)."""
        from deequ_tpu.observability.recorder import FLIGHT_DIR_ENV

        monkeypatch.setenv(FLIGHT_DIR_ENV, str(tmp_path))
        rec = recorder()
        with trace.span("long-lived-poller"):
            with inject(FaultSpec("device_update", "device", at=1)):
                AnalysisRunner.do_analysis_run(
                    _data(), BATTERY, batch_size=1024, placement="device"
                )
            # artifact exists ALREADY — the outer span is still open
            assert rec.dump_counts.get("DeviceFailureException", 0) >= 1
            assert rec.dump_paths

    def test_untraced_failure_still_counts_and_dumps(self, monkeypatch, tmp_path):
        from deequ_tpu.observability.recorder import FLIGHT_DIR_ENV
        from deequ_tpu.exceptions import CorruptStateError
        from deequ_tpu.integrity import verify_checksum

        monkeypatch.setenv(FLIGHT_DIR_ENV, str(tmp_path))
        monkeypatch.setenv(trace.TRACE_ENV, "0")
        with pytest.raises(CorruptStateError):
            verify_checksum(b"payload", "bogus", "state blob", "mem://y")
        rec = recorder()
        assert rec.dump_counts.get("CorruptStateError", 0) >= 1
        assert any("untraced" in p for p in rec.dump_paths)


class TestExporters:
    def _run_and_export(self, tmp_path):
        AnalysisRunner.do_analysis_run(_data(), BATTERY, batch_size=1024)
        path = str(tmp_path / "run.trace.json")
        obs_export.write_chrome_trace(path)
        with open(path) as fh:
            return json.load(fh)

    def test_chrome_artifact_validates_against_schema(self, tmp_path):
        """Tier-1 exporter-drift guard: load an emitted artifact and
        validate the Chrome trace-event contract — required fields,
        non-negative monotonic timestamps, parent refs that resolve."""
        doc = self._run_and_export(tmp_path)
        events = doc["traceEvents"]
        assert events
        span_ids = set()
        for ev in events:
            assert ev["ph"] in ("X", "i")
            for field in ("name", "cat", "ts", "pid", "tid"):
                assert field in ev, f"missing {field}: {ev}"
            assert ev["ts"] >= 0
            if ev["ph"] == "X":
                assert ev["dur"] >= 0
                span_ids.add(ev["args"]["span_id"])
        for ev in events:
            parent = ev["args"].get("parent_id")
            if ev["ph"] == "X" and parent is not None:
                assert parent in span_ids, f"dangling parent ref {parent}"
            # every event correlates to a trace
            assert ev["args"]["trace_id"] is not None
        # durations nest: each child's [ts, ts+dur] within its parent's
        by_id = {
            e["args"]["span_id"]: e for e in events if e["ph"] == "X"
        }
        for ev in by_id.values():
            parent = ev["args"].get("parent_id")
            if parent is None:
                continue
            p = by_id[parent]
            assert ev["ts"] >= p["ts"] - 1e3  # 1ms clock-read slack
            assert ev["ts"] + ev["dur"] <= p["ts"] + p["dur"] + 1e3

    def test_jsonl_journal_round_trips(self, tmp_path):
        AnalysisRunner.do_analysis_run(_data(), BATTERY, batch_size=1024)
        path = str(tmp_path / "run.jsonl")
        obs_export.write_jsonl(path)
        with open(path) as fh:
            rows = [json.loads(line) for line in fh]
        assert rows
        live = {s.span_id: s for s in recorder().spans()}
        for row in rows:
            assert row["span_id"] in live
            assert row["start_ns"] <= row["end_ns"]

    def test_trace_endpoint_serves_ring(self):
        import urllib.request

        from deequ_tpu.service import MetricsExporter, ServiceMetrics

        AnalysisRunner.do_analysis_run(_data(), BATTERY, batch_size=1024)
        exporter = MetricsExporter(ServiceMetrics())
        try:
            url = f"http://{exporter.host}:{exporter.port}"
            with urllib.request.urlopen(f"{url}/trace") as resp:
                doc = json.loads(resp.read())
            assert doc["traceEvents"]
            with urllib.request.urlopen(f"{url}/trace.jsonl") as resp:
                lines = resp.read().decode().strip().splitlines()
            assert lines and json.loads(lines[0])["span_id"]
        finally:
            exporter.close()


class TestTraceSummarize:
    def test_summary_from_degraded_run_artifact(self, tmp_path):
        from tools.trace_summarize import (
            critical_path,
            degradations,
            load_spans,
            summarize,
        )

        with inject(FaultSpec("device_update", "device", at=1)):
            AnalysisRunner.do_analysis_run(
                _data(), BATTERY, batch_size=1024, placement="device"
            )
        chrome = str(tmp_path / "degraded.trace.json")
        obs_export.write_chrome_trace(chrome)
        spans = load_spans(chrome)
        assert spans
        path = critical_path(spans)
        assert path and path[0]["parent_id"] is None
        # the critical path walks parent->child
        for parent, child in zip(path, path[1:]):
            assert child["parent_id"] == parent["span_id"]
        degrade = degradations(spans)
        assert any(ev["name"] == "device_failover" for _, _, ev in degrade)
        text = summarize(chrome)
        assert "critical path:" in text
        assert "device_failover" in text
        assert "top 5 spans by self-time:" in text

    def test_summary_reads_jsonl_too(self, tmp_path):
        AnalysisRunner.do_analysis_run(_data(), BATTERY, batch_size=1024)
        path = str(tmp_path / "run.jsonl")
        obs_export.write_jsonl(path)
        from tools.trace_summarize import summarize

        text = summarize(path)
        assert "critical path:" in text
        assert "(none — clean run)" in text


class TestOverheadGuards:
    def test_tracing_off_still_counts_costs(self, monkeypatch):
        """Cost attribution is monitor-driven, not span-driven: it must
        survive DEEQU_TPU_TRACE=0 (the knob an operator flips under
        overhead pressure)."""
        monkeypatch.setenv(trace.TRACE_ENV, "0")
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(
            _data(), BATTERY, batch_size=1024, monitor=mon, placement="device"
        )
        assert recorder().spans() == []
        assert mon.cost_by_analyzer
        assert mon.phase_seconds  # phase timers unaffected

    def test_rate_tracker_unaffected_by_tracing(self, monkeypatch):
        rate_tracker().clear()
        AnalysisRunner.do_analysis_run(_data(), [Mean("x")], batch_size=1024)
        with_trace = rate_tracker().per_row_s("device")
        assert with_trace is not None
