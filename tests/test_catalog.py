"""Tenant isolation plane: declarative catalog, hot/cold tiering,
last-good serving with content-addressed quarantine, admission budgets
(ISSUE 17).

The plane's contracts, pinned:
- a corrupt catalog edit NEVER takes a tenant down — load serves the
  last-good version and bumps the quarantine counter exactly once;
- a valid edit becomes effective at the next fold boundary, no restart;
- a catalog-REGISTERED tenant's first POST auto-opens its session from
  the document; UNREGISTERED tenants keep the endpoint's 404 (the
  endpoint still never invents a zero-check session);
- an over-quota tenant is shed TYPED (QuotaExceeded, HTTP 429) while its
  in-quota neighbors keep folding.
"""

import json
import os
import time

import numpy as np
import pytest

from deequ_tpu.service import (
    CatalogError,
    CatalogPlane,
    QuotaExceeded,
    TenantCatalog,
    TenantQuota,
    VerificationService,
)

pytestmark = pytest.mark.catalog


def _doc(priority="normal", max_len=3, quotas=None, watches=False):
    doc = {
        "checks": [{"name": "base", "constraints": [
            {"kind": "complete", "column": "id"},
            {"kind": "min", "column": "v", "min": 0},
        ]}],
        "row_gate": {"columns": [
            {"name": "id", "type": "int", "nullable": False},
            {"name": "s", "type": "string", "max_length": max_len},
        ]},
        "priority": priority,
    }
    if quotas is not None:
        doc["quotas"] = quotas
    if watches:
        doc["watches"] = [{
            "analyzer": {"kind": "mean", "column": "v"},
            "strategy": {"kind": "simple_threshold", "upper_bound": 1e9},
        }]
    return doc


def _frame(rows=3, start=0, s="ab"):
    return {
        "id": np.arange(start, start + rows),
        "s": np.array([s] * rows),
        "v": np.ones(rows, dtype=np.float64),
    }


@pytest.fixture
def catalog(tmp_path):
    return TenantCatalog(str(tmp_path / "catalog"))


@pytest.fixture
def service(catalog):
    with VerificationService(
        workers=2, max_queue_depth=32, background_warm=False,
        catalog=catalog,
    ) as svc:
        yield svc


class TestTenantCatalog:
    def test_register_versions_and_load(self, catalog):
        d1 = catalog.register("acme", _doc())
        d2 = catalog.register("acme", _doc(priority="high"))
        assert (d1.version, d2.version) == (1, 2)
        assert catalog.registered("acme")
        assert not catalog.registered("ghost")
        assert catalog.current_version("acme") == 2
        loaded = catalog.load("acme")
        assert loaded.version == 2
        assert loaded.doc["priority"] == "high"

    def test_invalid_document_bounces_at_register(self, catalog):
        with pytest.raises(CatalogError, match="constraint"):
            catalog.register("t", {"checks": [
                {"name": "x", "constraints": [{"kind": "no-such-kind"}]}
            ]})
        with pytest.raises(CatalogError):
            catalog.register("t", {"row_gate": {"columns": [
                {"name": "c", "type": "no-such-type"}
            ]}})
        # an invalid regex validates structurally but cannot BUILD —
        # it must bounce at registration, not on the ingest path
        with pytest.raises(CatalogError):
            catalog.register("t", {"checks": [{"name": "x", "constraints": [
                {"kind": "pattern", "column": "c", "pattern": "(unclosed"}
            ]}]})
        assert not catalog.registered("t")  # nothing was written

    def test_unregistered_tenant_load_is_typed(self, catalog):
        with pytest.raises(CatalogError, match="ghost"):
            catalog.load("ghost")

    def test_corrupt_edit_serves_last_good_quarantines_once(
        self, catalog, tmp_path
    ):
        from deequ_tpu.service.metrics import ServiceMetrics

        catalog.metrics = ServiceMetrics()
        catalog.register("acme", _doc())
        catalog.register("acme", _doc(priority="high"))
        # a torn write lands as version 3
        tdir = os.path.join(str(tmp_path / "catalog"), "t-acme")
        with open(os.path.join(tdir, "v00000003.json"), "w") as fh:
            fh.write('{"torn": tru')
        for _ in range(3):  # repeated loads must not re-quarantine
            loaded = catalog.load("acme")
            assert loaded.version == 2
            assert loaded.doc["priority"] == "high"
        assert catalog.metrics.counter_value(
            "deequ_service_catalog_quarantined_total", tenant="acme"
        ) == 1
        qdir = str(tmp_path / "catalog") + ".quarantine"
        names = os.listdir(qdir)
        assert len(names) == 1 and names[0].startswith("v00000003.json-")

    def test_tampered_checksum_quarantined(self, catalog, tmp_path):
        catalog.register("acme", _doc())
        tdir = os.path.join(str(tmp_path / "catalog"), "t-acme")
        path = os.path.join(tdir, "v00000001.json")
        with open(path) as fh:
            payload = json.load(fh)
        payload["doc"]["priority"] = "high"  # edit without re-checksumming
        with open(path, "w") as fh:
            json.dump(payload, fh)
        with pytest.raises(CatalogError, match="no servable document"):
            catalog.load("acme")

    def test_registered_scale_is_listing_only(self, catalog):
        """1M registered / 1k active must cost 1k tenants: registration
        writes one file; current_version is a pure listing, no parse."""
        for i in range(50):
            catalog.register(f"t{i:03d}", _doc())
        assert catalog.registered_count() == 50
        assert sorted(catalog.tenants())[0] == "t000"
        assert catalog.current_version("t007") == 1


class TestCatalogPlane:
    def test_materialize_from_document(self, service, catalog):
        catalog.register("acme", _doc(priority="high",
                                      quotas={"rows_per_s": 1e6}))
        plane = service.catalog_plane
        session = plane.ensure_session("acme", "clicks")
        from deequ_tpu.service.scheduler import Priority

        assert session.priority is Priority.HIGH
        assert session.row_gate is not None
        assert service.scheduler.get_quota("acme") == TenantQuota(
            rows_per_s=1e6
        )
        r = session.ingest(_frame())
        assert r.status.name == "SUCCESS"
        assert plane.hot_count() == 1
        assert plane.ensure_session("acme", "clicks") is session

    def test_ensure_session_unregistered_is_typed(self, service):
        with pytest.raises(CatalogError):
            service.catalog_plane.ensure_session("ghost", "d")

    def test_hot_reload_at_fold_boundary(self, service, catalog):
        catalog.register("acme", _doc(priority="high"))
        plane = service.catalog_plane
        plane.poll_s = 0.0  # poll every fold boundary
        session = plane.ensure_session("acme", "clicks")
        session.ingest(_frame())
        catalog.register("acme", _doc(priority="low", max_len=10))
        plane.on_fold_boundary(session)
        from deequ_tpu.service.scheduler import Priority

        assert session.priority is Priority.LOW
        # the new gate (max_len=10) is live: a frame the old gate would
        # have quarantined now folds
        session.ingest(_frame(s="longer-now", start=100))
        assert service.metrics.counter_value(
            "deequ_service_catalog_reloads_total", tenant="acme"
        ) == 1

    def test_corrupt_edit_keeps_live_config(self, service, catalog, tmp_path):
        catalog.register("acme", _doc(priority="high"))
        plane = service.catalog_plane
        plane.poll_s = 0.0
        session = plane.ensure_session("acme", "clicks")
        tdir = os.path.join(catalog.path, "t-acme")
        with open(os.path.join(tdir, "v00000002.json"), "w") as fh:
            fh.write("not json at all")
        plane.on_fold_boundary(session)
        from deequ_tpu.service.scheduler import Priority

        assert session.priority is Priority.HIGH  # unchanged
        assert service.metrics.counter_value(
            "deequ_service_catalog_reloads_total", tenant="acme"
        ) == 0
        assert service.metrics.counter_value(
            "deequ_service_catalog_quarantined_total", tenant="acme"
        ) == 1

    def test_ttl_eviction_to_cold_and_rematerialization(
        self, service, catalog
    ):
        catalog.register("acme", _doc())
        plane = service.catalog_plane
        session = plane.ensure_session("acme", "clicks")
        session.ingest(_frame())
        assert plane.sweep() == 0  # fresh: not idle yet
        plane.hot_ttl_s = 0.0
        assert plane.sweep() == 1
        assert plane.hot_count() == 0
        assert session.closed
        assert catalog.registered("acme")  # cold, not gone
        # next ensure re-materializes a fresh session from the document
        plane.hot_ttl_s = 300.0
        again = plane.ensure_session("acme", "clicks")
        assert again is not session and not again.closed


class TestAdmissionBudgets:
    def test_over_quota_shed_typed_neighbor_unaffected(self, service):
        service.scheduler.set_quota("hog", TenantQuota(rows_per_s=50))
        hog = service.session("hog", "d", [])
        neighbor = service.session("calm", "d", [])
        with pytest.raises(QuotaExceeded) as exc_info:
            for i in range(5):
                hog.ingest(_frame(rows=40, start=i * 40), block_s=0.0)
        assert exc_info.value.tenant == "hog"
        assert exc_info.value.resource == "rows_per_s"
        assert service.metrics.counter_value(
            "deequ_service_quota_shed_total", tenant="hog",
            resource="rows_per_s",
        ) >= 1
        for i in range(5):  # the neighbor has no quota: all 5 fold
            neighbor.ingest(_frame(rows=40, start=i * 40))
        assert neighbor.rows_ingested == 200

    def test_quota_raise_does_not_inherit_debt(self, service):
        service.scheduler.set_quota("t", TenantQuota(rows_per_s=10))
        # the deficit bucket admits the first over-burst charge (going
        # into debt) and refuses the next until the debt drains
        service.scheduler.charge_quota("t", rows=100, block_s=0.0)
        with pytest.raises(QuotaExceeded):
            service.scheduler.charge_quota("t", rows=100, block_s=0.0)
        service.scheduler.set_quota("t", TenantQuota(rows_per_s=1e6))
        service.scheduler.charge_quota("t", rows=100, block_s=0.0)

    def test_queue_share_shed_typed(self):
        with VerificationService(
            workers=1, max_queue_depth=8, background_warm=False,
        ) as svc:
            svc.scheduler.set_quota("t", TenantQuota(queue_share=0.25))
            # stall the single worker so submissions pile up
            import threading

            gate = threading.Event()
            svc.scheduler.submit(lambda ctx: gate.wait(10), tenant="x")
            time.sleep(0.05)
            svc.scheduler.submit(lambda ctx: None, tenant="t")
            svc.scheduler.submit(lambda ctx: None, tenant="t")
            with pytest.raises(QuotaExceeded) as exc_info:
                svc.scheduler.submit(
                    lambda ctx: None, tenant="t", block_s=0.0
                )
            assert exc_info.value.resource == "queue_share"
            gate.set()


class TestEndpointAutoOpen:
    def _post(self, exporter, path, body, headers=None):
        import http.client

        conn = http.client.HTTPConnection(
            exporter.host, exporter.port, timeout=30
        )
        try:
            conn.request("POST", path, body=body, headers=headers or {})
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read().decode())
        finally:
            conn.close()

    def _payload(self, rows=3, s="ab"):
        import pyarrow as pa

        from deequ_tpu.ingest import encode_ipc_stream

        f = _frame(rows=rows, s=s)
        return encode_ipc_stream(pa.table({
            k: pa.array(v) for k, v in f.items()
        }))

    def test_registered_tenant_auto_opens(self, service, catalog):
        catalog.register("acme", _doc())
        exporter = service.start_exporter()
        assert service.get_session("acme", "clicks") is None
        status, body = self._post(
            exporter, "/ingest/v1/acme/clicks", self._payload()
        )
        assert status == 200 and body["rows"] == 3
        session = service.get_session("acme", "clicks")
        assert session is not None and session.row_gate is not None

    def test_unregistered_stays_404(self, service):
        """The endpoint's documented contract survives the catalog: an
        UNREGISTERED tenant is still 404, never auto-created."""
        exporter = service.start_exporter()
        status, body = self._post(
            exporter, "/ingest/v1/ghost/clicks", self._payload()
        )
        assert status == 404 and body["error"] == "unknown_session"
        assert service.get_session("ghost", "clicks") is None

    def test_fully_rejected_frame_is_422(self, service, catalog):
        catalog.register("acme", _doc(max_len=3))
        exporter = service.start_exporter()
        status, body = self._post(
            exporter, "/ingest/v1/acme/clicks",
            self._payload(s="way-too-long"),
        )
        assert status == 422 and body["error"] == "frame_quarantined"

    def test_over_quota_is_429_with_resource(self, service, catalog):
        doc = _doc(quotas={"rows_per_s": 5})
        doc["session"] = {"admission_block_s": 0.0}
        catalog.register("acme", doc)
        exporter = service.start_exporter()
        last = None
        for i in range(4):
            last = self._post(
                exporter, "/ingest/v1/acme/clicks", self._payload(rows=4)
            )
            if last[0] == 429:
                break
        status, body = last
        assert status == 429
        assert body["error"] == "quota_exceeded"
        assert body["resource"] == "rows_per_s"

    def test_unservable_catalog_is_503(self, service, catalog, tmp_path):
        catalog.register("acme", _doc())
        # tamper the ONLY version: registered but nothing servable
        tdir = os.path.join(catalog.path, "t-acme")
        with open(os.path.join(tdir, "v00000001.json"), "w") as fh:
            fh.write("garbage")
        exporter = service.start_exporter()
        status, body = self._post(
            exporter, "/ingest/v1/acme/clicks", self._payload()
        )
        assert status == 503 and body["error"] == "catalog_error"
