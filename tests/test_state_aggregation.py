"""Ports of the reference ``StateAggregationTests.scala`` merge scenarios
against the mesh-path merge machinery: ``collective_merge_states`` (the
butterfly the sharded scan uses) and ``host_merge_states`` (the elastic
layer's salvage merge). The reference proves state aggregation is exact by
comparing a full-data run against ``runOnAggregatedStates`` over partition
states; here every scenario additionally pins that BOTH merge
implementations agree — the salvage path must never drift from the
collective it substitutes for.

Scenarios: cross-partition equivalence (full == merge of partitions),
merge-of-merges associativity, and empty-state identity (merging with an
``init_state`` changes nothing) — the algebra the whole elastic-mesh
recovery story rests on.
"""

import numpy as np
import pytest

import jax

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Distinctness,
    KLLParameters,
    KLLSketch,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
from deequ_tpu.data import Dataset
from deequ_tpu.parallel import (
    collective_merge_states,
    host_merge_states,
    make_mesh,
)
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import ScanEngine

pytestmark = pytest.mark.mesh

SCAN_ANALYZERS = [
    Size(),
    Completeness("att1"),
    Mean("price"),
    Sum("price"),
    Minimum("price"),
    Maximum("price"),
    StandardDeviation("price"),
    ApproxCountDistinct("att1"),
    KLLSketch("price", KLLParameters(128, 0.64, 10)),
]


def _partitions():
    """Three uneven partitions of one logical dataset (the reference's
    data/dataUpdated split, widened to exercise >2-way merges)."""
    rng = np.random.default_rng(42)
    parts = []
    for i, rows in enumerate((900, 1700, 400)):
        import pyarrow as pa

        price = rng.normal(50 + 10 * i, 12, rows)
        att1 = rng.integers(0, 40, rows).astype(np.float64)
        parts.append(
            Dataset.from_arrow(
                pa.table(
                    {
                        "price": pa.array(price),
                        "att1": pa.array(
                            att1, mask=rng.random(rows) < 0.08
                        ),
                    }
                )
            )
        )
    return parts


def _full(parts):
    import pyarrow as pa

    return Dataset.from_arrow(
        pa.concat_tables([p.arrow for p in parts])
    )


def _partition_states(parts):
    out = []
    for p in parts:
        states, _ = ScanEngine(SCAN_ANALYZERS).run(p)
        out.append(tuple(states))
    return out


def _stack(shard_states):
    return tuple(
        jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]),
            *[s[i] for s in shard_states],
        )
        for i in range(len(SCAN_ANALYZERS))
    )


def _metric(analyzer, state):
    return analyzer.compute_metric_from(
        jax.tree_util.tree_map(np.asarray, state)
    )


def _assert_metric_equal(analyzer, got_state, want_metric, rel=1e-9):
    got = _metric(analyzer, got_state).value.get()
    want = want_metric.value.get()
    if isinstance(analyzer, KLLSketch):
        assert sum(b.count for b in got.buckets) == sum(
            b.count for b in want.buckets
        )
    else:
        assert got == pytest.approx(want, rel=rel), analyzer


class TestCrossPartitionEquivalence:
    """Reference: 'correctly aggregate <analyzer> states' — metrics from
    merged partition states equal the full-data run's."""

    def test_collective_and_salvage_merges_match_full_run(self):
        parts = _partitions()
        full_ctx = AnalysisRunner.do_analysis_run(_full(parts), SCAN_ANALYZERS)
        shard_states = _partition_states(parts)
        collective = collective_merge_states(
            SCAN_ANALYZERS, make_mesh(4), _stack(shard_states)
        )
        salvage = host_merge_states(SCAN_ANALYZERS, shard_states)
        for i, a in enumerate(SCAN_ANALYZERS):
            want = full_ctx.metric(a)
            _assert_metric_equal(a, collective[i], want)
            _assert_metric_equal(a, salvage[i], want)

    def test_aggregated_states_runner_equivalence(self):
        """The reference's own aggregation surface
        (``runOnAggregatedStates``) agrees with the full run for grouping
        analyzers too (Uniqueness/Distinctness ride FrequenciesAndNumRows
        states, merged via outer-join adds)."""
        parts = _partitions()
        analyzers = [
            Size(), Distinctness(("att1",)), Uniqueness(("att1",)),
        ]
        providers = []
        for p in parts:
            prov = InMemoryStateProvider()
            AnalysisRunner.do_analysis_run(
                p, analyzers, save_states_with=prov
            )
            providers.append(prov)
        merged_ctx = AnalysisRunner.run_on_aggregated_states(
            parts[0].schema, analyzers, providers
        )
        full_ctx = AnalysisRunner.do_analysis_run(_full(parts), analyzers)
        for a in analyzers:
            assert merged_ctx.metric(a).value.get() == pytest.approx(
                full_ctx.metric(a).value.get(), rel=1e-9
            ), a


class TestMergeAlgebra:
    def test_merge_of_merges_associativity(self):
        """(a + b) + c == a + (b + c) == collective([a, b, c]) — the
        property that makes salvage-then-replay legal at any point in the
        fold."""
        shard_states = _partition_states(_partitions())
        a_states, b_states, c_states = shard_states
        for i, analyzer in enumerate(SCAN_ANALYZERS):
            left = analyzer.merge(
                analyzer.merge(a_states[i], b_states[i]), c_states[i]
            )
            right = analyzer.merge(
                a_states[i], analyzer.merge(b_states[i], c_states[i])
            )
            collective = collective_merge_states(
                SCAN_ANALYZERS, make_mesh(2), _stack(shard_states)
            )[i]
            want = _metric(analyzer, left)
            _assert_metric_equal(analyzer, right, want, rel=1e-12)
            _assert_metric_equal(analyzer, collective, want, rel=1e-12)

    def test_empty_state_identity(self):
        """Merging with ``init_state`` is the identity — what makes both
        shard-dim padding and the salvage re-stack ([merged, ident, ...])
        exact rather than approximate."""
        shard_states = _partition_states(_partitions())
        for i, analyzer in enumerate(SCAN_ANALYZERS):
            state = shard_states[1][i]
            want = _metric(analyzer, state)
            merged_r = analyzer.merge(state, analyzer.init_state())
            merged_l = analyzer.merge(analyzer.init_state(), state)
            _assert_metric_equal(analyzer, merged_r, want, rel=1e-12)
            _assert_metric_equal(analyzer, merged_l, want, rel=1e-12)

    def test_salvage_merge_of_empty_shard_list_is_identity(self):
        states = host_merge_states(SCAN_ANALYZERS, [])
        assert int(np.asarray(states[0].num_matches)) == 0
