"""Chaos soak: the whole service plane under a seeded mixed fault plan.

Drives `tools.chaos_soak.run_soak` — one-shot verifications plus a
streaming session through the scheduler while the deterministic injector
fires device failures, OOMs, per-analyzer faults, worker deaths and
stream-fold crashes — and asserts the reliability invariants (every job
terminates typed, metric maps stay complete, streaming folds neither drop
nor double). The tier-1 variant is small; the big soak is marked slow.
"""

import pytest

from tools.chaos_soak import default_plan, run_soak


@pytest.mark.chaos
def test_small_soak_invariants_hold():
    summary = run_soak(jobs=10, stream_batches=4, rows=2048, seed=3, workers=3)
    assert summary["ok"], summary
    assert summary["succeeded"] + summary["typed_failures"] == 10
    assert summary["unterminated"] == 0
    assert summary["untyped_failures"] == 0
    assert summary["incomplete_metric_maps"] == 0
    assert summary["stream_fold_parity"]


@pytest.mark.chaos
def test_soak_is_deterministic_per_seed():
    """Same seed -> the same fault sequence fires (the injector is the
    deterministic part; scheduling may vary but the plan must not)."""
    from deequ_tpu.reliability import FaultInjector

    plan = default_plan(5)
    a = FaultInjector(plan, seed=5)
    b = FaultInjector(plan, seed=5)
    for injector in (a, b):
        for i in range(64):
            try:
                injector.fire("device_update", str(i))
            except Exception:  # noqa: BLE001
                pass
    assert a.fired == b.fired


@pytest.mark.chaos
@pytest.mark.slow
def test_big_soak_invariants_hold():
    summary = run_soak(jobs=50, stream_batches=12, rows=8192, seed=1,
                       workers=4, cluster_drill=True)
    assert summary["ok"], summary
    assert summary["faults_fired"] > 0  # the plan really exercised the run
    # the multi-process kill-one drill ran (or skipped itself cleanly in
    # an environment that cannot spawn the worker processes)
    drill = summary["cluster_drill"]
    assert drill["ok"], drill
    if not drill["skipped"]:
        assert drill["sessions_recovered"] >= 1, drill
