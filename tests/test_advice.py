"""Regression tests for advisor findings (ADVICE.md rounds 1 and 2).

Each test pins a previously-divergent behavior to the reference semantics so
it cannot silently regress:

- round-1: Math.round half-up parity for HLL estimates; per-constraint
  applicability failure keys; KLL persistence round-trip exactness; KLL
  bucket-count rescale to the exact value count; schema null-bound semantics
  (documented divergence).
- round-2: uniform NaN min/max semantics across device / native-host /
  numpy-host paths; KLL host sampler phase-mixing on periodic input; feed
  probe + placement recording in RunMonitor.
"""

import numpy as np
import pyarrow as pa
import pytest

from deequ_tpu.analyzers import (
    ApproxQuantile,
    KLLParameters,
    KLLSketch,
    Maximum,
    Mean,
    Minimum,
    StandardDeviation,
    Sum,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor


class TestHllRoundHalfUp:
    """Reference `StatefulHyperloglogPlus.count` ends in JVM `Math.round`
    (half-up); numpy `rint` is half-to-even and diverges on .5 ties."""

    def test_math_round_semantics(self):
        from deequ_tpu.ops.hll import round_half_up

        assert round_half_up(0.5) == 1.0   # rint: 0
        assert round_half_up(2.5) == 3.0   # rint: 2
        assert round_half_up(-1.5) == -1.0  # rint: -2 (Math.round(-1.5) == -1)
        assert round_half_up(2.4) == 2.0
        assert round_half_up(2.6) == 3.0


class TestApplicabilityConstraintKeys:
    """Reference keys applicability failures by `constraint.toString`
    (`Applicability.scala:176-177`); keying by analyzer collapses two
    failing constraints that share one analyzer."""

    def test_duplicate_analyzer_failures_both_reported(self):
        from deequ_tpu.applicability import Applicability
        from deequ_tpu.checks import Check, CheckLevel
        from deequ_tpu.data import ColumnKind, ColumnSchema, Schema

        check = (
            Check(CheckLevel.ERROR, "dup")
            .has_min("s", lambda v: v > 0, hint="first")
            .has_min("s", lambda v: v > 10, hint="second")
        )
        schema = Schema([ColumnSchema("s", ColumnKind.STRING, True)])
        result = Applicability.is_applicable_check(check, schema)
        assert not result.is_applicable
        # both constraints failed (Minimum on a string column) and BOTH
        # appear — previously the second overwrote the first
        assert len(result.failures) == 2


class TestKLLBucketRescale:
    """The batch pre-collapse can drop remainder weight; bucket counts must
    still telescope to the EXACT count like the reference's weight-preserving
    compactor (`NonSampleCompactor.scala:29-69`)."""

    @pytest.mark.parametrize("placement", ["device", "host"])
    def test_bucket_counts_sum_to_exact_count(self, placement):
        rng = np.random.default_rng(0)
        n = 10000
        data = Dataset.from_dict({"x": rng.normal(size=n)})
        a = KLLSketch("x", KLLParameters(sketch_size=256, number_of_buckets=10))
        ctx = AnalysisRunner.do_analysis_run(
            data, [a], batch_size=2048, placement=placement
        )
        dist = ctx.metric(a).value.get()
        assert sum(b.count for b in dist.buckets) == n
        assert all(b.count >= 0 for b in dist.buckets)


class TestKLLPersistenceRoundTrip:
    """Persisted KLL state must round-trip bit-exactly (the documented f32
    item caveat lives at `ops/kll.py ITEM_DTYPE`; what IS stored must come
    back identical)."""

    def test_filesystem_round_trip_bit_exact(self, tmp_path):
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        rng = np.random.default_rng(1)
        data = Dataset.from_dict({"x": rng.normal(size=5000)})
        a = KLLSketch("x")
        sp = FileSystemStateProvider(str(tmp_path))
        AnalysisRunner.do_analysis_run(data, [a], save_states_with=sp)
        loaded = sp.load(a)
        again = sp.load(a)
        for lhs, rhs in zip(
            (loaded.items, loaded.sizes, loaded.count, loaded.g_min, loaded.g_max),
            (again.items, again.sizes, again.count, again.g_min, again.g_max),
        ):
            np.testing.assert_array_equal(np.asarray(lhs), np.asarray(rhs))
        # min/max/count persist at full precision even though items are f32
        assert np.asarray(loaded.g_min).dtype == np.float64
        assert np.asarray(loaded.count).dtype == np.int64


class TestSchemaNullBounds:
    """Documented divergence: the reference's min-bound CNF contains the
    constant-false `colIsNull.isNull` (`RowLevelSchemaValidator.scala:246`),
    an apparent typo that makes NULL fail minValue but pass maxValue. This
    build treats both bounds symmetrically: NULL passes when nullable."""

    def test_null_rows_pass_both_bounds_when_nullable(self):
        from deequ_tpu.schema import RowLevelSchema, RowLevelSchemaValidator

        schema = RowLevelSchema().with_int_column(
            "i", is_nullable=True, min_value=1, max_value=10
        )
        data = Dataset.from_arrow(
            pa.table({"i": pa.array([None, 5, 0, 99], type=pa.int64())})
        )
        result = RowLevelSchemaValidator.validate(data, schema)
        assert result.num_valid_rows == 2   # None and 5
        assert result.num_invalid_rows == 2  # 0 (< min), 99 (> max)


NAN = float("nan")


class TestNaNMinMaxSemantics:
    """Spark's NaN-largest total order, uniform across device streaming,
    native host tier and numpy host fallback: NaN never wins a min; any NaN
    wins the max; sum/mean/stddev propagate NaN."""

    def _run(self, values, placement):
        data = Dataset.from_arrow(pa.table({"x": pa.array(values, type=pa.float64())}))
        battery = [Minimum("x"), Maximum("x"), Mean("x"), Sum("x"), StandardDeviation("x")]
        return AnalysisRunner.do_analysis_run(data, battery, placement=placement)

    @pytest.mark.parametrize("placement", ["device", "host"])
    def test_mixed_nan(self, placement):
        ctx = self._run([5.0, NAN, 2.0, 9.0], placement)
        assert ctx.metric(Minimum("x")).value.get() == 2.0
        assert np.isnan(ctx.metric(Maximum("x")).value.get())
        assert np.isnan(ctx.metric(Mean("x")).value.get())
        assert np.isnan(ctx.metric(Sum("x")).value.get())
        assert np.isnan(ctx.metric(StandardDeviation("x")).value.get())

    @pytest.mark.parametrize("placement", ["device", "host"])
    def test_all_nan(self, placement):
        ctx = self._run([NAN, NAN], placement)
        # Spark: min/max over all-NaN are NaN (successful metrics, not empty)
        assert np.isnan(ctx.metric(Minimum("x")).value.get())
        assert np.isnan(ctx.metric(Maximum("x")).value.get())

    @pytest.mark.parametrize("placement", ["device", "host"])
    def test_nulls_still_empty(self, placement):
        data = Dataset.from_arrow(
            pa.table({"x": pa.array([None, None], type=pa.float64())})
        )
        ctx = AnalysisRunner.do_analysis_run(
            data, [Minimum("x"), Maximum("x")], placement=placement
        )
        assert not ctx.metric(Minimum("x")).value.is_success
        assert not ctx.metric(Maximum("x")).value.is_success

    def test_numpy_fallback_matches_native(self, monkeypatch):
        """Third code path (host tier without the native library)."""
        import deequ_tpu.native as native_mod

        monkeypatch.setattr(native_mod, "native_block_stats", None)
        ctx = self._run([5.0, NAN, 2.0, 9.0], "host")
        assert ctx.metric(Minimum("x")).value.get() == 2.0
        assert np.isnan(ctx.metric(Maximum("x")).value.get())

    @pytest.mark.parametrize("placement", ["device", "host"])
    def test_literal_inf_values_survive(self, placement):
        """+inf/-inf are ordinary ordered values, distinct from NaN."""
        ctx = self._run([float("inf"), float("inf")], placement)
        assert ctx.metric(Minimum("x")).value.get() == float("inf")
        assert ctx.metric(Maximum("x")).value.get() == float("inf")


class TestKLLSamplerPhaseMixing:
    """The host block sampler's stride offset mixes the valid-value count so
    a stream periodic in the batch size cannot phase-lock the sampler."""

    def test_periodic_input_quantile(self):
        # period-16 sawtooth aligned with the stride at batch 4096, k=400
        n = 65536
        vals = np.tile(np.arange(16, dtype=np.float64), n // 16)
        data = Dataset.from_dict({"x": vals})
        a = ApproxQuantile("x", 0.5, relative_error=0.01)
        ctx = AnalysisRunner.do_analysis_run(
            data, [a], batch_size=4096, placement="host"
        )
        med = ctx.metric(a).value.get()
        assert abs(med - 7.5) <= 1.5  # true median of 0..15 sawtooth

    def test_sorted_input_quantile(self):
        n = 65536
        data = Dataset.from_dict({"x": np.arange(n, dtype=np.float64)})
        a = ApproxQuantile("x", 0.5, relative_error=0.01)
        ctx = AnalysisRunner.do_analysis_run(
            data, [a], batch_size=4096, placement="host"
        )
        med = ctx.metric(a).value.get()
        assert abs(med - n / 2) <= 0.02 * n


class TestPlacementRecording:
    """Every run records which ingest tier executed (and the probed feed
    bandwidth when auto-placement ran) through RunMonitor."""

    def test_monitor_records_placement(self):
        data = Dataset.from_dict({"x": np.arange(100, dtype=np.float64)})
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(data, [Mean("x")], monitor=mon, placement="host")
        assert mon.placement == "host"
        mon.reset()
        assert mon.placement is None
        AnalysisRunner.do_analysis_run(
            data, [Mean("x")], monitor=mon, placement="device"
        )
        assert mon.placement == "device"

    def test_auto_placement_records_bandwidth(self):
        data = Dataset.from_dict({"x": np.arange(100, dtype=np.float64)})
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(data, [Mean("x")], monitor=mon, placement="auto")
        assert mon.feed_bandwidth_mbps is not None
        assert mon.feed_bandwidth_mbps > 0
        assert mon.placement in ("host", "device")


class TestHostTierQuantileAccuracy:
    """The host bottom-sampler must honor ApproxQuantile's relative_error
    like the device path does (regression: a plain k-item pick had ~2x the
    rank error and broke the 1% envelope at the tails)."""

    @pytest.mark.parametrize("q", [0.1, 0.5, 0.9])
    def test_host_rank_error_within_envelope(self, q):
        from deequ_tpu.analyzers import ApproxQuantile

        rng = np.random.default_rng(9)
        vals = rng.normal(100, 15, 100_000)
        data = Dataset.from_dict({"col": vals})
        a = ApproxQuantile("col", q, relative_error=0.01)
        ctx = AnalysisRunner.do_analysis_run(data, [a], placement="host")
        est = ctx.metric(a).value.get()
        rank = (np.sort(vals) <= est).mean()
        assert abs(rank - q) <= 0.01, (q, est, rank)


class TestHeterogeneousStateMergeFallback:
    """ADVICE r3: merge_states_batched must not np.stack states whose leaf
    shapes differ (e.g. KLL sketches persisted before a capacity widening);
    it must fall back to the sequential analyzer.merge fold."""

    def test_mixed_width_kll_states_merge(self):
        import jax.numpy as jnp

        from deequ_tpu.analyzers.base import merge_states_batched
        from deequ_tpu.ops.kll import kll_init, kll_merge, kll_update

        rng = np.random.default_rng(3)
        ones = jnp.ones(500, dtype=bool)
        a = kll_update(kll_init(sketch_size=64), jnp.asarray(rng.normal(size=500)), ones)
        b = kll_update(kll_init(sketch_size=64), jnp.asarray(rng.normal(size=500)), ones)
        # simulate a state persisted under an older, narrower item-buffer
        # layout: same treedef, different leaf shape
        narrow = b.replace(items=jnp.asarray(np.asarray(b.items)[:, :128]))

        class _KLLMergeOnly:
            def merge(self, x, y):
                return kll_merge(x, y)

        merged = merge_states_batched(_KLLMergeOnly(), [a, narrow])
        assert int(merged.count) == 1000

    def test_python_scalar_leaves_do_not_crash(self):
        from deequ_tpu.analyzers.base import merge_states_batched
        from deequ_tpu.analyzers.states import MeanState

        a = Mean("x")
        # second state carries python-scalar leaves (no .dtype): the shape
        # probe must not raise AttributeError
        states = [MeanState(np.float64(1.0), np.int64(1)), MeanState(2.0, 1)]
        merged = merge_states_batched(a, states)
        assert a.compute_metric_from(merged).value.get() == pytest.approx(1.5)

    def test_homogeneous_states_still_batch(self):
        from deequ_tpu.analyzers.base import merge_states_batched
        from deequ_tpu.analyzers.states import MeanState

        a = Mean("x")
        states = [
            MeanState(np.float64(float(i)), np.int64(1)) for i in range(4)
        ]
        merged = merge_states_batched(a, states)
        assert a.compute_metric_from(merged).value.get() == pytest.approx(1.5)


class TestKllSlimInvariantGuard:
    """ADVICE r3: _restore_kll_width must fail loudly (not silently corrupt
    quantiles) if a state was fetched mid-append with a non-top level
    holding more than sketch_size items."""

    def test_violation_raises(self):
        from deequ_tpu.ops.kll import kll_init, kll_update
        from deequ_tpu.runners.engine import _restore_kll_width, _slim_kll_for_fetch

        import jax.numpy as jnp

        vals = jnp.asarray(np.random.default_rng(0).normal(size=4000))
        s = kll_update(kll_init(sketch_size=32), vals, jnp.ones(4000, dtype=bool))
        slim, widths = _slim_kll_for_fetch((s,))
        assert widths[0] is not None
        low, top = slim[0]
        # forge a mid-append fetch: claim a non-top level holds > k items
        bad_sizes = np.asarray(low.sizes).copy()
        bad_sizes[0] = low.sketch_size + 5
        forged = low.replace(sizes=jnp.asarray(bad_sizes))
        with pytest.raises(AssertionError, match="mid-append"):
            _restore_kll_width([(forged, np.asarray(top))], widths)

    def test_normal_roundtrip_lossless(self):
        import jax.numpy as jnp

        from deequ_tpu.ops.kll import kll_init, kll_update
        from deequ_tpu.ops.kll_host import HostKLL
        from deequ_tpu.runners.engine import _restore_kll_width, _slim_kll_for_fetch

        vals = jnp.asarray(np.random.default_rng(1).normal(size=4000))
        s = kll_update(kll_init(sketch_size=32), vals, jnp.ones(4000, dtype=bool))
        slim, widths = _slim_kll_for_fetch((s,))
        low, top = slim[0]
        restored = _restore_kll_width(
            [(low, np.asarray(top))], widths
        )[0]
        assert np.asarray(restored.items).shape == np.asarray(s.items).shape
        for q in (0.1, 0.5, 0.9):
            assert HostKLL.from_state(restored).quantile(q) == HostKLL.from_state(s).quantile(q)


class TestJavaDoubleToStringParity:
    """VERDICT r3 weak #5: Spark casts DoubleType to string via Java
    Double.toString — scientific notation outside [1e-3, 1e7), shortest
    round-trip digits — so Histogram bin keys and suggestion category lists
    must match those strings exactly."""

    @pytest.mark.parametrize(
        "x,expected",
        [
            (1e7, "1.0E7"),
            (12345678.9, "1.23456789E7"),
            (1e-4, "1.0E-4"),
            (5e-4, "5.0E-4"),
            (0.00012345, "1.2345E-4"),
            (-0.0, "-0.0"),
            (0.0, "0.0"),
            (1e-3, "0.001"),
            (9999999.5, "9999999.5"),
            (100.0, "100.0"),
            (123.456, "123.456"),
            (-12345678.9, "-1.23456789E7"),
            (1.5e-5, "1.5E-5"),
            (1e16, "1.0E16"),
            (1.23456789e14, "1.23456789E14"),
            (float("nan"), "NaN"),
            (float("inf"), "Infinity"),
            (float("-inf"), "-Infinity"),
            (2.5e-323, "2.5E-323"),
            (1.7976931348623157e308, "1.7976931348623157E308"),
        ],
    )
    def test_matrix(self, x, expected):
        from deequ_tpu.analyzers.grouping import _spark_string_cast

        assert _spark_string_cast(x) == expected

    def test_histogram_keys_use_java_format(self):
        from deequ_tpu.analyzers import Histogram

        vals = np.array([1e7, 1e7, 0.5, 1e-4], dtype=np.float64)
        data = Dataset.from_dict({"x": vals})
        a = Histogram("x")
        ctx = AnalysisRunner.do_analysis_run(data, [a])
        dist = ctx.metric(a).value.get()
        assert dist["1.0E7"].absolute == 2
        assert dist["0.5"].absolute == 1
        assert dist["1.0E-4"].absolute == 1


class TestTwoPhaseFetchParity:
    """ADVICE r4: _fetch_states_two_phase's economic gate never fires in CI,
    so pin it DIRECTLY (bypassing the gate) against the one-phase slim path
    across occupancy shapes, including an empty sketch and an occupied top
    level."""

    def _sketch(self, values):
        import jax.numpy as jnp

        from deequ_tpu.ops.kll import kll_init, kll_update

        state = kll_init(64)
        if len(values):
            v = jnp.asarray(np.asarray(values, dtype=np.float64))
            state = kll_update(state, v, jnp.ones(len(values), dtype=bool))
        return state

    def _occupied_top(self):
        import jax.numpy as jnp

        from deequ_tpu.ops.kll import kll_init

        state = kll_init(64)
        items = np.asarray(state.items).copy()
        sizes = np.asarray(state.sizes).copy()
        items[-1, :70] = np.sort(np.linspace(0, 1, 70))
        sizes[-1] = 70
        return state.replace(
            items=jnp.asarray(items), sizes=jnp.asarray(sizes),
            count=jnp.asarray(70 << 31, dtype=state.count.dtype),
        )

    def test_matches_one_phase_slim_path(self):
        import jax

        from deequ_tpu.ops.kll import KLLSketchState
        from deequ_tpu.runners.engine import (
            _fetch_states_packed_raw,
            _fetch_states_two_phase,
            _restore_kll_width,
            _slim_kll_for_fetch,
        )

        rng = np.random.default_rng(12)
        states = (
            self._sketch(rng.normal(size=50_000)),  # multi-level occupancy
            self._sketch([]),                       # empty sketch
            self._sketch(rng.normal(size=100)),     # single level
            self._occupied_top(),                   # top level occupied
        )
        states = tuple(jax.device_put(s) for s in states)
        kll_idx = [
            i for i, s in enumerate(states)
            if isinstance(s, KLLSketchState) and s.items.shape[1] > s.sketch_size
        ]
        two_phase = _fetch_states_two_phase(states, kll_idx)
        slim, widths = _slim_kll_for_fetch(states)
        one_phase = _restore_kll_width(_fetch_states_packed_raw(slim), widths)
        for a, b in zip(two_phase, one_phase):
            la, ta = jax.tree_util.tree_flatten(a)
            lb, tb = jax.tree_util.tree_flatten(b)
            assert ta == tb
            for x, y in zip(la, lb):
                assert np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True)


class TestDictMaskedBincountFuzz:
    """ADVICE r4: fuzz native_dict_masked_bincount against the masked
    np.bincount formulation, covering out-of-range and negative codes."""

    def test_fuzz_against_numpy_oracle(self):
        from deequ_tpu.native import native_dict_masked_bincount

        if native_dict_masked_bincount is None:
            pytest.skip("native kernels unavailable")
        rng = np.random.default_rng(13)
        for trial in range(25):
            n = int(rng.integers(0, 5000))
            num_cats = int(rng.integers(1, 50))
            codes = rng.integers(-3, num_cats + 4, n).astype(np.int32)
            mask = rng.random(n) < rng.random()
            got = native_dict_masked_bincount(codes, mask, num_cats)
            want = np.zeros(num_cats + 1, dtype=np.int64)
            in_range = mask & (codes >= 0) & (codes < num_cats)
            np.add.at(want, codes[in_range], 1)
            want[num_cats] = n - int(in_range.sum())
            assert np.array_equal(got, want), trial
