"""Port of the reference's `AnalysisBasedConstraintTest.scala` mocked-metric
scenarios (VERDICT r5 ask #6): constraint evaluation against a hand-built
metric map — no data pass — pinning the failure-message contract and the
status precedence rules of `constraints/AnalysisBasedConstraint.scala:42-122`.

Scenarios (reference test names in comments):
- assert correctly on values if analysis is successful
- missing analysis -> MISSING_ANALYSIS_MESSAGE, never an exception
- value picker runs on the metric value; a RAISING picker degrades to
  PROBLEMATIC_METRIC_PICKER
- a raising assertion degrades to ASSERTION_EXCEPTION
- a Failure metric propagates its exception message
- check/suite status precedence: constraint failures roll up by check
  level (Error > Warning > Success)
"""

import pytest

from deequ_tpu.analyzers import Completeness, Mean, Size
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.constraints import (
    ASSERTION_EXCEPTION,
    MISSING_ANALYSIS_MESSAGE,
    PROBLEMATIC_METRIC_PICKER,
    AnalysisBasedConstraint,
    ConstraintStatus,
)
from deequ_tpu.exceptions import MetricCalculationRuntimeException
from deequ_tpu.metrics import DoubleMetric, Entity, Failure, Success
from deequ_tpu.runners.context import AnalyzerContext
from deequ_tpu.verification import VerificationSuite


def _metric(value, analyzer=None, success=True):
    analyzer = analyzer or Completeness("att1")
    wrapped = (
        Success(float(value))
        if success
        else Failure(MetricCalculationRuntimeException(str(value)))
    )
    return DoubleMetric(Entity.COLUMN, analyzer.name, analyzer.instance, wrapped)


class TestAnalysisBasedConstraintScenarios:
    def test_assert_correctly_on_values_if_analysis_is_successful(self):
        # reference: "assert correctly on values if analysis is successful"
        analyzer = Completeness("att1")
        results = {analyzer: _metric(0.5, analyzer)}
        passing = AnalysisBasedConstraint(analyzer, lambda v: v == 0.5)
        failing = AnalysisBasedConstraint(analyzer, lambda v: v > 0.9)
        assert passing.evaluate(results).status == ConstraintStatus.SUCCESS
        failed = failing.evaluate(results)
        assert failed.status == ConstraintStatus.FAILURE
        assert "does not meet the constraint requirement" in failed.message

    def test_missing_analysis_yields_typed_message(self):
        # reference: evaluation without the metric in the context
        constraint = AnalysisBasedConstraint(
            Completeness("att1"), lambda v: v == 1.0
        )
        result = constraint.evaluate({})
        assert result.status == ConstraintStatus.FAILURE
        assert result.message == MISSING_ANALYSIS_MESSAGE

    def test_value_picker_runs_on_metric_value(self):
        # reference: "execute value picker on the analysis result value"
        analyzer = Completeness("att1")
        results = {analyzer: _metric(0.5, analyzer)}
        constraint = AnalysisBasedConstraint(
            analyzer, lambda v: v == 50, value_picker=lambda v: v * 100
        )
        assert constraint.evaluate(results).status == ConstraintStatus.SUCCESS

    def test_failing_value_picker_degrades_typed(self):
        # reference: "fail on analysis if value picker is provided but fails"
        analyzer = Completeness("att1")
        results = {analyzer: _metric(0.5, analyzer)}

        def exploding_picker(value):
            raise RuntimeError("picker exploded")

        constraint = AnalysisBasedConstraint(
            analyzer, lambda v: True, value_picker=exploding_picker
        )
        result = constraint.evaluate(results)
        assert result.status == ConstraintStatus.FAILURE
        assert result.message.startswith(PROBLEMATIC_METRIC_PICKER)
        assert result.metric is not None  # the metric itself was fine

    def test_raising_assertion_degrades_typed(self):
        # reference: "fail on failed assertion" (exception variant)
        analyzer = Completeness("att1")
        results = {analyzer: _metric(0.5, analyzer)}

        def exploding_assertion(value):
            raise ValueError("assertion exploded")

        constraint = AnalysisBasedConstraint(analyzer, exploding_assertion)
        result = constraint.evaluate(results)
        assert result.status == ConstraintStatus.FAILURE
        assert result.message.startswith(ASSERTION_EXCEPTION)

    def test_failure_metric_propagates_exception_message(self):
        # reference: a failed metric calculation surfaces in the constraint
        analyzer = Completeness("att1")
        results = {analyzer: _metric("division by zero", analyzer, success=False)}
        constraint = AnalysisBasedConstraint(analyzer, lambda v: True)
        result = constraint.evaluate(results)
        assert result.status == ConstraintStatus.FAILURE
        assert "division by zero" in result.message

    def test_hint_rides_the_failure_message(self):
        analyzer = Completeness("att1")
        results = {analyzer: _metric(0.5, analyzer)}
        constraint = AnalysisBasedConstraint(
            analyzer, lambda v: v > 0.9, hint="att1 must be nearly complete"
        )
        result = constraint.evaluate(results)
        assert "att1 must be nearly complete" in result.message


class TestStatusPrecedence:
    """Reference status-precedence behavior: constraint failures roll up to
    their check's level, and the suite reports the MOST severe check."""

    def _context(self, size_value: float) -> AnalyzerContext:
        return AnalyzerContext(
            {
                Size(): DoubleMetric(
                    Entity.DATASET, "Size", "*", Success(size_value)
                ),
                Mean("att1"): DoubleMetric(
                    Entity.COLUMN, "Mean", "att1", Success(5.0)
                ),
            }
        )

    def test_error_check_failure_is_error(self):
        check = Check(CheckLevel.ERROR, "errors").has_size(lambda n: n > 100)
        result = check.evaluate(self._context(5))
        assert result.status == CheckStatus.ERROR

    def test_warning_check_failure_is_warning(self):
        check = Check(CheckLevel.WARNING, "warns").has_size(lambda n: n > 100)
        result = check.evaluate(self._context(5))
        assert result.status == CheckStatus.WARNING

    def test_suite_status_is_max_severity(self):
        warning = Check(CheckLevel.WARNING, "warns").has_size(lambda n: n > 100)
        error = Check(CheckLevel.ERROR, "errors").has_mean(
            "att1", lambda m: m < 0
        )
        passing = Check(CheckLevel.ERROR, "passes").has_size(lambda n: n == 5)
        context = self._context(5)
        only_warning = VerificationSuite.evaluate([warning, passing], context)
        assert only_warning.status == CheckStatus.WARNING
        with_error = VerificationSuite.evaluate(
            [warning, error, passing], context
        )
        assert with_error.status == CheckStatus.ERROR
        all_pass = VerificationSuite.evaluate([passing], context)
        assert all_pass.status == CheckStatus.SUCCESS

    def test_success_inside_failing_check_stays_visible(self):
        check = (
            Check(CheckLevel.ERROR, "mixed")
            .has_size(lambda n: n == 5)
            .has_mean("att1", lambda m: m < 0)
        )
        result = check.evaluate(self._context(5))
        statuses = [r.status for r in result.constraint_results]
        assert ConstraintStatus.SUCCESS in statuses
        assert ConstraintStatus.FAILURE in statuses
        assert result.status == CheckStatus.ERROR
