"""Cross-session fold coalescing (service.coalesce): routing, parity,
FIFO, fault isolation, scheduler-diet invariants.

Parity contract pinned here:

- the tiny-delta HOST fast path is BIT-EXACT against the serial path on
  both tiers (its states are identity-merge transparent and its merge is
  the numpy twin of the compiled one);
- the coalesced DEVICE launch (vmap of the identical fused update) is
  bit-exact for the algebraic accumulator classes; KLL sketches stay
  within their documented rank-error envelope (vmap lowers the sketch's
  sort/compaction differently — both results are valid sketches of the
  same data) and Correlation agrees to ~1 ulp (batched co-moment
  reduction order).
"""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

import deequ_tpu  # noqa: F401 - x64 config
from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Correlation,
    KLLParameters,
    KLLSketch,
    Mean,
    Size,
    StandardDeviation,
    Uniqueness,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.service import VerificationService
from deequ_tpu.service.coalesce import (
    COALESCE_ENV,
    FAST_PATH_MAX_ROWS_ENV,
    CrossoverRouter,
    build_fold_plan,
    coalesce_enabled,
)

pytestmark = pytest.mark.coalesce


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in (COALESCE_ENV, FAST_PATH_MAX_ROWS_ENV,
                "DEEQU_TPU_COALESCE_MAX_WIDTH", "DEEQU_TPU_PLACEMENT"):
        monkeypatch.delenv(var, raising=False)
    yield


def _table(rows: int, seed: int) -> "pa.Table":
    rng = np.random.default_rng(seed)
    return pa.table({
        "x": pa.array(rng.normal(size=rows),
                      mask=rng.random(rows) < 0.05),
        "y": rng.normal(10.0, 2.0, size=rows),
        "k": rng.integers(0, 500, size=rows),
    })


def _checks():
    return [
        Check(CheckLevel.ERROR, "battery")
        .has_size(lambda n: n > 0)
        .is_complete("y")
        .has_completeness("x", lambda c: c > 0.5)
        .has_mean("y", lambda m: 5 < m < 15)
        .has_sum("y", lambda s: s > 0)
        .has_min("y", lambda m: True)
        .has_max("y", lambda m: True),
    ]


def _metrics_map(session):
    cum = session.current()
    return {
        repr(a): m.value.get()
        for a, m in cum.metrics.items()
        if m.value.is_success
    }


def _run_stream(
    coalesce: str,
    *,
    placement=None,
    required=(),
    checks=None,
    sessions=1,
    batches=3,
    rows=4096,
    workers=2,
    pipelined=False,
    monkeypatch=None,
    force_device=False,
):
    monkeypatch.setenv(COALESCE_ENV, coalesce)
    if force_device:
        monkeypatch.setenv(FAST_PATH_MAX_ROWS_ENV, "0")
    if placement:
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
    svc = VerificationService(workers=workers, background_warm=False)
    try:
        sess = [
            svc.session(f"t{i}", "d", checks or _checks(),
                        required_analyzers=list(required))
            for i in range(sessions)
        ]
        for b in range(batches):
            handles = []
            for i, s in enumerate(sess):
                data = _table(rows, seed=1000 + 97 * i + b)
                if pipelined:
                    handles.append(s.ingest(data, wait=False))
                else:
                    s.ingest(data)
            for h in handles:
                h.result(180)
        outs = [_metrics_map(s) for s in sess]
        counters = svc.metrics.json_snapshot()["counters"]
        return outs, counters
    finally:
        svc.close()


class TestRouting:
    def test_escape_hatch_reproduces_serial_path(self, monkeypatch):
        """DEEQU_TPU_COALESCE=0: no routing counters, no fast folds, no
        coalesced launches — the exact pre-coalescing path."""
        outs_off, counters = _run_stream("0", monkeypatch=monkeypatch)
        assert "deequ_service_fold_route_total" not in counters
        assert "deequ_service_fast_path_folds_total" not in counters
        assert "deequ_service_coalesced_folds_total" not in counters
        assert outs_off[0]  # the folds themselves completed

    def test_fast_route_for_transparent_battery(self, monkeypatch):
        outs, counters = _run_stream("1", monkeypatch=monkeypatch,
                                     batches=2)
        fast = counters.get("deequ_service_fast_path_folds_total", {})
        total = sum(fast.values()) if isinstance(fast, dict) else fast
        assert total == 2
        routes = counters["deequ_service_fold_route_total"]
        assert routes.get("route=fast") == 2

    def test_sketch_battery_routes_device(self, monkeypatch):
        """KLL overrides ingest_partial and its state is not
        identity-merge transparent -> the crossover router must send the
        battery to the coalesced device path, never the host fast path."""
        _, counters = _run_stream(
            "1", monkeypatch=monkeypatch, batches=2,
            required=[KLLSketch("y", KLLParameters(256, 0.64, 10))],
        )
        routes = counters["deequ_service_fold_route_total"]
        assert routes.get("route=fast") is None
        assert routes.get("route=device") == 2
        co = counters.get("deequ_service_coalesced_folds_total", 0)
        assert co == 2

    def test_grouping_battery_routes_serial(self, monkeypatch):
        checks = [Check(CheckLevel.ERROR, "g").has_uniqueness(
            ["k"], lambda u: True)]
        _, counters = _run_stream(
            "1", monkeypatch=monkeypatch, checks=checks, batches=1,
        )
        routes = counters["deequ_service_fold_route_total"]
        assert routes.get("route=serial") == 1

    def test_multi_batch_fold_keeps_engine_path(self, monkeypatch):
        """A micro-batch larger than the bucket cap streams through the
        ordinary engine (multi-batch pass) — never coalesced."""
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=1, background_warm=False)
        try:
            s = svc.session("t", "big", _checks(), batch_size=2048)
            s.ingest(_table(8192, seed=3))
            counters = svc.metrics.json_snapshot()["counters"]
            assert "deequ_service_fast_path_folds_total" not in counters
            assert s.batches_ingested == 1
        finally:
            svc.close()


class TestParity:
    def test_fast_path_bit_exact_vs_serial_both_tiers(self, monkeypatch):
        req = [ApproxCountDistinct("k")]
        fast, counters = _run_stream(
            "1", monkeypatch=monkeypatch, required=req)
        assert sum(
            counters["deequ_service_fast_path_folds_total"].values()
        ) == 3
        serial_auto, _ = _run_stream(
            "0", monkeypatch=monkeypatch, required=req)
        serial_host, _ = _run_stream(
            "0", placement="host", monkeypatch=monkeypatch, required=req)
        assert fast == serial_auto  # bit-exact, device-tier serial
        assert fast == serial_host  # bit-exact, host-tier serial

    def test_coalesced_device_parity_vs_serial(self, monkeypatch):
        """3 sessions' folds stacked into vmapped launches: accumulator
        classes bit-exact vs the serial device path; KLL within its
        sketch envelope; Correlation within reduction-order ulps."""
        from deequ_tpu.analyzers import ApproxQuantile

        req = [
            StandardDeviation("y"), Correlation("x", "y"),
            ApproxCountDistinct("k"),
            ApproxQuantile("y", 0.5),  # KLL state through the vmapped fold
        ]
        dev, counters = _run_stream(
            "1", monkeypatch=monkeypatch, required=req, sessions=3,
            workers=1, pipelined=True, force_device=True,
        )
        assert counters["deequ_service_coalesced_folds_total"] == 9
        ser, _ = _run_stream(
            "0", monkeypatch=monkeypatch, required=req, sessions=3,
            workers=1, pipelined=True,
        )
        for got, want in zip(dev, ser):
            assert set(got) == set(want)
            for key in want:
                if "ApproxQuantile" in key or "KLL" in key:
                    assert got[key] == pytest.approx(want[key], rel=2e-2)
                elif "Correlation" in key:
                    assert got[key] == pytest.approx(want[key], rel=1e-9)
                else:
                    assert got[key] == want[key], key

    def test_coalesced_launch_width_recorded(self, monkeypatch):
        _, counters = _run_stream(
            "1", monkeypatch=monkeypatch, sessions=4, workers=1,
            pipelined=True, batches=2, force_device=True,
        )
        widths = counters["deequ_service_coalesce_width_total"]
        # 1 worker + pipelined submits: drains find peers (width > 1)
        assert any(k != "width=1" for k in widths)
        assert counters["deequ_service_coalesce_width_sum"] == 8


class TestFifoAndAtomicity:
    def test_per_session_fifo_under_coalescing(self, monkeypatch):
        """Pipelined folds of many sessions drain cross-session, but each
        session's folds must commit in submission order: cumulative Size
        over batches 1..N is strictly increasing in each session's result
        ring, and batch counts equal folds submitted."""
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=4, background_warm=False)
        try:
            n_sessions, n_batches = 8, 6
            sess = [
                svc.session(f"t{i}", "fifo", _checks())
                for i in range(n_sessions)
            ]
            handles = []
            for b in range(n_batches):
                for i, s in enumerate(sess):
                    handles.append(
                        s.ingest(_table(512, seed=i * 100 + b), wait=False)
                    )
            for h in handles:
                h.result(180)
            for s in sess:
                assert s.batches_ingested == n_batches
                sizes = []
                for r in s.results:
                    for a, m in r.metrics.items():
                        if a.name == "Size":
                            sizes.append(m.value.get())
                assert sizes == sorted(sizes)
                assert sizes[-1] == 512 * n_batches
        finally:
            svc.close()

    def test_on_result_delivered_once_per_fold(self, monkeypatch):
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=2, background_warm=False)
        seen = []
        lock = threading.Lock()

        def cb(result):
            with lock:
                seen.append(result)

        try:
            s = svc.session("t", "cb", _checks(), on_result=cb)
            hs = [s.ingest(_table(256, seed=i), wait=False) for i in range(5)]
            for h in hs:
                h.result(60)
            assert len(seen) == 5
        finally:
            svc.close()

    def test_retried_job_never_refolds(self, monkeypatch):
        """A fold executed by a drain is memoized: its job (or a retry of
        it) consumes the result instead of folding again."""
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=1, background_warm=False)
        try:
            s = svc.session("t", "memo", _checks(), max_retries=2)
            for i in range(4):
                s.ingest(_table(256, seed=i))
            assert s.batches_ingested == 4
            assert s.rows_ingested == 4 * 256
        finally:
            svc.close()


@pytest.mark.chaos
class TestFaultIsolation:
    def test_fault_mid_coalesced_launch_quarantines_owner_only(
        self, monkeypatch
    ):
        """An injected fault inside the joint launch must fail ONLY the
        owning session's fold (group bisection), with the siblings'
        folds committed."""
        from deequ_tpu.reliability import FaultSpec, inject
        from deequ_tpu.service import JobFailed

        monkeypatch.setenv(COALESCE_ENV, "1")
        monkeypatch.setenv(FAST_PATH_MAX_ROWS_ENV, "0")  # device route
        svc = VerificationService(workers=1, background_warm=False)
        try:
            sess = [
                svc.session(f"t{i}", "chaos", _checks()) for i in range(4)
            ]
            with inject(
                FaultSpec("coalesced_fold", "poison", every=1, count=None,
                          match="t2/chaos")
            ):
                handles = [
                    s.ingest(_table(512, seed=i), wait=False)
                    for i, s in enumerate(sess)
                ]
                outcomes = []
                for h in handles:
                    try:
                        outcomes.append(("ok", h.result(120)))
                    except JobFailed as exc:
                        outcomes.append(("failed", exc))
            assert [o[0] for o in outcomes] == ["ok", "ok", "failed", "ok"]
            for i, s in enumerate(sess):
                assert s.batches_ingested == (0 if i == 2 else 1)
            quarantined = svc.metrics.counter_value(
                "deequ_service_coalesce_quarantined_total"
            )
            assert quarantined == 1
        finally:
            svc.close()

    def test_fast_fold_fault_fails_alone(self, monkeypatch):
        from deequ_tpu.reliability import FaultSpec, inject
        from deequ_tpu.service import JobFailed

        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=1, background_warm=False)
        try:
            sess = [
                svc.session(f"t{i}", "fchaos", _checks()) for i in range(3)
            ]
            with inject(
                FaultSpec("coalesced_fold", "poison", every=1, count=None,
                          match="t1/fchaos")
            ):
                handles = [
                    s.ingest(_table(512, seed=i), wait=False)
                    for i, s in enumerate(sess)
                ]
                results = []
                for h in handles:
                    try:
                        h.result(60)
                        results.append("ok")
                    except JobFailed:
                        results.append("failed")
            assert results == ["ok", "failed", "ok"]
            assert [s.batches_ingested for s in sess] == [1, 0, 1]
        finally:
            svc.close()


class TestCrossoverRouter:
    def test_route_respects_env_override(self, monkeypatch):
        router = CrossoverRouter()
        from deequ_tpu.data import Dataset

        data = Dataset.from_arrow(_table(64, seed=1))
        plan = build_fold_plan([Size(), Mean("y")], data.schema)
        assert plan is not None and plan.fast_ok
        monkeypatch.setenv(FAST_PATH_MAX_ROWS_ENV, "1000")
        assert router.route(plan, 1000) == "fast"
        assert router.route(plan, 1001) == "device"
        monkeypatch.setenv(FAST_PATH_MAX_ROWS_ENV, "0")
        assert router.route(plan, 1) == "device"

    def test_measured_rates_move_the_crossover(self):
        router = CrossoverRouter()
        before = router.crossover_rows([Mean])
        # a faster measured host kernel pushes the crossover up
        for _ in range(50):
            router.observe_host(Mean, 1_000_000, 0.02)  # 50M rows/s
        after = router.crossover_rows([Mean])
        assert after > before
        # a host rate above the device's per-row rate: host never loses
        fast_router = CrossoverRouter()
        for _ in range(50):
            fast_router.observe_host(Mean, 1_000_000, 0.001)  # 1e9 rows/s
        assert fast_router.crossover_rows([Mean]) == 1 << 62
        # a cheaper measured device fixed cost pulls the crossover down
        for _ in range(50):
            router.observe_device(4096, 0.0005, 1)
        assert router.crossover_rows([Mean]) < after

    def test_non_transparent_classes_never_fast(self):
        from deequ_tpu.data import Dataset

        data = Dataset.from_arrow(_table(64, seed=1))
        plan = build_fold_plan(
            [Size(), StandardDeviation("y")], data.schema
        )
        assert plan is not None and not plan.fast_ok
        assert CrossoverRouter().route(plan, 16) == "device"

    def test_plan_ineligible_for_grouping_and_preconditions(self):
        from deequ_tpu.data import Dataset

        data = Dataset.from_arrow(_table(64, seed=1))
        assert build_fold_plan([Uniqueness(["k"])], data.schema) is None
        assert build_fold_plan([Mean("missing")], data.schema) is None
        assert build_fold_plan([], data.schema) is None

    def test_knob_defaults(self, monkeypatch):
        assert coalesce_enabled()
        monkeypatch.setenv(COALESCE_ENV, "0")
        assert not coalesce_enabled()


class TestHostMerge:
    def test_host_merge_matches_compiled_merge_bitwise(self):
        import jax

        from deequ_tpu.analyzers.states import (
            ApproxCountDistinctState,
            DataTypeHistogram,
            MaxState,
            MeanState,
            MinState,
            NumMatches,
            NumMatchesAndCount,
            SumState,
            host_merge,
        )

        rng = np.random.default_rng(11)

        def np_state(cls, *leaves):
            return cls(*[np.asarray(l) for l in leaves])

        cases = []
        for _ in range(200):
            a, b = rng.normal(0, 1e6, 2)
            n1, n2 = rng.integers(0, 1 << 40, 2)
            cases.extend([
                (np_state(NumMatches, np.int64(n1)),
                 np_state(NumMatches, np.int64(n2))),
                (np_state(MeanState, a, np.int64(n1)),
                 np_state(MeanState, b, np.int64(n2))),
                (np_state(SumState, a, np.int64(n1)),
                 np_state(SumState, b, np.int64(n2))),
                (np_state(MinState, a, np.int64(n1)),
                 np_state(MinState, b, np.int64(n2))),
                (np_state(MaxState, a, np.int64(n1)),
                 np_state(MaxState, b, np.int64(n2))),
                (np_state(NumMatchesAndCount, np.int64(n1), np.int64(n2)),
                 np_state(NumMatchesAndCount, np.int64(n2), np.int64(n1))),
            ])
        # NaN / inf edges of the ordered states
        for edge in (np.nan, np.inf, -np.inf, -0.0, 0.0):
            cases.append((np_state(MinState, edge, np.int64(1)),
                          np_state(MinState, 1.5, np.int64(1))))
            cases.append((np_state(MaxState, 1.5, np.int64(1)),
                          np_state(MaxState, edge, np.int64(1))))
        cases.append((
            np_state(DataTypeHistogram,
                     rng.integers(0, 1 << 30, 5).astype(np.int64)),
            np_state(DataTypeHistogram,
                     rng.integers(0, 1 << 30, 5).astype(np.int64)),
        ))
        cases.append((
            np_state(ApproxCountDistinctState,
                     rng.integers(0, 30, 512).astype(np.int32)),
            np_state(ApproxCountDistinctState,
                     rng.integers(0, 30, 512).astype(np.int32)),
        ))
        for sa, sb in cases:
            got = host_merge(sa, sb)
            want = jax.device_get(sa.merge(sb))
            for g, w in zip(
                jax.tree_util.tree_leaves(got),
                jax.tree_util.tree_leaves(want),
            ):
                ga, wa = np.asarray(g), np.asarray(w)
                assert ga.dtype.kind == wa.dtype.kind
                assert np.array_equal(ga, wa, equal_nan=True), (sa, sb)

    def test_host_merge_refuses_non_transparent(self):
        from deequ_tpu.analyzers.states import (
            StandardDeviationState,
            host_merge,
        )

        s = StandardDeviationState(
            np.float64(1), np.float64(2), np.float64(3)
        )
        with pytest.raises(TypeError):
            host_merge(s, s)

    def test_identity_transparency_claims_hold(self):
        """merge(init, s) == s at the BIT level for every class in the
        registry — the algebraic fact the fast path rests on."""
        import jax

        from deequ_tpu.analyzers.states import (
            IDENTITY_TRANSPARENT_STATES,
            ApproxCountDistinctState,
            DataTypeHistogram,
            FrequencyCountsState,
            MaxState,
            MeanState,
            MinState,
            NumMatches,
            NumMatchesAndCount,
            SumState,
        )

        rng = np.random.default_rng(4)
        samples = {
            NumMatches: lambda: NumMatches(
                np.int64(rng.integers(0, 1 << 50))),
            NumMatchesAndCount: lambda: NumMatchesAndCount(
                np.int64(rng.integers(0, 1 << 50)),
                np.int64(rng.integers(0, 1 << 50))),
            MeanState: lambda: MeanState(
                np.float64(rng.normal(0, 1e9)),
                np.int64(rng.integers(0, 1 << 50))),
            SumState: lambda: SumState(
                np.float64(rng.normal(0, 1e9)),
                np.int64(rng.integers(0, 1 << 50))),
            MinState: lambda: MinState(
                np.float64(rng.normal()), np.int64(1)),
            MaxState: lambda: MaxState(
                np.float64(rng.normal()), np.int64(1)),
            DataTypeHistogram: lambda: DataTypeHistogram(
                rng.integers(0, 1 << 40, 5).astype(np.int64)),
            ApproxCountDistinctState: lambda: ApproxCountDistinctState(
                rng.integers(0, 31, 512).astype(np.int32)),
            FrequencyCountsState: lambda: FrequencyCountsState(
                rng.integers(0, 1 << 40, 16).astype(np.int64),
                np.int64(rng.integers(0, 1 << 50))),
        }
        assert set(samples) == set(IDENTITY_TRANSPARENT_STATES)
        for cls, make in samples.items():
            if cls is FrequencyCountsState:
                init = FrequencyCountsState.init(16)
            else:
                init = cls.init()
            for _ in range(25):
                s = make()
                merged = jax.device_get(init.merge(s))
                for m, o in zip(
                    jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(s),
                ):
                    assert np.array_equal(
                        np.asarray(m), np.asarray(o), equal_nan=True
                    ), cls


class TestSchedulerDiet:
    def test_absorbed_jobs_resolve_without_running(self, monkeypatch):
        """Under a drain, sibling jobs finish straight from the queue:
        every handle resolves, phases are harvested, stream counters hold
        the exact fold count."""
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=2, background_warm=False)
        try:
            sess = [
                svc.session(f"t{i}", "abs", _checks()) for i in range(16)
            ]
            handles = [
                s.ingest(_table(256, seed=i), wait=False)
                for i, s in enumerate(sess)
            ]
            for h in handles:
                r = h.result(120)
                assert r.status == CheckStatus.SUCCESS
            assert svc.metrics.counter_value(
                "deequ_service_stream_batches_total"
            ) == 16
            assert svc.metrics.counter_value(
                "deequ_service_jobs_completed_total"
            ) >= 16
            # phase harvests reached the export plane for absorbed folds
            assert svc.metrics.counter_value(
                "deequ_service_phase_seconds_total", phase="host_partials"
            ) > 0
        finally:
            svc.close()

    def test_backpressure_and_shed_semantics_unchanged(self, monkeypatch):
        from deequ_tpu.service import ServiceOverloaded

        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(
            workers=1, max_queue_depth=2, background_warm=False
        )
        try:
            gate = threading.Event()
            svc.scheduler.submit(lambda ctx: gate.wait(20))
            time.sleep(0.1)
            s = svc.session("t", "bp", _checks())
            s.ingest(_table(128, seed=1), wait=False)
            s.ingest(_table(128, seed=2), wait=False)
            with pytest.raises(ServiceOverloaded):
                s.ingest(_table(128, seed=3), wait=False)
            gate.set()
        finally:
            svc.close()

    def test_deadlined_folds_never_cross_drain(self, monkeypatch):
        """A fold with a deadline executes only under its own job (the
        queued-past-deadline contract needs the scheduler's clock), so
        it must not be claimable by another session's drain."""
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=2, background_warm=False)
        try:
            s = svc.session("t", "dl", _checks(), deadline_s=30.0)
            r = s.ingest(_table(256, seed=1))
            assert r.status == CheckStatus.SUCCESS
            assert s.batches_ingested == 1
        finally:
            svc.close()


class TestStreamingSemantics:
    def test_drift_reject_unchanged_under_coalescing(self, monkeypatch):
        from deequ_tpu.exceptions import SchemaDriftError

        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=1, background_warm=False)
        try:
            s = svc.session("t", "drift", _checks())
            s.ingest(_table(256, seed=1))
            drifted = pa.table({"x": np.zeros(16)})
            with pytest.raises(SchemaDriftError):
                s.ingest(drifted)
            assert s.batches_ingested == 1
        finally:
            svc.close()

    def test_contract_commits_after_first_coalesced_fold(self, monkeypatch):
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=1, background_warm=False)
        try:
            s = svc.session("t", "contract", _checks())
            assert s._contract is None
            s.ingest(_table(256, seed=1))
            assert s._contract is not None
        finally:
            svc.close()

    def test_closed_session_rejects_typed(self, monkeypatch):
        from deequ_tpu.service import SessionClosed

        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=1, background_warm=False)
        try:
            s = svc.session("t", "closed", _checks())
            s.ingest(_table(256, seed=1))
            s.close()
            with pytest.raises(SessionClosed):
                s.ingest(_table(256, seed=2))
        finally:
            svc.close()

    def test_monitor_counters_reach_run_monitor(self, monkeypatch):
        from deequ_tpu.runners.engine import RunMonitor

        m = RunMonitor()
        other = RunMonitor()
        other.fast_path_folds = 2
        other.coalesced_folds = 3
        other.batches = 5
        other.phase_seconds = {"host_partials": 0.5}
        other.cost_by_analyzer = {"Mean": 0.1}
        m.merge_from(other)
        m.merge_from(RunMonitor())
        assert m.fast_path_folds == 2
        assert m.coalesced_folds == 3
        assert m.batches == 5
        assert m.phase_seconds["host_partials"] == 0.5
        assert m.cost_by_analyzer["Mean"] == 0.1


class TestOrderingAcrossKeys:
    """Review-hardening pins: per-session FIFO must hold even when a
    session's folds land under DIFFERENT coalesce keys (varying buckets)
    or mix serial-path folds between coalesced ones."""

    def test_drain_never_claims_past_an_older_fold_in_another_key(
        self, monkeypatch
    ):
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=0, background_warm=False)
        try:
            co = svc.coalescer
            from deequ_tpu.ingest.columnar import as_dataset

            s1 = svc.session("v", "d", _checks())
            s2 = svc.session("w", "d", _checks())
            small = as_dataset(_table(512, seed=1))   # bucket 1024
            big1 = as_dataset(_table(3000, seed=2))   # bucket 4096
            big2 = as_dataset(_table(3000, seed=3))
            p1 = co.prepare(s1, small, 1024)
            p2 = co.prepare(s1, big1, 4096)
            p3 = co.prepare(s2, big2, 4096)
            for p in (p1, p2, p3):
                assert p is not None
                co.mark_submitted(p)
            assert p2.key == p3.key and p1.key != p2.key
            with co._lock:
                group = co._claim_group_locked(p3)
            # s1's oldest outstanding fold is p1 (a DIFFERENT key): the
            # drain on p3's key must NOT claim p2 ahead of it
            assert group == [p3]
            # once p1 completes, p2 becomes s1's head and is drainable
            co._complete(p1, result="r1")
            with co._lock:
                extra = co._claim_sweep_locked(p3.key)
            assert extra == [p2]
        finally:
            svc.close()

    def test_serial_barrier_blocks_cross_drain(self, monkeypatch):
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=0, background_warm=False)
        try:
            co = svc.coalescer
            from deequ_tpu.ingest.columnar import as_dataset

            s1 = svc.session("v", "bar", _checks())
            s2 = svc.session("w", "bar", _checks())
            assert co.note_serial_fold(s1)  # an outstanding serial fold
            p1 = co.prepare(s1, as_dataset(_table(256, seed=1)), 1024)
            p2 = co.prepare(s2, as_dataset(_table(256, seed=2)), 1024)
            co.mark_submitted(p1)
            co.mark_submitted(p2)
            with co._lock:
                group = co._claim_group_locked(p2)
            assert group == [p2]  # p1 barred by the serial barrier
            co.clear_serial_barrier(("v", "bar"))
            with co._lock:
                extra = co._claim_sweep_locked(p1.key)
            assert extra == [p1]
        finally:
            svc.close()

    def test_mixed_bucket_pipelined_session_commits_in_order(
        self, monkeypatch
    ):
        """End-to-end: a session alternating micro-batch sizes (two
        coalesce keys) among many same-key sessions must still see its
        cumulative Size grow monotonically in submission order."""
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=4, background_warm=False)
        try:
            victim = svc.session("v", "mix", _checks())
            peers = [
                svc.session(f"p{i}", "mix", _checks()) for i in range(6)
            ]
            handles = []
            sizes = [512, 3000, 700, 2500, 900, 3500]
            for b, rows in enumerate(sizes):
                handles.append(
                    victim.ingest(_table(rows, seed=b), wait=False)
                )
                for i, p in enumerate(peers):
                    handles.append(
                        p.ingest(_table(3000, seed=100 + i), wait=False)
                    )
            for h in handles:
                h.result(180)
            cum = []
            for r in victim.results:
                for a, m in r.metrics.items():
                    if a.name == "Size":
                        cum.append(m.value.get())
            assert cum == [float(sum(sizes[: i + 1]))
                           for i in range(len(sizes))]
        finally:
            svc.close()


class TestCommitFinishAtomicity:
    """A fold's COMMIT and its job's FINISH are atomic with respect to
    worker-fault injection (the chaos soak's stream_fold_parity flake):
    a job killed OUTSIDE the fold body must withdraw an unclaimed fold —
    no later drain may commit a batch the caller was told failed — or
    adopt the outcome of a drain that already claimed it."""

    def test_worker_fault_withdraws_unclaimed_fold(self, monkeypatch):
        """Pre-fix: the orphaned fold lingered claimable and the NEXT
        ingest's drain committed it — after its failure, and out of
        order (seed tree measured batches_ingested=2, sizes
        [700, 1212])."""
        from deequ_tpu.reliability import FaultSpec, inject
        from deequ_tpu.service.errors import JobFailed

        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=2, background_warm=False)
        try:
            s = svc.session("t", "orphan", _checks())
            with inject(FaultSpec("worker", "worker_death", at=1)) as inj:
                h1 = s.ingest(_table(512, 1), wait=False)
                with pytest.raises(JobFailed):
                    h1.result(60)
            assert inj.fired
            s.ingest(_table(700, 2), timeout=60)
            time.sleep(0.3)  # any stray drain would misbehave here
            assert s.batches_ingested == 1
            sizes = [
                m.value.get()
                for r in s.results
                for a, m in r.metrics.items()
                if a.name == "Size"
            ]
            assert sizes == [700.0], sizes
        finally:
            svc.close()

    def test_job_adopts_drain_committed_outcome(self, monkeypatch):
        """White-box: the fold was CLAIMED by another worker's drain when
        its own job died pre-body — reconcile waits the claim out and
        adopts the committed result (the job then finishes success)."""
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=0, background_warm=False)
        try:
            co = svc.coalescer
            from deequ_tpu.ingest.columnar import as_dataset

            s = svc.session("t", "adopt", _checks())
            p = co.prepare(s, as_dataset(_table(256, 3)), 1024)
            co.mark_submitted(p)
            with co._lock:
                group = co._claim_group_locked(p)
            assert group == [p]
            out = []
            t = threading.Thread(
                target=lambda: out.append(
                    co.reconcile_orphan(None, p, RuntimeError("crash"))
                )
            )
            t.start()
            time.sleep(0.2)
            assert not out, "reconcile must wait for the claim owner"
            co._complete(p, result="committed-by-drain")
            t.join(10)
            assert out == [("committed-by-drain", None)]
        finally:
            svc.close()

    def test_withdrawn_fold_invisible_to_sweeps(self, monkeypatch):
        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=0, background_warm=False)
        try:
            co = svc.coalescer
            from deequ_tpu.ingest.columnar import as_dataset

            s = svc.session("t", "withdraw", _checks())
            p = co.prepare(s, as_dataset(_table(256, 4)), 1024)
            co.mark_submitted(p)
            assert co.reconcile_orphan(
                None, p, RuntimeError("crash")
            ) is None
            assert p.error is not None
            with co._lock:
                assert co._claim_sweep_locked(p.key) == []
            # the session's fifo released: a later fold is drainable
            p2 = co.prepare(s, as_dataset(_table(256, 5)), 1024)
            co.mark_submitted(p2)
            with co._lock:
                assert co._claim_group_locked(p2) == [p2]
        finally:
            svc.close()

    def test_deferred_sibling_blocks_cross_key_pickup(self):
        """The _pick ordering rule behind the mixed-bucket inversion fix:
        an INELIGIBLE (drain-deferred) job blocks later same-serial-key
        jobs from pickup — skipping it would let fold N+1 claim and
        commit ahead of fold N."""
        from deequ_tpu.service import battery_signature
        from deequ_tpu.service.scheduler import JobScheduler

        sched = JobScheduler(workers=0, max_queue_depth=16)
        try:
            ran = []
            # j2 carries a signature worker 0 is WARM for: the affinity
            # promotion path must honor the blocked key exactly like the
            # first-eligible scan (it used to re-open the inversion)
            sig = battery_signature([Mean("deferred_affinity_col")])
            sched.router.note_ran(sig, 0, placement="device")
            sched.defer_pickup("keyA")
            sched.submit(lambda ctx: ran.append(1), serial_key="s",
                         defer_key="keyA", job_id="j1")
            sched.submit(lambda ctx: ran.append(3), serial_key="other",
                         job_id="j3")
            sched.submit(lambda ctx: ran.append(2), serial_key="s",
                         defer_key="keyB", signature=sig, job_id="j2")
            with sched._lock:
                picked = sched._pick(0)
            # j1 deferred -> j2 (same serial key) must NOT be picked —
            # neither as first-eligible nor by affinity promotion; the
            # unrelated j3 is
            assert picked is not None and picked.job_id == "j3"
            with sched._lock:
                assert sched._pick(0) is None
            sched.resume_pickup("keyA")
            with sched._lock:
                picked = sched._pick(0)
            assert picked.job_id == "j1"
        finally:
            sched.shutdown(wait=False)


class TestRetrySemantics:
    def test_failed_fold_reexecutes_on_retry(self, monkeypatch):
        """A memoized FAILURE must re-run on a scheduler retry (the
        serial done-dict memoizes only committed results); the retry
        commits the batch exactly once."""
        from deequ_tpu.reliability import FaultSpec, inject
        from deequ_tpu.runners.engine import RunMonitor

        monkeypatch.setenv(COALESCE_ENV, "1")
        svc = VerificationService(workers=0, background_warm=False)
        try:
            co = svc.coalescer
            from deequ_tpu.ingest.columnar import as_dataset

            s = svc.session("t", "retry", _checks())
            p = co.prepare(s, as_dataset(_table(256, seed=5)), 1024)
            co.mark_submitted(p)

            class Ctx:
                def __init__(self, attempt):
                    self.attempt = attempt
                    self.worker_id = 0
                    self.monitor = RunMonitor()

            with inject(
                FaultSpec("coalesced_fold", "poison", at=1)
            ):
                with pytest.raises(Exception):
                    co.run_fold(Ctx(1), p)
                assert s.batches_ingested == 0
                # the scheduler re-dispatches: attempt 2 must RE-EXECUTE
                result = co.run_fold(Ctx(2), p)
            assert result is not None
            assert s.batches_ingested == 1
        finally:
            svc.close()
