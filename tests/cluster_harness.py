"""Reusable multi-process spawn harness for cluster tests.

Promoted from the ad-hoc plumbing inside ``tools/dcn_smoke.py`` (free
port probing, worker env, file barriers, last-JSON-line parsing, the
exit-2-means-skipped protocol) so every multi-process test — DCN smoke,
cluster soak, lease interleaving — composes the same primitives instead
of re-growing its own. Pure helpers, importable from both tests and
tools.

Protocol conventions these helpers encode:

- a tool/worker prints its machine-readable result as the LAST stdout
  line, as JSON;
- exit code 2 with ``{"skipped": true}`` means the ENVIRONMENT cannot
  run the scenario (e.g. no multi-process CPU collectives) — tests skip,
  they don't fail;
- cross-process synchronization uses file barriers in a shared temp dir
  (create-to-signal, poll-to-wait): signal-safe, debuggable post-mortem,
  and immune to the wedged-socket failure modes the drills create on
  purpose.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    """An OS-assigned free TCP port (bind-to-0 probe)."""
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        return probe.getsockname()[1]


def worker_env(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Env for a spawned cluster/DCN worker: CPU platform, ONE device per
    process (mesh axes then span processes — the DCN path)."""
    from deequ_tpu.parallel.dcn import dcn_worker_env

    env = dcn_worker_env()
    if extra:
        env.update(extra)
    return env


def spawn_module(
    module: str,
    argv: Sequence[str] = (),
    env: Optional[Dict[str, str]] = None,
) -> subprocess.Popen:
    """``python -m <module> <argv...>`` from the repo root with captured
    stdout/stderr — the shape every multi-process scenario spawns."""
    return subprocess.Popen(
        [sys.executable, "-m", module, *argv],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=env if env is not None else worker_env(), cwd=REPO_ROOT,
    )


def last_json_line(raw: bytes) -> dict:
    """The machine-readable result: last non-empty stdout line as JSON."""
    lines = [ln for ln in raw.decode().strip().splitlines() if ln.strip()]
    if not lines:
        raise ValueError("no stdout lines to parse")
    return json.loads(lines[-1])


def communicate_json(
    proc: subprocess.Popen, timeout: float = 300.0
) -> Tuple[int, dict, str]:
    """Wait for ``proc``; returns ``(returncode, report, stderr_tail)``.
    A process that died without parseable output reports
    ``{"skipped": True, "reason": ...}`` so callers uniformly skip."""
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
    tail = err.decode()[-500:] if err else ""
    try:
        report = last_json_line(out)
    except (ValueError, json.JSONDecodeError):
        report = {
            "ok": False, "skipped": True,
            "reason": f"rc={proc.returncode}, no JSON output: {tail}",
        }
    return proc.returncode, report, tail


def run_tool_json(
    module: str,
    argv: Sequence[str] = (),
    timeout: float = 300.0,
    env: Optional[Dict[str, str]] = None,
) -> Tuple[int, dict]:
    """Run a tool to completion and parse its JSON report line."""
    proc = spawn_module(module, argv, env=env)
    rc, report, _tail = communicate_json(proc, timeout=timeout)
    return rc, report


def skip_if_skipped(rc: int, report: dict) -> None:
    """pytest.skip on the exit-2/"skipped" protocol (sandboxes without
    multi-process CPU collectives must not fail the suite)."""
    import pytest

    if rc == 2 or report.get("skipped"):
        pytest.skip(
            f"environment cannot run scenario: "
            f"{report.get('reason', 'skipped')}"
        )


def barrier_dir(prefix: str = "cluster-") -> str:
    """Fresh shared temp dir for file barriers."""
    return tempfile.mkdtemp(prefix=prefix)


def signal_barrier(path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("ok")


def wait_for_file(path: str, timeout_s: float = 60.0) -> bool:
    """Poll until ``path`` exists (True) or the deadline passes (False)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.05)
    return os.path.exists(path)


def kill_and_reap(procs: List[subprocess.Popen]) -> List[str]:
    """Kill every process and return stderr tails (failure diagnostics)."""
    tails = []
    for proc in procs:
        try:
            proc.kill()
        except OSError:
            pass
        try:
            _out, err = proc.communicate(timeout=10)
            tails.append(err.decode()[-400:] if err else "")
        except Exception:  # noqa: BLE001 - diagnostics only
            tails.append("<unreapable>")
    return tails
