"""Host ingest tier (native block partials + device fold) must produce the
same metrics as the device-streaming path — the framework's placement choice
is a performance decision, never a semantic one. Mirrors the reference's
partial-aggregation-per-partition + merge execution split
(`AnalysisRunner.scala:303-318`, SURVEY.md §2.9)."""

import numpy as np
import pyarrow as pa
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Compliance,
    Correlation,
    DataType,
    KLLParameters,
    KLLSketch,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor


@pytest.fixture(scope="module")
def mixed_data():
    rng = np.random.default_rng(3)
    n = 20000
    x = rng.normal(50, 10, n)
    xnull = rng.random(n) < 0.1
    y = rng.normal(-1, 2, n)
    cats = rng.integers(0, 500, n)
    strs = np.array(
        [None if rng.random() < 0.05 else f"v{int(i)}" for i in cats], dtype=object
    )
    return Dataset.from_arrow(
        pa.table(
            {
                "x": pa.array(x, mask=xnull),
                "y": pa.array(y),
                "cat": pa.array(cats),
                "s": pa.array(strs.tolist()),
            }
        )
    )


BATTERY = [
    Size(),
    Size(where="x > 50"),
    Completeness("x"),
    Compliance("pos", "y > 0"),
    PatternMatch("s", r"v\d+"),
    Mean("x"),
    Sum("x"),
    Minimum("x"),
    Maximum("x"),
    StandardDeviation("x"),
    Correlation("x", "y"),
    MinLength("s"),
    MaxLength("s"),
    DataType("s"),
    ApproxCountDistinct("cat"),
    ApproxCountDistinct("s"),
    Mean("x", where="y > 0"),
]


class TestHostTierEquivalence:
    def test_metrics_match_device_path(self, mixed_data):
        dev = AnalysisRunner.do_analysis_run(
            mixed_data, BATTERY, batch_size=4096, placement="device"
        )
        host = AnalysisRunner.do_analysis_run(
            mixed_data, BATTERY, batch_size=4096, placement="host"
        )
        for a in BATTERY:
            dv = dev.metric(a).value
            hv = host.metric(a).value
            assert dv.is_success == hv.is_success, a
            if dv.is_success and isinstance(dv.get(), float):
                assert hv.get() == pytest.approx(dv.get(), rel=1e-9, abs=1e-12), a

    def test_hll_registers_bit_exact(self, mixed_data):
        a = ApproxCountDistinct("cat")
        dev = AnalysisRunner.do_analysis_run(mixed_data, [a], placement="device")
        host = AnalysisRunner.do_analysis_run(mixed_data, [a], placement="host")
        assert dev.metric(a).value.get() == host.metric(a).value.get()

    def test_kll_quantiles_within_bounds(self, mixed_data):
        a = ApproxQuantile("x", 0.5)
        host = AnalysisRunner.do_analysis_run(
            mixed_data, [a], batch_size=4096, placement="host"
        )
        med = host.metric(a).value.get()
        truth = np.nanquantile(
            np.where(
                np.asarray(mixed_data.arrow["x"].is_valid()),
                mixed_data.arrow["x"].to_numpy(zero_copy_only=False),
                np.nan,
            ),
            0.5,
        )
        # rank error 1% of 20k rows around a dense normal: generous envelope
        assert abs(med - truth) < 1.0

    def test_single_device_fold(self, mixed_data):
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(
            mixed_data, BATTERY, batch_size=2048, monitor=mon, placement="host"
        )
        assert mon.passes == 1
        assert mon.batches == -(-mixed_data.num_rows // 2048)
        # ALL batches fold in ONE device execution (the ingest program)
        assert mon.device_updates == 1

    def test_empty_dataset(self):
        data = Dataset.from_dict({"x": np.array([], dtype=np.float64)})
        ctx = AnalysisRunner.do_analysis_run(
            data, [Size(), Mean("x"), Minimum("x")], placement="host"
        )
        assert ctx.metric(Size()).value.get() == 0.0
        assert not ctx.metric(Mean("x")).value.is_success

    def test_incremental_state_merge_across_tiers(self, mixed_data):
        """States produced by the host tier merge cleanly with device-tier
        states (same pytree contract)."""
        from deequ_tpu.analyzers.state_provider import InMemoryStateProvider

        half = mixed_data.num_rows // 2
        first = Dataset.from_arrow(mixed_data.arrow.slice(0, half))
        second = Dataset.from_arrow(mixed_data.arrow.slice(half))
        battery = [Size(), Mean("x"), StandardDeviation("x")]

        sp = InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(
            first, battery, save_states_with=sp, placement="host"
        )
        ctx = AnalysisRunner.do_analysis_run(
            second, battery, aggregate_with=sp, placement="device"
        )
        full = AnalysisRunner.do_analysis_run(mixed_data, battery, placement="device")
        for a in battery:
            assert ctx.metric(a).value.get() == pytest.approx(
                full.metric(a).value.get(), rel=1e-9
            ), a
