"""Check/Constraint DSL + VerificationSuite tests — the analog of the
reference `checks/CheckTest.scala`, `constraints/ConstraintsTest.scala` and
`VerificationSuiteTest.scala` (incl. the BasicExample end-to-end)."""

import numpy as np
import pytest

from deequ_tpu import (
    Check,
    CheckLevel,
    CheckStatus,
    Dataset,
    VerificationSuite,
)
from deequ_tpu.analyzers import Completeness, Size
from deequ_tpu.constraints import (
    AnalysisBasedConstraint,
    ConstrainableDataTypes,
    ConstraintStatus,
    MISSING_ANALYSIS_MESSAGE,
    completeness_constraint,
)
from deequ_tpu.metrics import DoubleMetric, Entity, Success


class TestConstraintEvaluation:
    def test_missing_analysis(self):
        c = completeness_constraint("att1", lambda v: v == 1.0)
        result = c.evaluate({})
        assert result.status == ConstraintStatus.FAILURE
        assert MISSING_ANALYSIS_MESSAGE in result.message

    def test_success_and_failure(self):
        analyzer = Completeness("att1")
        metric = DoubleMetric(Entity.COLUMN, "Completeness", "att1", Success(0.5))
        ok = AnalysisBasedConstraint(analyzer, lambda v: v == 0.5)
        bad = AnalysisBasedConstraint(analyzer, lambda v: v > 0.9)
        assert ok.evaluate({analyzer: metric}).status == ConstraintStatus.SUCCESS
        res = bad.evaluate({analyzer: metric})
        assert res.status == ConstraintStatus.FAILURE
        assert "Value: 0.5 does not meet the constraint requirement!" in res.message

    def test_picker_and_assertion_errors_are_captured(self):
        analyzer = Completeness("att1")
        metric = DoubleMetric(Entity.COLUMN, "Completeness", "att1", Success(0.5))
        bad_picker = AnalysisBasedConstraint(
            analyzer, lambda v: True, value_picker=lambda v: 1 / 0
        )
        assert bad_picker.evaluate({analyzer: metric}).status == ConstraintStatus.FAILURE
        bad_assert = AnalysisBasedConstraint(analyzer, lambda v: 1 / 0 > 0)
        assert bad_assert.evaluate({analyzer: metric}).status == ConstraintStatus.FAILURE

    def test_hint_in_message(self):
        analyzer = Completeness("att1")
        metric = DoubleMetric(Entity.COLUMN, "Completeness", "att1", Success(0.5))
        c = AnalysisBasedConstraint(analyzer, lambda v: v > 0.9, hint="expect high completeness")
        assert "expect high completeness" in c.evaluate({analyzer: metric}).message


class TestCheckDSL:
    def test_basic_example_end_to_end(self):
        """The reference `examples/BasicExample.scala` scenario."""
        data = Dataset.from_dict(
            {
                "id": [1, 2, 3, 4, 5],
                "productName": ["Thingy A", "Thingy B", None, "Thingy D", "Thingy E"],
                "description": [
                    "awesome thing.",
                    "available at http://thingb.com",
                    None,
                    "checkout https://thingd.ca",
                    "thingy model E",
                ],
                "rating": ["high", "high", None, "low", "high"],
                "numViews": [0, 0, 56, 0, 86],
            }
        )
        check = (
            Check(CheckLevel.ERROR, "unit testing my data")
            .has_size(lambda v: v == 5)
            .is_complete("id")
            .is_unique("id")
            .is_complete("productName")
            .is_contained_in("rating", allowed_values=["high", "low"])
            .is_non_negative("numViews")
        )
        result = VerificationSuite.on_data(data).add_check(check).run()
        statuses = {
            str(cr.constraint): cr.status
            for r in result.check_results.values()
            for cr in r.constraint_results
        }
        # productName has a null -> isComplete fails; everything else passes
        failures = [k for k, v in statuses.items() if v == ConstraintStatus.FAILURE]
        assert len(failures) == 1
        assert "productName" in failures[0]
        assert result.status == CheckStatus.ERROR

    def test_warning_level(self, df_missing):
        check = Check(CheckLevel.WARNING, "warn").is_complete("att1")
        result = VerificationSuite.on_data(df_missing).add_check(check).run()
        assert result.status == CheckStatus.WARNING

    def test_success_status(self, df_full):
        check = (
            Check(CheckLevel.ERROR, "ok")
            .has_size(lambda v: v == 4)
            .is_complete("att1")
            .has_completeness("att1", lambda v: v == 1.0)
        )
        result = VerificationSuite.on_data(df_full).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_where_filter(self, df_numeric):
        check = Check(CheckLevel.ERROR, "filtered").has_max(
            "att1", lambda v: v == 3.0
        ).where("att1 <= 3")
        result = VerificationSuite.on_data(df_numeric).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_scan_sharing_across_checks(self, df_full):
        """Analyzers shared between checks compute once, one pass total
        (the SparkMonitor jobs-count analog)."""
        from deequ_tpu.runners.engine import RunMonitor

        mon = RunMonitor()
        c1 = Check(CheckLevel.ERROR, "a").has_size(lambda v: v == 4).is_complete("att1")
        c2 = Check(CheckLevel.ERROR, "b").is_complete("att1").is_complete("att2")
        result = (
            VerificationSuite.on_data(df_full)
            .add_check(c1)
            .add_check(c2)
            .with_monitor(mon)
            .run()
        )
        assert mon.passes == 1
        assert result.status == CheckStatus.SUCCESS
        # one metric per distinct analyzer (Size, Completeness x2)
        assert len(result.metrics) == 3

    def test_uniqueness_checks(self, df_full):
        check = (
            Check(CheckLevel.ERROR, "unique")
            .is_unique("item")
            .is_primary_key("item", "att1")
            .has_uniqueness(["att1"], lambda v: v < 0.5)
            .has_distinctness(["att1"], lambda v: v == 0.5)
        )
        result = VerificationSuite.on_data(df_full).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_pattern_checks(self):
        data = Dataset.from_dict(
            {
                "email": ["a@example.com", "b@test.org", "not-an-email"],
                "url": ["https://x.io", "nope", "http://y.de/z"],
            }
        )
        check = (
            Check(CheckLevel.ERROR, "patterns")
            .contains_email("email", lambda v: abs(v - 2 / 3) < 1e-9)
            .contains_url("url", lambda v: abs(v - 2 / 3) < 1e-9)
        )
        result = VerificationSuite.on_data(data).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_data_type_check(self):
        data = Dataset.from_dict({"mixed": ["1", "2.0", "three", "4"]})
        check = Check(CheckLevel.ERROR, "dt").has_data_type(
            "mixed", ConstrainableDataTypes.INTEGRAL, lambda v: v == 0.5
        )
        result = VerificationSuite.on_data(data).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS, [
            cr.message
            for r in result.check_results.values()
            for cr in r.constraint_results
        ]

    def test_comparison_checks(self, df_numeric):
        check = (
            Check(CheckLevel.ERROR, "cmp")
            .is_less_than_or_equal_to("att2", "att1", lambda v: v > 0.4)
            .is_contained_in("att1", lower_bound=1, upper_bound=6)
        )
        result = VerificationSuite.on_data(df_numeric).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_quantile_check(self):
        data = Dataset.from_dict({"x": np.arange(1, 101, dtype=np.float64)})
        check = Check(CheckLevel.ERROR, "q").has_approx_quantile(
            "x", 0.5, lambda v: 45 <= v <= 55
        )
        result = VerificationSuite.on_data(data).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_kll_check(self):
        data = Dataset.from_dict({"x": np.arange(0, 100, dtype=np.float64)})
        from deequ_tpu.analyzers import KLLParameters

        check = Check(CheckLevel.ERROR, "kll").kll_sketch_satisfies(
            "x",
            lambda dist: dist.buckets[0].count == 50,
            KLLParameters(1024, 0.64, 2),
        )
        result = VerificationSuite.on_data(data).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_histogram_checks(self, df_full):
        check = (
            Check(CheckLevel.ERROR, "hist")
            .has_number_of_distinct_values("att1", lambda v: v == 2)
            .has_histogram_values("att1", lambda d: d["a"].absolute == 3)
        )
        result = VerificationSuite.on_data(df_full).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_entropy_and_mi(self, df_full):
        expected_entropy = -(0.75 * np.log(0.75) + 0.25 * np.log(0.25))
        check = Check(CheckLevel.ERROR, "ent").has_entropy(
            "att1", lambda v: abs(v - expected_entropy) < 1e-9
        )
        result = VerificationSuite.on_data(df_full).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_check_results_dataframe(self, df_full):
        check = Check(CheckLevel.ERROR, "df").has_size(lambda v: v == 999)
        result = VerificationSuite.on_data(df_full).add_check(check).run()
        df = result.check_results_as_data_frame()
        assert list(df["check_status"]) == ["Error"]
        assert "does not meet the constraint requirement" in df["constraint_message"][0]
        mdf = result.success_metrics_as_data_frame()
        assert set(mdf.columns) == {"entity", "instance", "name", "value"}
        assert len(mdf) == 1

    def test_required_analyzers_dedupe(self):
        c = Check(CheckLevel.ERROR, "x").is_complete("a").has_completeness("a", lambda v: v > 0)
        assert c.required_analyzers() == {Completeness("a")}

    def test_verification_on_aggregated_states(self, df_full):
        from deequ_tpu.analyzers import InMemoryStateProvider
        from deequ_tpu.runners import AnalysisRunner

        s1, s2 = InMemoryStateProvider(), InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(df_full, [Size()], save_states_with=s1)
        AnalysisRunner.do_analysis_run(df_full, [Size()], save_states_with=s2)
        check = Check(CheckLevel.ERROR, "agg").has_size(lambda v: v == 8)
        result = VerificationSuite.run_on_aggregated_states(
            df_full.schema, [check], [s1, s2]
        )
        assert result.status == CheckStatus.SUCCESS


class TestIsContainedInNumeric:
    def test_numeric_allowed_values(self):
        data = Dataset.from_dict({"x": [1, 2, 3, 1, 2]})
        check = Check(CheckLevel.ERROR, "n").is_contained_in("x", allowed_values=[1, 2, 3])
        result = VerificationSuite.on_data(data).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_numeric_detects_violation(self):
        data = Dataset.from_dict({"x": [1, 2, 99]})
        check = Check(CheckLevel.ERROR, "n").is_contained_in("x", allowed_values=[1, 2])
        result = VerificationSuite.on_data(data).add_check(check).run()
        assert result.status == CheckStatus.ERROR
