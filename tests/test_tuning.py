"""Self-tuning performance control plane (ISSUE 18).

Four contracts pinned here:

- **registry resolution**: every routing constant resolves env override >
  tuned (autotune on) > static default, with tuned values clamped to the
  registry's audited bounds;
- **static parity**: ``DEEQU_TPU_AUTOTUNE=0`` makes the tuned layer
  invisible — every knob read, every migrated reader, and
  ``probably_low_cardinality`` behave byte-identically to the pre-registry
  constants even with poisoned tuned values installed;
- **profile integrity**: calibration profiles round-trip under their
  content checksum; corrupt/stale/torn files quarantine and surface the
  typed ``CorruptStateError`` the service boot degrades through;
- **guardrails**: candidates promote only after beating the incumbent
  beyond the band on measured traffic, losers roll back, and the
  never-below-static floor demotes every tuned knob when the live rate
  falls under the static reference (the planted-mis-calibration drill).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from deequ_tpu.exceptions import CorruptStateError
from deequ_tpu.tuning import knobs
from deequ_tpu.tuning.controller import TuningController
from deequ_tpu.tuning.profile import (
    PROFILE_VERSION,
    SubstrateProfile,
    load_profile,
    profile_dir,
    save_profile,
    substrate_fingerprint,
    substrate_key,
)

pytestmark = pytest.mark.tuning


@pytest.fixture(autouse=True)
def _clean_tuned_layer(tmp_path, monkeypatch):
    """Every test starts from static: no tuned values, and any service
    booted inside the test resolves its profile dir to an empty tmp dir
    (never the developer's real profile beside the XLA cache)."""
    knobs.clear_tuned()
    monkeypatch.setenv(knobs.TUNING_PROFILE_DIR_ENV,
                       str(tmp_path / "profiles"))
    yield
    knobs.clear_tuned()


def _profile(knob_values=None, probes=None) -> SubstrateProfile:
    return SubstrateProfile(
        substrate=substrate_key(),
        probes=probes or {"device_fixed_s": 0.002},
        knob_values=knob_values if knob_values is not None
        else {"coalesce_max_width": 8},
        calibration_wall_s=1.0,
    )


# ---------------------------------------------------------------------------
# the knob registry: resolution order, bounds, escape hatch
# ---------------------------------------------------------------------------

def test_every_knob_resolves_to_its_static_default():
    for name, k in knobs.REGISTRY.items():
        assert knobs.value(name) == k.static_default, name


def test_registry_env_names_follow_the_convention():
    for k in knobs.REGISTRY.values():
        if k.env is not None:
            assert k.env.startswith("DEEQU_TPU_"), k.name
        assert k.lo <= k.static_default <= k.hi, (
            f"{k.name}: static default outside its own clamp bounds"
        )


def test_tuned_value_wins_only_with_autotune_on(monkeypatch):
    knobs.set_tuned("coalesce_max_width", 4, source="test")
    assert knobs.value("coalesce_max_width") == 4
    monkeypatch.setenv(knobs.AUTOTUNE_ENV, "0")
    assert knobs.value("coalesce_max_width") == 16  # static, byte-for-byte
    monkeypatch.delenv(knobs.AUTOTUNE_ENV)
    assert knobs.value("coalesce_max_width") == 4


def test_env_override_beats_tuned(monkeypatch):
    knobs.set_tuned("coalesce_max_width", 4, source="test")
    monkeypatch.setenv("DEEQU_TPU_COALESCE_MAX_WIDTH", "32")
    assert knobs.value("coalesce_max_width") == 32


def test_set_tuned_clamps_to_registry_bounds():
    assert knobs.set_tuned("coalesce_max_width", 10_000) == 1024
    assert knobs.set_tuned("coalesce_max_width", 0) == 1
    assert knobs.set_tuned("prefetch_depth", -3) == 0
    with pytest.raises(KeyError):
        knobs.set_tuned("not_a_knob", 1)


def test_clear_and_snapshot_round_trip():
    assert not knobs.any_tuned()
    knobs.set_tuned("prefetch_depth", 4, source="test")
    knobs.set_tuned("coalesce_max_width", 8, source="profile")
    assert knobs.any_tuned()
    snap = knobs.tuned_snapshot()
    assert snap["prefetch_depth"] == {
        "value": 4, "source": "test", "static": 2,
    }
    knobs.clear_tuned("prefetch_depth")
    assert "prefetch_depth" not in knobs.tuned_snapshot()
    knobs.clear_tuned()
    assert not knobs.any_tuned()


def test_migrated_readers_resolve_through_the_registry(monkeypatch):
    """The hot-path readers the registry replaced read tuned values with
    autotune on — and the exact pre-registry defaults with it off."""
    from deequ_tpu.analyzers import grouping
    from deequ_tpu.ingest.prefetch import prefetch_depth
    from deequ_tpu.service.coalesce import (
        coalesce_max_width,
        fast_path_max_rows,
    )
    from deequ_tpu.service.fleet import fleet_stream_min_rows

    readers = {
        fast_path_max_rows: ("fast_path_max_rows", -1, 0),
        coalesce_max_width: ("coalesce_max_width", 16, 4),
        fleet_stream_min_rows: ("fleet_stream_min_rows", 65536, 4096),
        prefetch_depth: ("prefetch_depth", 2, 5),
        grouping.device_freq_max_cardinality: (
            "device_freq_max_cardinality", 1 << 16, 1 << 10),
        grouping.freq_table_slots: ("freq_table_slots", 1 << 22, 1 << 12),
        grouping.freq_buffer_entries: (
            "freq_buffer_entries", 1 << 25, 1 << 17),
    }
    for reader, (name, static, tuned) in readers.items():
        assert reader() == static, name
        knobs.set_tuned(name, tuned, source="test")
        assert reader() == tuned, name
    monkeypatch.setenv(knobs.AUTOTUNE_ENV, "0")
    for reader, (name, static, _tuned) in readers.items():
        assert reader() == static, f"{name}: AUTOTUNE=0 must be static"


def test_probably_low_cardinality_static_parity(monkeypatch):
    """The probe's 2M-row floor and probe sizes are knobs now — but with
    AUTOTUNE=0 a poisoned tuned layer cannot change a single routing
    answer (the byte-for-byte escape-hatch pin)."""
    import numpy as np

    from deequ_tpu.analyzers.grouping import probably_low_cardinality
    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(7)
    rows = 1 << 14
    data = Dataset.from_dict({"k": rng.integers(0, 50, size=rows)})

    baseline = probably_low_cardinality(data, ["k"])
    assert baseline is False  # under the 2M-row static floor

    # poison the tuned layer: a 0-row floor and doll-sized probe slices
    # flip the answer...
    knobs.set_tuned("freq_host_route_min_rows", 0, source="test")
    knobs.set_tuned("freq_probe_rows", 1024, source="test")
    assert probably_low_cardinality(data, ["k"]) is True
    # ...but AUTOTUNE=0 restores the static answer byte-for-byte
    monkeypatch.setenv(knobs.AUTOTUNE_ENV, "0")
    assert probably_low_cardinality(data, ["k"]) is baseline


def test_router_reseeds_from_tuned_knobs():
    from deequ_tpu.service.coalesce import CrossoverRouter

    static = CrossoverRouter()
    assert static.crossover_rows([object]) == int(
        knobs.static_value("router_device_fixed_s")
        / (1.0 / knobs.static_value("router_host_rows_per_s")
           - 1.0 / knobs.static_value("router_device_rows_per_s"))
    )
    knobs.set_tuned("router_host_rows_per_s", 1e12, source="test")
    tuned = CrossoverRouter()
    # host faster than the device per-row rate: host never loses
    assert tuned.crossover_rows([object]) == 1 << 62
    # a measured device launch outranks any later reseed of the fixed cost
    tuned.observe_device(rows=1 << 20, seconds=0.5, folds=1)
    fixed = tuned._device_fixed_s
    knobs.set_tuned("router_device_fixed_s", 5.0, source="test")
    tuned.reseed_from_knobs()
    assert tuned._device_fixed_s == fixed


# ---------------------------------------------------------------------------
# profile persistence: checksum round trip, quarantine, staleness
# ---------------------------------------------------------------------------

def test_profile_round_trip(tmp_path):
    d = str(tmp_path)
    saved = _profile({"coalesce_max_width": 8, "prefetch_depth": 3})
    path = save_profile(saved, d)
    assert os.path.basename(path) == f"profile-{saved.fingerprint}.json"
    loaded = load_profile(d)
    assert loaded is not None
    assert loaded.knob_values == saved.knob_values
    assert loaded.probes == saved.probes
    assert loaded.substrate == substrate_key()
    assert loaded.created_at > 0


def test_missing_profile_is_none_not_an_error(tmp_path):
    assert load_profile(str(tmp_path)) is None


def test_torn_profile_quarantines_and_raises(tmp_path):
    d = str(tmp_path)
    path = save_profile(_profile(), d)
    with open(path, "w") as fh:
        fh.write("{ torn json")
    with pytest.raises(CorruptStateError, match="unreadable"):
        load_profile(d)
    assert not os.path.exists(path)
    assert os.listdir(os.path.join(d, ".quarantine"))
    # the poisoned file can never affect a later boot
    assert load_profile(d) is None


def test_checksum_mismatch_quarantines_and_raises(tmp_path):
    d = str(tmp_path)
    path = save_profile(_profile({"coalesce_max_width": 8}), d)
    with open(path) as fh:
        record = json.load(fh)
    record["payload"]["knob_values"]["coalesce_max_width"] = 1024  # tamper
    with open(path, "w") as fh:
        json.dump(record, fh)
    with pytest.raises(CorruptStateError, match="checksum"):
        load_profile(d)
    assert not os.path.exists(path)


def test_stale_schema_version_quarantines_and_raises(tmp_path):
    d = str(tmp_path)
    stale = _profile()
    stale.version = PROFILE_VERSION + 1
    path = save_profile(stale, d)
    with pytest.raises(CorruptStateError, match="version"):
        load_profile(d)
    assert not os.path.exists(path)


def test_apply_skips_unknown_knobs_and_clamps():
    profile = _profile({
        "coalesce_max_width": 10_000,     # above the hi bound
        "knob_from_the_future": 42,       # newer build's knob
    })
    applied = profile.apply(source="test")
    assert applied == {"coalesce_max_width": 1024}
    assert knobs.value("coalesce_max_width") == 1024
    assert "knob_from_the_future" not in knobs.tuned_snapshot()


def test_profile_dir_honors_env(tmp_path, monkeypatch):
    monkeypatch.setenv(knobs.TUNING_PROFILE_DIR_ENV, str(tmp_path / "p"))
    assert profile_dir() == str(tmp_path / "p")


# ---------------------------------------------------------------------------
# boot-time calibration (small probes: the real probe/derive/save loop)
# ---------------------------------------------------------------------------

def test_calibrate_smoke_derives_in_bounds_and_persists(tmp_path):
    from deequ_tpu.tuning.calibrate import calibrate

    d = str(tmp_path)
    profile = calibrate(save=True, profile_dir=d, rows=1 << 12, repeats=1)
    assert profile.calibration_wall_s > 0
    assert profile.probes["device_fixed_s"] > 0
    assert profile.probes["device_rows_per_s"] > 0
    assert profile.probes["group_host_rows_per_s"] > 0
    for name, value in profile.knob_values.items():
        k = knobs.REGISTRY[name]
        assert k.lo <= value <= k.hi, name
        assert k.cast(value) == value, name
    # calibrate() measures; it never installs into the live registry
    assert not knobs.any_tuned()
    loaded = load_profile(d)
    assert loaded is not None
    assert loaded.knob_values == profile.knob_values


def test_derive_knobs_cost_model():
    from deequ_tpu.tuning.calibrate import derive_knobs

    derived = derive_knobs({
        "host_rows_per_s_Mean": 40e6,
        "device_fixed_s": 0.004,
        "device_rows_per_s": 64e6,
        "device_stack_slope_s": 0.0005,
        "staging_rows_per_s": 16e6,
        "group_host_rows_per_s": 64e6,
        "group_device_rows_per_s": 16e6,
    })
    assert derived["router_host_rows_per_s"] == 40e6
    # 0.25 * 4ms * 64M = 64k rows -> largest power of two at most that
    assert derived["fleet_stream_min_rows"] == 32768
    # fixed/slope = 8 launches' worth of stacking
    assert derived["coalesce_max_width"] == 8
    # device consumes 4x faster than staging feeds: deeper pipeline
    assert derived["prefetch_depth"] == 5
    # host group-by 4x faster: distinct ceiling scales up (clamped ratio)
    assert derived["freq_host_route_max_distinct"] == (1 << 15) * 4


# ---------------------------------------------------------------------------
# the online controller: promotion bands, rollback, the static floor
# ---------------------------------------------------------------------------

@pytest.fixture()
def metrics():
    from deequ_tpu.service.metrics import ServiceMetrics

    return ServiceMetrics()


@pytest.fixture()
def fast_decisions(monkeypatch):
    monkeypatch.setenv(knobs.TUNING_MIN_SAMPLES_ENV, "4")
    monkeypatch.setenv(knobs.TUNING_SHADOW_FRACTION_ENV, "0.25")


def test_shadow_candidate_promotes_after_winning(metrics, fast_decisions):
    ctl = TuningController(metrics=metrics)
    assert ctl.propose("fast_path_max_rows", 8192, mode="shadow")
    assert not ctl.propose("fast_path_max_rows", 4096)  # one per knob
    for _ in range(8):
        ctl.record(4096, seconds=0.010)                   # incumbent: 410k/s
        ctl.record(4096, seconds=0.001, arm="fast_path_max_rows")  # 4.1M/s
    snap = ctl.snapshot()
    assert snap["experiments"] == {}
    assert snap["tuned"]["fast_path_max_rows"]["value"] == 8192
    assert snap["decisions"][-1]["verdict"] == "promote"
    assert metrics.counter_value(
        "deequ_service_tuning_promotions_total") == 1.0
    assert metrics.counter_value(
        "deequ_service_tuning_proposals_total") == 1.0


def test_shadow_candidate_rejects_inside_the_band(metrics, fast_decisions):
    ctl = TuningController(metrics=metrics)
    ctl.propose("fast_path_max_rows", 8192, mode="shadow")
    for _ in range(8):
        ctl.record(4096, seconds=0.010)
        ctl.record(4096, seconds=0.009, arm="fast_path_max_rows")
    snap = ctl.snapshot()
    assert "fast_path_max_rows" not in snap["tuned"]  # ~1.1x < 1.25x band
    assert snap["decisions"][-1]["verdict"] == "reject"
    assert metrics.counter_value(
        "deequ_service_tuning_demotions_total") == 1.0


def test_starved_shadow_arm_eventually_rejects(metrics, fast_decisions):
    ctl = TuningController(metrics=metrics)
    ctl.propose("fast_path_max_rows", 8192, mode="shadow")
    for _ in range(4 * 20):
        ctl.record(4096, seconds=0.005)  # incumbent only: no shadow folds
    snap = ctl.snapshot()
    assert snap["experiments"] == {}
    assert snap["decisions"][-1]["verdict"] == "reject"
    assert "fast_path_max_rows" not in snap["tuned"]


def test_trial_candidate_installs_then_rolls_back(metrics, fast_decisions):
    ctl = TuningController(metrics=metrics)
    for _ in range(6):
        ctl.record(4096, seconds=0.002)  # baseline rate before the flip
    ctl.propose("coalesce_max_width", 8, mode="trial")
    assert knobs.value("coalesce_max_width") == 8  # tentatively live
    for _ in range(4):
        ctl.record(4096, seconds=0.004)  # regressed under the candidate
    assert knobs.value("coalesce_max_width") == 16  # rolled back to static
    assert "coalesce_max_width" not in knobs.tuned_snapshot()
    assert ctl.snapshot()["decisions"][-1]["verdict"] == "reject"


def test_trial_candidate_promotes_beyond_band(metrics, fast_decisions):
    ctl = TuningController(metrics=metrics)
    for _ in range(6):
        ctl.record(4096, seconds=0.010)
    ctl.propose("coalesce_max_width", 8, mode="trial")
    for _ in range(10):
        ctl.record(4096, seconds=0.001)  # 10x the baseline
    assert knobs.tuned_snapshot()["coalesce_max_width"]["value"] == 8
    assert ctl.snapshot()["decisions"][-1]["verdict"] == "promote"


def test_floor_guardrail_demotes_planted_miscalibration(
        metrics, fast_decisions):
    """The acceptance drill's core: plant a mis-calibration, feed folds
    that measure WORSE than the static floor, and the guardrail must
    demote every tuned knob — never leaving the system below static."""
    ctl = TuningController(metrics=metrics)
    for _ in range(8):
        ctl.record(4096, seconds=0.002)  # static floor ~2M rows/s
    knobs.set_tuned("coalesce_max_width", 1, source="bad-profile")
    knobs.set_tuned("prefetch_depth", 0, source="bad-profile")
    for _ in range(8):
        ctl.record(4096, seconds=0.020)  # 10x slower than the floor
    assert not knobs.any_tuned(), "floor guardrail must demote ALL knobs"
    decision = ctl.snapshot()["decisions"][-1]
    assert decision["verdict"] == "floor_demotion"
    assert "coalesce_max_width" in decision["knob"]
    assert "prefetch_depth" in decision["knob"]
    assert metrics.counter_value(
        "deequ_service_tuning_demotions_total") == 2.0
    # the live EWMA restarted at the demotion (mid-loop, as soon as the
    # sample requirement filled): only post-demotion folds remain in it
    assert ctl.snapshot()["live_samples"] < 8


def test_floor_never_fires_at_static(metrics, fast_decisions):
    ctl = TuningController(metrics=metrics)
    for _ in range(50):
        ctl.record(4096, seconds=0.002)
    assert ctl.snapshot()["decisions"] == []


def test_choose_is_deterministic_and_counts_shadow_folds(
        metrics, fast_decisions):
    ctl = TuningController(metrics=metrics)
    ctl.propose("fast_path_max_rows", 8192, mode="shadow")
    arms = [ctl.choose(4096) for _ in range(12)]
    # fraction 0.25 -> period 4: folds 4, 8, 12 ride the candidate arm
    assert arms == [None, None, None, "host"] * 3
    # the next shadow fold (fold 16) carries rows above the candidate
    # ceiling: the forced arm is the device route
    assert [ctl.choose(1 << 20) for _ in range(4)][-1] == "device"
    assert metrics.counter_value(
        "deequ_service_tuning_shadow_folds_total") == 4.0
    assert ctl.choose(4096) is None and ctl.choose(4096) is None


def test_refit_reproposes_only_missing_profile_knobs(fast_decisions):
    profile = _profile({
        "coalesce_max_width": 8,
        "prefetch_depth": 4,
        "router_device_fixed_s": 0.001,
    })
    knobs.set_tuned("coalesce_max_width", 8, source="profile")
    ctl = TuningController(profile=profile)
    assert ctl.refit() == 1  # prefetch_depth only: width held, router skipped
    assert set(ctl.snapshot()["experiments"]) == {"prefetch_depth"}
    assert ctl.refit() == 0  # already experimenting


def test_decision_history_is_bounded(metrics, fast_decisions):
    from deequ_tpu.tuning.controller import _MAX_DECISIONS

    ctl = TuningController(metrics=metrics)
    for i in range(_MAX_DECISIONS + 40):
        ctl.propose("coalesce_max_width", 8 if i % 2 else 4, mode="trial")
        for _ in range(4):
            ctl.record(4096, seconds=0.002)
        knobs.clear_tuned()
    assert len(ctl.snapshot()["decisions"]) <= _MAX_DECISIONS


# ---------------------------------------------------------------------------
# service bootstrap: the wired-in plane and its escape hatch
# ---------------------------------------------------------------------------

def _boot_service():
    from deequ_tpu.service import VerificationService

    return VerificationService(background_warm=False)


def test_autotune_off_boots_no_controller(monkeypatch):
    monkeypatch.setenv(knobs.AUTOTUNE_ENV, "0")
    with _boot_service() as service:
        assert service.tuning_controller is None
        # a disabled plane still exports described zeros (no dashboard gaps)
        assert service.metrics.counter_value(
            "deequ_service_tuning_promotions_total") == 0.0


def test_boot_applies_profile_and_starts_controller(tmp_path, monkeypatch):
    d = str(tmp_path / "profiles")
    monkeypatch.setenv(knobs.TUNING_PROFILE_DIR_ENV, d)
    save_profile(_profile({"coalesce_max_width": 8,
                           "router_host_rows_per_s": 1e12}), d)
    with _boot_service() as service:
        ctl = service.tuning_controller
        assert ctl is not None and ctl.profile is not None
        assert knobs.tuned_snapshot()["coalesce_max_width"]["source"] == (
            "profile")
        # the router reseeded from the tuned seeds at boot
        assert service.coalescer.router._default_host_rate == 1e12


def test_corrupt_profile_boots_static_with_quarantine(tmp_path, monkeypatch):
    d = str(tmp_path / "profiles")
    monkeypatch.setenv(knobs.TUNING_PROFILE_DIR_ENV, d)
    path = save_profile(_profile({"coalesce_max_width": 8}), d)
    with open(path, "w") as fh:
        fh.write("not json")
    with _boot_service() as service:
        assert service.tuning_controller is not None
        assert service.tuning_controller.profile is None
        assert not knobs.any_tuned()  # static fallback, no poisoned knobs
    assert os.listdir(os.path.join(d, ".quarantine"))


# ---------------------------------------------------------------------------
# two-substrate parity drill: the same home directory serves distinct
# profiles to distinct substrates (8-virtual-device CPU mesh vs this host)
# ---------------------------------------------------------------------------

_MESH_DRILL = r"""
import json, sys
from deequ_tpu.tuning.calibrate import calibrate
from deequ_tpu.tuning.profile import load_profile, substrate_key

profile = calibrate(save=True, profile_dir=sys.argv[1],
                    rows=1 << 12, repeats=1)
loaded = load_profile(sys.argv[1])
print(json.dumps({
    "fingerprint": profile.fingerprint,
    "chip_count": substrate_key()["chip_count"],
    "round_trip": loaded is not None
                  and loaded.knob_values == profile.knob_values,
}))
"""


def _calibrate_drill(directory: str, device_count: int) -> dict:
    """Run the calibrate drill in a child forced to ``device_count``
    virtual CPU devices (replacing any inherited force flag — the pytest
    process itself runs under an 8-device mesh)."""
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={device_count}")
    env["XLA_FLAGS"] = " ".join(flags)
    env.pop("DEEQU_TPU_TUNING_PROFILE_DIR", None)
    proc = subprocess.run(
        [sys.executable, "-c", _MESH_DRILL, directory],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_two_substrate_profiles_coexist(tmp_path):
    """Calibrate a single-device substrate and an 8-virtual-device CPU
    mesh into the same directory: different fingerprints, two files, and
    each loader resolves only its own substrate's profile."""
    d = str(tmp_path / "shared")
    solo = _calibrate_drill(d, 1)
    mesh = _calibrate_drill(d, 8)
    assert solo["round_trip"] is True and mesh["round_trip"] is True
    assert solo["chip_count"] == 1
    assert mesh["chip_count"] == 8
    assert solo["fingerprint"] != mesh["fingerprint"]
    files = [f for f in os.listdir(d) if f.startswith("profile-")]
    assert len(files) == 2, files
    # the pytest process is itself the 8-device substrate: from the
    # shared dir it resolves ONLY the mesh profile
    loaded = load_profile(d)
    assert loaded is not None and loaded.fingerprint == mesh["fingerprint"]
