"""Fleet watch (ISSUE 15 tentpole): the standing batched anomaly plane —
scheduler-harvest trigger, one detect_batch call per strategy bundle,
deequ_service_anomaly_* export series, poisoned-history quarantine
isolation, trace-correlated flight dumps."""

import glob
import json
import os
import time

import numpy as np
import pytest

from deequ_tpu.analyzers import Completeness, Mean, Size
from deequ_tpu.anomalydetection import (
    AbsoluteChangeStrategy,
    OnlineNormalStrategy,
)
from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.data import Dataset
from deequ_tpu.metrics import DoubleMetric, Entity, Success
from deequ_tpu.repository import PartitionedMetricsRepository, ResultKey
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.context import AnalyzerContext
from deequ_tpu.service import VerificationService
from deequ_tpu.service.fleetwatch import (
    FleetWatch,
    fleetwatch_bundle_size,
    fleetwatch_window_months,
    window_after_ms,
)

DAY_MS = 86_400_000


@pytest.fixture(scope="module")
def steady_ctx():
    data = Dataset.from_dict(
        {"x": np.random.default_rng(0).normal(10, 1, 128)}
    )
    return AnalysisRunner.do_analysis_run(
        data, [Size(), Completeness("x"), Mean("x")]
    )


def wild_ctx(steady, value=999.0):
    return AnalyzerContext({
        **{a: m for a, m in steady.metric_map.items() if a != Mean("x")},
        Mean("x"): DoubleMetric(Entity.COLUMN, "Mean", "x", Success(value)),
    })


def history_repo(tmp_path, name, steady, days=30, wild_newest=False):
    repo = PartitionedMetricsRepository(str(tmp_path / name))
    now = int(time.time() * 1000)
    for day in range(days):
        repo.save(ResultKey(now - (days - day) * DAY_MS), steady)
    repo.save(
        ResultKey(now), wild_ctx(steady) if wild_newest else steady
    )
    return repo


@pytest.fixture
def service():
    with VerificationService(
        workers=2, background_warm=False, fleet=False
    ) as svc:
        yield svc


class TestHarvestScoring:
    def test_scores_fleet_and_flags_wild_tenant(self, tmp_path, service, steady_ctx):
        service.watch_metrics(
            "t-steady", history_repo(tmp_path, "a", steady_ctx),
            [Size(), Mean("x")],
        )
        service.watch_metrics(
            "t-wild",
            history_repo(tmp_path, "b", steady_ctx, wild_newest=True),
            [Size(), Mean("x")],
        )
        report = service.fleetwatch.harvest_now()
        assert report.tenants == 2
        assert report.series_scored == 4
        assert report.detect_calls == 1  # ONE bundle, one batched call
        flagged_tenants = {f[0] for f in report.flagged}
        assert flagged_tenants == {"t-wild"}
        assert any("Mean" in f[2] for f in report.flagged)
        snap = service.json_snapshot()["counters"]
        assert snap["deequ_service_anomaly_flagged_total"][
            "tenant=t-wild"
        ] >= 1
        assert snap["deequ_service_anomaly_series_scored_total"][
            "tenant=t-steady"
        ] == 2
        assert snap["deequ_service_anomaly_harvests_total"] == 1
        assert snap["deequ_service_anomaly_scoring_seconds_total"] > 0

    def test_one_call_per_strategy_bundle(self, tmp_path, service, steady_ctx):
        """Two strategies = two bundles = two calls, regardless of tenant
        count."""
        repo = history_repo(tmp_path, "a", steady_ctx)
        service.watch_metrics(
            "t1", repo, [Size(), Mean("x")], strategy=OnlineNormalStrategy()
        )
        service.watch_metrics(
            "t2", repo, [Size(), Mean("x")], strategy=OnlineNormalStrategy(),
            dataset="d2",
        )
        service.watch_metrics(
            "t3", repo, [Mean("x")],
            strategy=AbsoluteChangeStrategy(max_rate_increase=100.0),
        )
        report = service.fleetwatch.harvest_now()
        assert report.series_scored == 5
        assert report.detect_calls == 2

    def test_bundle_size_knob_chunks(self, tmp_path, service, steady_ctx, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_FLEETWATCH_BUNDLE", "1")
        assert fleetwatch_bundle_size() == 1
        service.watch_metrics(
            "t1", history_repo(tmp_path, "a", steady_ctx),
            [Size(), Mean("x")],
        )
        report = service.fleetwatch.harvest_now()
        assert report.detect_calls == 2  # one per series at bundle=1

    def test_standing_anomaly_exports_and_dumps_once(
        self, tmp_path, service, steady_ctx, monkeypatch
    ):
        """A persistently anomalous newest point stays in every harvest's
        REPORT but bumps the export counter and schedules a flight dump
        exactly once — re-dumping per harvest would drain the recorder's
        process-wide dump budget and inflate the counter by harvest
        rate."""
        flight_dir = str(tmp_path / "flight")
        monkeypatch.setenv("DEEQU_TPU_FLIGHT_DIR", flight_dir)
        service.watch_metrics(
            "t-wild",
            history_repo(tmp_path, "a", steady_ctx, wild_newest=True),
            [Mean("x")],
        )
        first = service.fleetwatch.harvest_now()
        second = service.fleetwatch.harvest_now()
        assert first.flagged and second.flagged == first.flagged
        snap = service.json_snapshot()["counters"]
        assert snap["deequ_service_anomaly_flagged_total"][
            "tenant=t-wild"
        ] == 1
        dumps = [
            p for p in glob.glob(os.path.join(flight_dir, "*.jsonl"))
            if "AnomalyFlagged" in open(p).read()
        ]
        assert len(dumps) == 1

    def test_short_holtwinters_tenant_does_not_degrade_its_bundle(
        self, tmp_path, service, steady_ctx
    ):
        """One tenant younger than two full cycles is pre-filtered
        (skipped), keeping the rest of the Holt-Winters bundle on the ONE
        batched call."""
        from deequ_tpu.anomalydetection import (
            HoltWinters, MetricInterval, SeriesSeasonality,
        )

        long_repo = history_repo(tmp_path, "a", steady_ctx, days=40)
        short_repo = PartitionedMetricsRepository(str(tmp_path / "short"))
        now = int(time.time() * 1000)
        for d in range(10):  # < 2 weekly cycles + 1
            short_repo.save(ResultKey(now - (10 - d) * DAY_MS), steady_ctx)
        hw = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        service.watch_metrics("t-long", long_repo, [Mean("x")], strategy=hw)
        service.watch_metrics("t-young", short_repo, [Mean("x")], strategy=hw)
        report = service.fleetwatch.harvest_now()
        assert report.detect_calls == 1  # the bundle stayed batched
        assert report.series_scored == 1
        assert report.series_skipped == 1

    def test_holtwinters_fits_cache_across_harvests(
        self, tmp_path, service, steady_ctx, monkeypatch
    ):
        """The per-series L-BFGS-B fit (the dominant serial cost) runs
        once per training slice, not once per harvest: an unchanged
        history re-scores with ZERO new optimizer calls; a new committed
        point re-fits exactly that series."""
        from deequ_tpu.anomalydetection import (
            HoltWinters, MetricInterval, SeriesSeasonality,
        )

        hw = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        repo = history_repo(tmp_path, "a", steady_ctx, days=30)
        service.watch_metrics("t1", repo, [Mean("x")], strategy=hw)
        calls = []
        real_fit = HoltWinters._fit
        monkeypatch.setattr(
            HoltWinters, "_fit",
            lambda self, training, nf: calls.append(1) or real_fit(
                self, training, nf
            ),
        )
        first = service.fleetwatch.harvest_now()
        assert first.series_scored == 1 and len(calls) == 1
        second = service.fleetwatch.harvest_now()
        assert second.series_scored == 1 and len(calls) == 1  # cached
        # flags identical with and without the cache in play
        assert second.flagged == first.flagged
        repo.save(ResultKey(int(time.time() * 1000) + 1), steady_ctx)
        service.fleetwatch.harvest_now()
        assert len(calls) == 2  # the grown history re-fits

    def test_short_history_skipped_not_fatal(self, tmp_path, service, steady_ctx):
        repo = PartitionedMetricsRepository(str(tmp_path / "short"))
        repo.save(ResultKey(int(time.time() * 1000)), steady_ctx)
        service.watch_metrics("t-short", repo, [Mean("x")])
        report = service.fleetwatch.harvest_now()
        assert report.series_scored == 0
        assert report.series_skipped == 1

    def test_unwatch(self, tmp_path, service, steady_ctx):
        service.watch_metrics(
            "t1", history_repo(tmp_path, "a", steady_ctx), [Mean("x")]
        )
        assert service.fleetwatch.unwatch("t1")
        assert not service.fleetwatch.unwatch("t1")
        assert service.fleetwatch.harvest_now().series_scored == 0


class TestStandingTrigger:
    def test_scheduler_harvest_triggers_scoring_job(self, tmp_path, steady_ctx):
        """The standing-watch contract: a completed job for a WATCHED
        tenant schedules ONE debounced fleet-scoring job; unwatched
        tenants never trigger."""
        data = Dataset.from_dict({"x": [1.0, 2.0, 3.0]})
        checks = [Check(CheckLevel.ERROR, "c").has_size(lambda n: n > 0)]
        with VerificationService(
            workers=2, background_warm=False, fleet=False
        ) as svc:
            svc.watch_metrics(
                "watched", history_repo(tmp_path, "a", steady_ctx),
                [Mean("x")],
            )
            svc.verify(data, checks, tenant="unwatched", timeout=60)
            time.sleep(0.3)
            assert svc.fleetwatch.last_report is None
            svc.verify(data, checks, tenant="watched", timeout=60)
            for _ in range(100):
                if svc.fleetwatch.last_report is not None:
                    break
                time.sleep(0.05)
            report = svc.fleetwatch.last_report
            assert report is not None and report.series_scored == 1
            snap = svc.json_snapshot()["counters"]
            assert snap["deequ_service_anomaly_harvests_total"] >= 1

    def test_watch_survives_scoring_job_killed_before_body(
        self, tmp_path, steady_ctx
    ):
        """Liveness: a scoring job that dies BEFORE its body runs (the
        injected worker fault fires between pickup and fn) must not leak
        the debounce flag — the next harvest re-schedules and the fleet
        keeps being scored."""
        from deequ_tpu.reliability import FaultSpec, inject

        data = Dataset.from_dict({"x": [1.0, 2.0, 3.0]})
        checks = [Check(CheckLevel.ERROR, "c").has_size(lambda n: n > 0)]
        with VerificationService(
            workers=1, background_warm=False, fleet=False
        ) as svc:
            svc.watch_metrics(
                "watched", history_repo(tmp_path, "a", steady_ctx),
                [Mean("x")],
            )
            # every worker pickup crashes while armed: the triggering job
            # retries through its own budget; the scoring job (retries=0)
            # dies pre-body and must clear _job_pending via recover_fn
            with inject(
                FaultSpec("worker", "worker_death", every=1, count=2)
            ):
                try:
                    svc.verify(data, checks, tenant="watched", timeout=60,
                               max_retries=0)
                except Exception:  # noqa: BLE001 - the crash is the point
                    pass
                deadline = time.time() + 5
                while time.time() < deadline and svc.fleetwatch._job_pending:
                    time.sleep(0.05)
            assert not svc.fleetwatch._job_pending
            # disarmed: the watch schedules and scores normally again
            svc.verify(data, checks, tenant="watched", timeout=60)
            for _ in range(100):
                if svc.fleetwatch.last_report is not None:
                    break
                time.sleep(0.05)
            assert svc.fleetwatch.last_report is not None

    def test_disabled_knob_detaches(self, tmp_path, steady_ctx, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_FLEETWATCH", "0")
        data = Dataset.from_dict({"x": [1.0, 2.0, 3.0]})
        checks = [Check(CheckLevel.ERROR, "c").has_size(lambda n: n > 0)]
        with VerificationService(
            workers=2, background_warm=False, fleet=False
        ) as svc:
            svc.watch_metrics(
                "watched", history_repo(tmp_path, "a", steady_ctx),
                [Mean("x")],
            )
            svc.verify(data, checks, tenant="watched", timeout=60)
            time.sleep(0.3)
            assert svc.fleetwatch.last_report is None
            # explicit scoring still works
            assert svc.fleetwatch.harvest_now().series_scored == 1


class TestQuarantineIsolation:
    def test_poisoned_history_quarantines_typed_others_unaffected(
        self, tmp_path, service, steady_ctx
    ):
        """ISSUE 15 reliability leg: one tenant's corrupt history bucket
        quarantines (typed, counted) while the other tenants' scores are
        byte-identical to a clean run."""
        service.watch_metrics(
            "t-clean", history_repo(tmp_path, "a", steady_ctx, wild_newest=True),
            [Size(), Mean("x")],
        )
        poisoned = history_repo(tmp_path, "b", steady_ctx)
        service.watch_metrics("t-poisoned", poisoned, [Size(), Mean("x")])
        clean_report = service.fleetwatch.harvest_now()
        assert not clean_report.quarantined_tenants
        # flip one byte inside one of the poisoned tenant's stored entries
        # (valid JSON, failing checksum — the bit-rot shape)
        [entry] = sorted(glob.glob(
            os.path.join(poisoned.path, "*", "e-*.json")
        ))[-1:]
        raw = open(entry).read()
        i = raw.index("Mean") + 1
        open(entry, "w").write(
            raw[:i] + ("X" if raw[i] != "X" else "Y") + raw[i + 1:]
        )
        report = service.fleetwatch.harvest_now()
        assert report.quarantined_tenants == ["t-poisoned"]
        # the clean tenant's flags are unchanged
        assert (
            [f for f in report.flagged if f[0] == "t-clean"]
            == [f for f in clean_report.flagged if f[0] == "t-clean"]
        )
        snap = service.json_snapshot()["counters"]
        assert snap["deequ_service_anomaly_quarantined_total"][
            "tenant=t-poisoned"
        ] == 1
        # the corrupt LOOSE entry self-healed on the quarantining read
        # (bytes preserved in the sidecar): the next harvest loads clean,
        # the standing episode closes, and the counter stays put
        again = service.fleetwatch.harvest_now()
        assert again.quarantined_tenants == []
        snap = service.json_snapshot()["counters"]
        assert snap["deequ_service_anomaly_quarantined_total"][
            "tenant=t-poisoned"
        ] == 1

    def test_standing_quarantine_episode_counts_once(
        self, tmp_path, service, steady_ctx
    ):
        """A corruption that re-quarantines on EVERY load (injected at the
        repository_load site, so no self-heal) reports per harvest but
        exports one counter bump per episode, not per harvest."""
        from deequ_tpu.reliability import FaultSpec, inject

        service.watch_metrics(
            "t1", history_repo(tmp_path, "a", steady_ctx), [Mean("x")]
        )
        with inject(
            FaultSpec("repository_load", "corrupt", every=1, count=None)
        ):
            first = service.fleetwatch.harvest_now()
            second = service.fleetwatch.harvest_now()
        assert first.quarantined_tenants == ["t1"]
        assert second.quarantined_tenants == ["t1"]
        snap = service.json_snapshot()["counters"]
        assert snap["deequ_service_anomaly_quarantined_total"][
            "tenant=t1"
        ] == 1
        # a clean harvest closes the episode; fresh corruption counts anew
        service.fleetwatch.harvest_now()
        with inject(
            FaultSpec("repository_load", "corrupt", every=1, count=None)
        ):
            service.fleetwatch.harvest_now()
        snap = service.json_snapshot()["counters"]
        assert snap["deequ_service_anomaly_quarantined_total"][
            "tenant=t1"
        ] == 2

    def test_concurrent_foreign_quarantine_is_not_misattributed(
        self, tmp_path, service, steady_ctx
    ):
        """Attribution is per REPOSITORY: a quarantine happening elsewhere
        in the process while a clean tenant's history loads (another
        worker hitting a corrupt store) must not flag THIS tenant."""
        from deequ_tpu.repository import FileSystemMetricsRepository

        corrupt_path = tmp_path / "foreign.json"
        corrupt_path.write_text('[{"torn"')
        foreign = FileSystemMetricsRepository(str(corrupt_path))
        inner = history_repo(tmp_path, "a", steady_ctx)

        class InterleavingRepo:
            """Simulates a concurrent worker quarantining a FOREIGN store
            mid-load (deterministically, inside this tenant's load)."""

            @property
            def quarantines(self):
                return inner.quarantines

            def load(self):
                foreign._read_all()  # bumps the process-global counter
                return inner.load()

        service.watch_metrics("t-clean", InterleavingRepo(), [Mean("x")])
        report = service.fleetwatch.harvest_now()
        assert report.quarantined_tenants == []
        assert report.series_scored == 1

    def test_injected_corrupt_fault_quarantines(self, tmp_path, service, steady_ctx):
        from deequ_tpu.reliability import FaultSpec, inject

        service.watch_metrics(
            "t1", history_repo(tmp_path, "a", steady_ctx), [Mean("x")]
        )
        with inject(
            FaultSpec("repository_load", "corrupt", at=1)
        ) as inj:
            report = service.fleetwatch.harvest_now()
        assert inj.fired
        assert report.quarantined_tenants == ["t1"]


class TestObservability:
    def test_flight_dump_correlates_to_harvest_trace(
        self, tmp_path, service, steady_ctx, monkeypatch
    ):
        flight_dir = str(tmp_path / "flight")
        monkeypatch.setenv("DEEQU_TPU_FLIGHT_DIR", flight_dir)
        service.watch_metrics(
            "t-wild",
            history_repo(tmp_path, "a", steady_ctx, wild_newest=True),
            [Mean("x")],
        )
        report = service.fleetwatch.harvest_now()
        assert report.flagged
        dumps = glob.glob(os.path.join(flight_dir, "*.jsonl"))
        assert dumps
        correlated = []
        for path in dumps:
            records = [json.loads(line) for line in open(path)]
            header = records[0]
            if any(
                f.get("kind") == "AnomalyFlagged"
                for f in header.get("failures", [])
            ):
                correlated.append((header, records[1:]))
        assert correlated, "no AnomalyFlagged dump"
        header, spans = correlated[0]
        # the dump is CORRELATED: its spans belong to the harvest trace
        assert header["trace_id"]
        assert any(
            s.get("name") == "fleetwatch:harvest" for s in spans
        )
        detail = next(
            f["detail"] for f in header["failures"]
            if f["kind"] == "AnomalyFlagged"
        )
        assert "t-wild" in detail and "Mean" in detail

    def test_export_help_lines_present(self, service):
        text = service.prometheus_text()
        for series in (
            "deequ_service_anomaly_series_scored_total",
            "deequ_service_anomaly_flagged_total",
            "deequ_service_anomaly_quarantined_total",
            "deequ_service_anomaly_harvests_total",
            "deequ_service_anomaly_scoring_seconds_total",
            "deequ_service_anomaly_watched_series",
        ):
            # the gauge always exports; counters export once touched — but
            # HELP must be REGISTERED for all (statlint export-help)
            assert series in service.metrics._help

    def test_watched_series_gauge(self, tmp_path, service, steady_ctx):
        repo = history_repo(tmp_path, "a", steady_ctx)
        service.watch_metrics("t1", repo, [Size(), Mean("x")])
        snap = service.json_snapshot()
        assert snap["gauges"]["deequ_service_anomaly_watched_series"] == 2


class TestWindowKnob:
    def test_window_after_ms_arithmetic(self):
        import datetime

        def utc_ms(y, m, d):
            return int(datetime.datetime(
                y, m, d, tzinfo=datetime.timezone.utc
            ).timestamp() * 1000)

        now = utc_ms(2026, 8, 4)
        # 12-month window -> first ms of the month 11 buckets back
        assert window_after_ms(12, now) == utc_ms(2025, 9, 1)
        # 1-month window -> the current (partial) month counts
        assert window_after_ms(1, now) == utc_ms(2026, 8, 1)
        # a window crossing a year boundary
        assert window_after_ms(3, utc_ms(2026, 1, 15)) == utc_ms(2025, 11, 1)
        assert window_after_ms(0) is None

    def test_window_bounds_history_load(self, tmp_path, service, steady_ctx, monkeypatch):
        """Entries older than the window never score (and never even
        deserialize — the partitioned walk skips their buckets)."""
        repo = PartitionedMetricsRepository(str(tmp_path / "hist"))
        now = int(time.time() * 1000)
        # 10 recent dailies + 10 two years old
        for day in range(10):
            repo.save(ResultKey(now - (10 - day) * DAY_MS), steady_ctx)
            repo.save(
                ResultKey(now - 730 * DAY_MS - day * DAY_MS), steady_ctx
            )
        service.watch_metrics("t1", repo, [Mean("x")])
        monkeypatch.setenv("DEEQU_TPU_FLEETWATCH_WINDOW_MONTHS", "2")
        assert fleetwatch_window_months() == 2
        repo.entries_deserialized = 0
        service.fleetwatch.harvest_now()
        assert repo.entries_deserialized == 10  # the stale decade untouched
        monkeypatch.setenv("DEEQU_TPU_FLEETWATCH_WINDOW_MONTHS", "0")
        repo.entries_deserialized = 0
        service.fleetwatch.harvest_now()
        assert repo.entries_deserialized == 20
