"""Spark-SQL predicate dialect support (VERDICT r4 #8): the predicate
strings the reference's checks/examples emit run verbatim through the
translator (`checks/Check.scala:786-799,734,751,913,942`)."""

import numpy as np
import pytest

from deequ_tpu.analyzers import Compliance
from deequ_tpu.data import Dataset
from deequ_tpu.expr import ExpressionError, evaluate_predicate
from deequ_tpu.runners import AnalysisRunner


def ev(pred, cols):
    n = len(next(iter(cols.values())))
    return evaluate_predicate(pred, cols, n)


class TestSqlTranslation:
    def setup_method(self):
        self.cols = {
            "att1": np.array([1.0, 4.0, np.nan, 7.0]),
            "att2": np.array([2.0, 3.0, 5.0, 7.0]),
            "marketplace": np.array(["EU", "NA", None, "EU"], dtype=object),
        }

    def test_plain_comparisons_unchanged(self):
        assert ev("att1 > 3", self.cols).tolist() == [False, True, False, True]
        assert ev("att1 < att2", self.cols).tolist() == [True, False, False, False]

    def test_sql_equality(self):
        assert ev("marketplace = 'EU'", self.cols).tolist() == [True, False, False, True]
        assert ev("marketplace <> 'EU'", self.cols).tolist() == [False, True, False, False]

    def test_case_insensitive_keywords(self):
        got = ev("marketplace = 'EU' OR att1 > 5", self.cols)
        assert got.tolist() == [True, False, False, True]
        got = ev("NOT (marketplace = 'EU') AND att2 < 6", self.cols)
        assert got.tolist() == [False, True, True, False]

    def test_is_null_and_in_list(self):
        pred = "`marketplace` IS NULL OR `marketplace` IN ('EU','NA')"
        assert ev(pred, self.cols).tolist() == [True, True, True, True]
        assert ev("`att1` IS NOT NULL", self.cols).tolist() == [True, True, False, True]

    def test_single_element_in_list(self):
        assert ev("marketplace IN ('EU')", self.cols).tolist() == [
            True, False, False, True,
        ]

    def test_escaped_quote_in_literal(self):
        cols = {"c": np.array(["it's", "not"], dtype=object)}
        assert ev("c = 'it''s'", cols).tolist() == [True, False]

    def test_coalesce_non_negative(self):
        # the exact string Check.isNonNegative emits (`Check.scala:734`)
        cols = {"v": np.array([1.0, -2.0, np.nan])}
        assert ev("COALESCE(v, 0.0) >= 0", cols).tolist() == [True, False, True]
        assert ev("COALESCE(v, 1.0) > 0", cols).tolist() == [True, False, True]

    def test_interval_contained_in(self):
        # the exact shape Check.isContainedIn(interval) emits (`:942`)
        cols = {"c": np.array([0.5, 1.0, 3.0, 5.0, 9.0, np.nan])}
        pred = "`c` IS NULL OR (`c` >= 1.0 AND `c` <= 5.0)"
        assert ev(pred, cols).tolist() == [False, True, True, True, False, True]

    def test_null_literal_and_booleans(self):
        cols = {"b": np.array([True, False, True])}
        assert ev("b = TRUE", cols).tolist() == [True, False, True]

    def test_bad_sql_reports_both_grammars(self):
        with pytest.raises(ExpressionError, match="neither a valid Python"):
            ev("att1 >> ?? 3", self.cols)
        with pytest.raises(ExpressionError, match="IS must be followed"):
            from deequ_tpu.expr import _translate_sql_predicate

            _translate_sql_predicate("x IS 3")

    def test_backquoted_non_identifier_rejected(self):
        with pytest.raises(ExpressionError, match="not expressible"):
            ev("`weird col` > 3", {"weird col": np.array([1.0])})


class TestSqlPredicatesEndToEnd:
    def test_compliance_with_reference_strings(self):
        rng = np.random.default_rng(3)
        data = Dataset.from_dict(
            {
                "att1": rng.integers(0, 10, 5000).astype(np.float64),
                "marketplace": np.array(["EU", "NA", "JP"])[
                    rng.integers(0, 3, 5000)
                ],
            }
        )
        battery = [
            Compliance("rule1", "att1 > 0"),
            Compliance("rule2", "marketplace = 'EU'"),
            Compliance("rule3", "`marketplace` IS NULL OR `marketplace` IN ('EU','NA','JP')"),
            Compliance("rule4", "COALESCE(att1, 0.0) >= 0"),
        ]
        ctx = AnalysisRunner.do_analysis_run(data, battery, batch_size=1024)
        df = data.arrow.to_pandas()
        assert ctx.metric(battery[0]).value.get() == (df["att1"] > 0).mean()
        assert ctx.metric(battery[1]).value.get() == (df["marketplace"] == "EU").mean()
        assert ctx.metric(battery[2]).value.get() == 1.0
        assert ctx.metric(battery[3]).value.get() == 1.0

    def test_where_filter_sql(self):
        # reference FilterableCheckTest: .where("marketplace = 'EU'")
        data = Dataset.from_dict(
            {
                "col2": [1.0, None, 3.0, 4.0],
                "marketplace": ["EU", "EU", "NA", "EU"],
            }
        )
        from deequ_tpu.analyzers import Completeness

        a = Completeness("col2", where="marketplace = 'EU'")
        ctx = AnalysisRunner.do_analysis_run(data, [a])
        assert ctx.metric(a).value.get() == pytest.approx(2 / 3)


class TestSqlLiteralEdgeCases:
    def test_double_quoted_literal(self):
        cols = {"x": np.array(["abc", "zzz"], dtype=object)}
        assert ev('x = "abc"', cols).tolist() == [True, False]
        assert ev('x = "say ""hi"""', {"x": np.array(['say "hi"'], dtype=object)}).tolist() == [True]

    def test_lowercase_single_element_in(self):
        cols = {"x": np.array(["abc", "ab"], dtype=object)}
        # Python collapses ('abc') to a scalar; the dialect treats it as a
        # one-element IN list, never substring membership
        assert ev("x in ('abc')", cols).tolist() == [True, False]


class TestFunctionNamesAsColumns:
    """ADVICE r5 `expr.py:475`: a bare word matching a whitelisted function
    name (Length, Matches, Abs, ...) is a COLUMN identifier unless it is
    actually called — Spark resolves unquoted identifiers as columns."""

    def test_column_named_length(self):
        cols = {"Length": np.array([1.0, 10.0, 3.0])}
        assert ev("Length > 2", cols).tolist() == [False, True, True]

    def test_column_named_matches_in_sql_expression(self):
        cols = {"Matches": np.array([0.0, 5.0]), "x": np.array([1.0, 1.0])}
        assert ev("Matches = 5 AND x = 1", cols).tolist() == [False, True]

    def test_function_call_still_translates(self):
        cols = {"s": np.array(["ab", "abcd"], dtype=object)}
        assert ev("LENGTH(s) > 3", cols).tolist() == [False, True]

    def test_column_and_call_coexist(self):
        cols = {
            "Length": np.array([9.0, 1.0]),
            "s": np.array(["ab", "abcd"], dtype=object),
        }
        assert ev("Length > 5 OR LENGTH(s) > 3", cols).tolist() == [True, True]

    def test_end_to_end_compliance_on_length_column(self):
        data = Dataset.from_dict({"Length": [1.0, 2.0, 30.0, 40.0]})
        a = Compliance("len-rule", "Length >= 10")
        ctx = AnalysisRunner.do_analysis_run(data, [a])
        assert ctx.metric(a).value.get() == pytest.approx(0.5)


class TestStateStaticFieldsExact:
    def test_missing_static_field_fails_loudly(self, tmp_path):
        from deequ_tpu.analyzers import Mean
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        sp = FileSystemStateProvider(str(tmp_path))
        a = Mean("x")
        base = str(tmp_path / sp._key(a))
        np.savez(
            base + "-state.npz",
            __format_version__=np.int64(2),
            __state_type__=np.str_("KLLSketchState"),
            __static__=np.str_("{}"),  # sketch_size missing: must not default
            **{f"leaf{i}": np.zeros(2) for i in range(7)},
        )
        with pytest.raises(ValueError, match="static fields"):
            sp.load(a)
