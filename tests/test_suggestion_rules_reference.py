"""Per-rule suggestion scenarios ported from the reference
`suggestions/rules/*Test.scala` (`ConstraintRulesTest.scala`): each rule's
applicability matrix over hand-built profiles, the candidate's computed
bounds/ordering, and that suggested constraints EVALUATE cleanly on data
shaped like the profile that suggested them (VERDICT r5 ask #6 leftover).
"""

import math

import numpy as np
import pytest

from deequ_tpu.analyzers.grouping import NULL_FIELD_REPLACEMENT
from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.constraints import ConstraintStatus
from deequ_tpu.data import Dataset
from deequ_tpu.metrics import Distribution, DistributionValue
from deequ_tpu.profiles import NumericColumnProfile, StandardColumnProfile
from deequ_tpu.suggestions.rules import (
    CategoricalRangeRule,
    CompleteIfCompleteRule,
    FractionalCategoricalRangeRule,
    NonNegativeNumbersRule,
    RetainCompletenessRule,
    RetainTypeRule,
    UniqueIfApproximatelyUniqueRule,
)
from deequ_tpu.verification import VerificationSuite


def _string_profile(
    column="att1",
    completeness=1.0,
    approx_distinct=100,
    data_type="String",
    inferred=True,
    histogram=None,
):
    return StandardColumnProfile(
        column, completeness, approx_distinct, data_type, inferred,
        {}, histogram,
    )


def _numeric_profile(column="att1", completeness=1.0, minimum=0.0, **kw):
    return NumericColumnProfile(
        column, completeness, kw.pop("approx_distinct", 100), "Integral",
        True, {}, None, minimum=minimum, **kw,
    )


def _evaluate(data, suggestion):
    """One suggested constraint run against real data -> ConstraintStatus."""
    check = Check(CheckLevel.ERROR, "eval").add_constraint(suggestion.constraint)
    result = VerificationSuite.on_data(data).add_check(check).run()
    statuses = [
        cr.status
        for r in result.check_results.values()
        for cr in r.constraint_results
    ]
    assert len(statuses) == 1
    return statuses[0]


class TestCompleteIfCompleteRule:
    """Reference: `CompleteIfCompleteRule` block of ConstraintRulesTest."""

    def test_applicability_matrix(self):
        rule = CompleteIfCompleteRule()
        assert rule.should_be_applied(_string_profile(completeness=1.0), 1000)
        assert not rule.should_be_applied(_string_profile(completeness=0.99), 1000)
        assert not rule.should_be_applied(_string_profile(completeness=0.25), 1000)

    def test_evaluates_on_data(self):
        complete = Dataset.from_dict({"att1": [f"v{i}" for i in range(20)]})
        incomplete = Dataset.from_dict(
            {"att1": [f"v{i}" if i % 4 else None for i in range(20)]}
        )
        suggestion = CompleteIfCompleteRule().candidate(
            _string_profile(completeness=1.0), 20
        )
        assert _evaluate(complete, suggestion) == ConstraintStatus.SUCCESS
        assert _evaluate(incomplete, suggestion) == ConstraintStatus.FAILURE
        assert suggestion.code_for_constraint == '.is_complete("att1")'


class TestRetainCompletenessRule:
    """Reference: `RetainCompletenessRule` block (binomial CI lower bound)."""

    def test_applicability_matrix(self):
        rule = RetainCompletenessRule()
        assert rule.should_be_applied(_string_profile(completeness=0.5), 1000)
        assert rule.should_be_applied(_string_profile(completeness=0.21), 1000)
        assert rule.should_be_applied(_string_profile(completeness=0.99), 1000)
        assert not rule.should_be_applied(_string_profile(completeness=0.2), 1000)
        assert not rule.should_be_applied(_string_profile(completeness=0.05), 1000)
        assert not rule.should_be_applied(_string_profile(completeness=1.0), 1000)

    def test_ci_lower_bound_pinned(self):
        """p=0.5, n=100 -> target = floor((0.5 - 1.96*sqrt(0.25/100))*100)/100
        = 0.40 (the reference's BigDecimal setScale(2, DOWN))."""
        suggestion = RetainCompletenessRule().candidate(
            _string_profile(completeness=0.5), 100
        )
        assert "v >= 0.4" in suggestion.code_for_constraint
        expected = math.floor((0.5 - 1.96 * math.sqrt(0.25 / 100)) * 100) / 100
        assert expected == 0.4

    def test_evaluates_on_data(self):
        # 75% complete, 200 rows: bound is ~0.69 -> holds on the same data
        data = Dataset.from_dict(
            {"att1": [f"v{i}" if i % 4 else None for i in range(200)]}
        )
        suggestion = RetainCompletenessRule().candidate(
            _string_profile(completeness=0.75), 200
        )
        assert _evaluate(data, suggestion) == ConstraintStatus.SUCCESS


class TestRetainTypeRule:
    """Reference: `RetainTypeRule` block — only INFERRED non-string types."""

    @pytest.mark.parametrize("dtype", ["Integral", "Fractional", "Boolean"])
    def test_applies_to_inferred_typed_columns(self, dtype):
        rule = RetainTypeRule()
        assert rule.should_be_applied(
            _string_profile(data_type=dtype, inferred=True), 1000
        )
        # the same type NOT inferred (declared by the schema) never
        # suggests: the constraint would re-check what the schema enforces
        assert not rule.should_be_applied(
            _string_profile(data_type=dtype, inferred=False), 1000
        )

    @pytest.mark.parametrize("dtype", ["String", "Unknown"])
    def test_never_applies_to_string_or_unknown(self, dtype):
        rule = RetainTypeRule()
        for inferred in (True, False):
            assert not rule.should_be_applied(
                _string_profile(data_type=dtype, inferred=inferred), 1000
            )

    def test_evaluates_on_data(self):
        data = Dataset.from_dict({"att1": [str(i) for i in range(30)]})
        suggestion = RetainTypeRule().candidate(
            _string_profile(data_type="Integral"), 30
        )
        assert "ConstrainableDataTypes.INTEGRAL" in suggestion.code_for_constraint
        assert _evaluate(data, suggestion) == ConstraintStatus.SUCCESS


def _histogram(counts, total=None):
    total = total or sum(counts.values())
    return Distribution(
        {k: DistributionValue(v, v / total) for k, v in counts.items()},
        number_of_bins=len(counts),
    )


class TestCategoricalRangeRule:
    """Reference: `CategoricalRangeRule` block."""

    def test_applies_only_below_unique_ratio_threshold(self):
        rule = CategoricalRangeRule()
        # 2 categories over many rows: ratio of singleton values is 0
        hist = _histogram({"a": 50, "b": 50})
        assert rule.should_be_applied(_string_profile(histogram=hist), 100)
        # every value unique: ratio 1 > 0.1
        unique_hist = _histogram({f"v{i}": 1 for i in range(20)})
        assert not rule.should_be_applied(
            _string_profile(histogram=unique_hist), 20
        )
        # non-string profiles never apply
        assert not rule.should_be_applied(
            _string_profile(data_type="Integral", histogram=hist), 100
        )
        # no histogram -> no basis
        assert not rule.should_be_applied(_string_profile(histogram=None), 100)

    def test_categories_ordered_by_popularity_null_excluded(self):
        hist = _histogram(
            {"rare": 5, "common": 80, NULL_FIELD_REPLACEMENT: 10, "mid": 15}
        )
        suggestion = CategoricalRangeRule().candidate(
            _string_profile(histogram=hist), 110
        )
        code = suggestion.code_for_constraint
        assert NULL_FIELD_REPLACEMENT not in code
        assert code.index('"common"') < code.index('"mid"') < code.index('"rare"')

    def test_sql_quote_escaping(self):
        hist = _histogram({"it's": 50, "plain": 50})
        suggestion = CategoricalRangeRule().candidate(
            _string_profile(histogram=hist), 100
        )
        # SQL predicate doubles the quote (reference escapes the same way)
        assert "it''s" in suggestion.description

    def test_evaluates_on_data(self):
        values = ["ACTIVE"] * 45 + ["INACTIVE"] * 45 + ["DELETED"] * 10
        data = Dataset.from_dict({"att1": values})
        hist = _histogram({"ACTIVE": 45, "INACTIVE": 45, "DELETED": 10})
        suggestion = CategoricalRangeRule().candidate(
            _string_profile(histogram=hist), 100
        )
        assert _evaluate(data, suggestion) == ConstraintStatus.SUCCESS
        # a value OUTSIDE the suggested range fails the constraint
        drifted = Dataset.from_dict({"att1": values[:-1] + ["NEW"]})
        assert _evaluate(drifted, suggestion) == ConstraintStatus.FAILURE


class TestFractionalCategoricalRangeRule:
    """Reference: `FractionalCategoricalRangeRule` block."""

    def test_applies_for_mostly_categorical_data(self):
        rule = FractionalCategoricalRangeRule()
        # two big categories + a tail of singletons: ratio of unique values
        # is 10/12 > 0.4? no — 10 singletons / 12 entries = 0.83 > 0.4 ->
        # NOT applied; use a smaller tail
        hist = _histogram({"a": 60, "b": 30, "x": 1, "y": 1})
        # unique ratio = 2/4 = 0.5 > 0.4 -> still not applied
        assert not rule.should_be_applied(_string_profile(histogram=hist), 92)
        hist2 = _histogram(
            {"a": 60, "b": 30, "c": 5, "d": 4, "e": 3, "f": 2, "x": 1}
        )
        # unique ratio = 1/7 <= 0.4 and the top categories cover < 1
        assert rule.should_be_applied(_string_profile(histogram=hist2), 105)
        # fully covered (no tail) -> nothing fractional about it
        full = _histogram({"a": 60, "b": 40})
        assert not rule.should_be_applied(_string_profile(histogram=full), 100)

    def test_top_categories_cover_target_fraction(self):
        rule = FractionalCategoricalRangeRule()
        hist = _histogram(
            {"a": 60, "b": 30, "c": 5, "d": 4, "e": 3, "f": 2, "x": 1}
        )
        top = rule._top_categories(_string_profile(histogram=hist))
        coverage = sum(v.ratio for v in top.values())
        assert coverage >= 0.9
        assert "a" in top and "b" in top and "x" not in top

    def test_evaluates_on_data(self):
        values = ["a"] * 60 + ["b"] * 30 + ["c"] * 5 + ["d"] * 4 + ["x"]
        data = Dataset.from_dict({"att1": values})
        hist = _histogram({"a": 60, "b": 30, "c": 5, "d": 4, "x": 1})
        suggestion = FractionalCategoricalRangeRule().candidate(
            _string_profile(histogram=hist), 100
        )
        assert _evaluate(data, suggestion) == ConstraintStatus.SUCCESS


class TestNonNegativeNumbersRule:
    """Reference: `NonNegativeNumbersRule` block."""

    def test_applicability_matrix(self):
        rule = NonNegativeNumbersRule()
        assert rule.should_be_applied(_numeric_profile(minimum=0.0), 1000)
        assert rule.should_be_applied(_numeric_profile(minimum=17.0), 1000)
        assert not rule.should_be_applied(_numeric_profile(minimum=-1e-9), 1000)
        assert not rule.should_be_applied(_numeric_profile(minimum=None), 1000)
        # string profiles have no minimum at all
        assert not rule.should_be_applied(_string_profile(), 1000)

    def test_evaluates_on_data(self):
        data = Dataset.from_dict({"att1": np.arange(50, dtype=np.float64)})
        suggestion = NonNegativeNumbersRule().candidate(
            _numeric_profile(minimum=0.0), 50
        )
        assert suggestion.code_for_constraint == '.is_non_negative("att1")'
        assert _evaluate(data, suggestion) == ConstraintStatus.SUCCESS
        negatives = Dataset.from_dict(
            {"att1": np.arange(50, dtype=np.float64) - 5.0}
        )
        assert _evaluate(negatives, suggestion) == ConstraintStatus.FAILURE


class TestUniqueIfApproximatelyUniqueRule:
    """Reference: `UniqueIfApproximatelyUniqueRule` block — the HLL error
    envelope (8%) decides applicability, completeness must be exact."""

    def test_applicability_matrix(self):
        rule = UniqueIfApproximatelyUniqueRule()
        assert rule.should_be_applied(
            _string_profile(approx_distinct=100), 100
        )
        assert rule.should_be_applied(
            _string_profile(approx_distinct=95), 100  # within 8% envelope
        )
        assert not rule.should_be_applied(
            _string_profile(approx_distinct=91), 100  # 9% off: outside
        )
        assert not rule.should_be_applied(
            _string_profile(approx_distinct=100, completeness=0.99), 100
        )
        assert not rule.should_be_applied(
            _string_profile(approx_distinct=0), 0  # empty data never unique
        )

    def test_evaluates_on_data(self):
        data = Dataset.from_dict({"att1": [f"v{i}" for i in range(100)]})
        suggestion = UniqueIfApproximatelyUniqueRule().candidate(
            _string_profile(approx_distinct=100), 100
        )
        assert suggestion.code_for_constraint == '.is_unique("att1")'
        assert _evaluate(data, suggestion) == ConstraintStatus.SUCCESS
        duplicated = Dataset.from_dict(
            {"att1": [f"v{i % 50}" for i in range(100)]}
        )
        assert _evaluate(duplicated, suggestion) == ConstraintStatus.FAILURE
