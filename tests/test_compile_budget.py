"""Compile-budget regression tests for the signature-bundled device scan.

The device tier used to compile ONE monolithic PackedScanProgram keyed on
the full analyzer tuple: a 50-column battery was one giant XLA compile
(1140.6s staging vs 1.98s warm on the bench box — 575x, BENCH_r05) that no
other battery could reuse. The bundled design partitions a battery into
(analyzer-class, state-shape) signature bundles and compiles one SMALL
program per bundle signature, shared across columns, batteries and runs.

These tests pin the budget that redesign buys, via RunMonitor's
``program_compiles`` delta counter:

- a 50-column battery compiles at most (distinct signatures + a small
  constant for bundle-shape variants) programs — NOT one per analyzer and
  NOT one monolith whose cost scales superlinearly with battery width;
- re-running the same battery compiles 0 new programs;
- a DIFFERENT battery over different columns with the same analyzer
  classes at the same group sizes compiles 0 new programs (cross-battery
  sharing — the property that makes profile pass 2 and the suggestion
  stage nearly compile-free).
"""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Maximum,
    Mean,
    Minimum,
    StandardDeviation,
    Sum,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor


def wide_data(n_cols: int, rows: int = 4096, seed: int = 0) -> Dataset:
    rng = np.random.default_rng(seed)
    return Dataset.from_dict(
        {f"c{i}": rng.normal(size=rows) for i in range(n_cols)}
    )


def battery_for(columns):
    analyzers = []
    for c in columns:
        analyzers += [
            Completeness(c), Mean(c), Sum(c), Minimum(c), Maximum(c),
            StandardDeviation(c), ApproxCountDistinct(c),
        ]
    return analyzers


class TestCompileBudget:
    def test_50_column_battery_compiles_at_most_signatures_plus_constant(self):
        data = wide_data(8, seed=1)
        cols = [f"c{i}" for i in range(8)]
        battery = battery_for(cols)  # 56 analyzers, 7 distinct signatures
        distinct_signatures = 7
        mon = RunMonitor()
        ctx = AnalysisRunner.do_analysis_run(
            data, battery, batch_size=2048, monitor=mon, placement="device"
        )
        assert all(m.value.is_success for m in ctx.metric_map.values())
        # each signature compiles one full-size bundle program; the
        # "small constant" covers at most one extra shape variant per
        # signature (a power-of-two tail), never per-column growth
        assert 0 < mon.program_compiles <= distinct_signatures * 2, (
            mon.program_compiles
        )

    def test_rerunning_same_battery_compiles_zero(self):
        data = wide_data(4, seed=2)
        battery = battery_for([f"c{i}" for i in range(4)])
        AnalysisRunner.do_analysis_run(
            data, battery, batch_size=2048, placement="device"
        )
        mon = RunMonitor()
        AnalysisRunner.do_analysis_run(
            data, battery, batch_size=2048, monitor=mon, placement="device"
        )
        assert mon.program_compiles == 0, mon.program_compiles

    def test_same_shape_battery_over_new_columns_compiles_zero(self):
        # same classes, same per-class group SIZE, different column names
        # and different dataset: the signature-keyed programs must be
        # reused wholesale (feature arrays are remapped positionally)
        data_a = wide_data(4, seed=3)
        AnalysisRunner.do_analysis_run(
            data_a, battery_for([f"c{i}" for i in range(4)]),
            batch_size=2048, placement="device",
        )
        rng = np.random.default_rng(7)
        data_b = Dataset.from_dict(
            {f"other{i}": rng.normal(size=4096) for i in range(4)}
        )
        mon = RunMonitor()
        ctx = AnalysisRunner.do_analysis_run(
            data_b, battery_for([f"other{i}" for i in range(4)]),
            batch_size=2048, monitor=mon, placement="device",
        )
        assert all(m.value.is_success for m in ctx.metric_map.values())
        assert mon.program_compiles == 0, mon.program_compiles

    @pytest.mark.slow
    def test_50_columns_full_shape(self):
        """The literal 50-column shape from the acceptance bar (slow: ~350
        analyzer states on the 8-virtual-device CPU backend)."""
        data = wide_data(50, rows=2048, seed=4)
        battery = battery_for([f"c{i}" for i in range(50)])
        mon = RunMonitor()
        ctx = AnalysisRunner.do_analysis_run(
            data, battery, batch_size=1024, monitor=mon, placement="device"
        )
        assert all(m.value.is_success for m in ctx.metric_map.values())
        assert mon.program_compiles <= 7 * 2, mon.program_compiles
        mon2 = RunMonitor()
        AnalysisRunner.do_analysis_run(
            data, battery, batch_size=1024, monitor=mon2, placement="device"
        )
        assert mon2.program_compiles == 0
