"""Result-serialization report scenarios, ported from the reference's
`VerificationResultTest.scala` / `AnalyzerContextTest.scala`: the
successMetricsAsJson / checkResultsAsJson record shapes, analyzer
filtering, status precedence in reports — plus the new
``cost_by_analyzer`` table's JSON round trip (ISSUE 5 satellite).
"""

import json

import numpy as np
import pytest

from deequ_tpu.analyzers import Completeness, Maximum, Mean, Minimum, Size
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.runners.context import AnalyzerContext
from deequ_tpu.verification import VerificationResult, VerificationSuite


@pytest.fixture
def data():
    rng = np.random.default_rng(7)
    return Dataset.from_dict(
        {
            "item": [str(i) for i in range(1, 5)],
            "att1": ["a", "b", "a", "a"],
            "numeric": rng.normal(10.0, 1.0, size=4),
        }
    )


def _suite(data, *checks):
    builder = VerificationSuite.on_data(data)
    for check in checks:
        builder = builder.add_check(check)
    return builder.run()


class TestSuccessMetricsAsJson:
    """`VerificationResultTest` "getSuccessMetricsAsJson" scenarios."""

    def test_record_shape_and_values(self, data):
        result = _suite(
            data,
            Check(CheckLevel.ERROR, "group-1")
            .has_size(lambda n: n == 4)
            .is_complete("att1"),
        )
        records = json.loads(result.success_metrics_as_json())
        assert all(
            set(r) == {"entity", "instance", "name", "value"} for r in records
        )
        by_name = {(r["name"], r["instance"]): r for r in records}
        size = by_name[("Size", "*")]
        assert size["entity"] == "Dataset" and size["value"] == 4.0
        comp = by_name[("Completeness", "att1")]
        assert comp["entity"] == "Column" and comp["value"] == 1.0

    def test_filtering_by_analyzer(self, data):
        result = _suite(
            data,
            Check(CheckLevel.ERROR, "g")
            .has_size(lambda n: n == 4)
            .is_complete("att1"),
        )
        only = json.loads(
            result.success_metrics_as_json(for_analyzers=[Size()])
        )
        assert [r["name"] for r in only] == ["Size"]

    def test_failure_metrics_excluded(self, data):
        ctx = AnalysisRunner.do_analysis_run(
            data, [Size(), Completeness("no_such_column")]
        )
        records = json.loads(AnalyzerContext(ctx.metric_map).success_metrics_as_json())
        assert [r["name"] for r in records] == ["Size"]

    def test_context_addition_merges_metric_maps(self, data):
        """`AnalyzerContextTest`: two contexts combine; the right side
        wins on shared analyzers."""
        ctx1 = AnalysisRunner.do_analysis_run(data, [Size()])
        ctx2 = AnalysisRunner.do_analysis_run(data, [Completeness("att1")])
        merged = ctx1 + ctx2
        assert merged.metric(Size()) is not None
        assert merged.metric(Completeness("att1")) is not None
        assert merged.metric(Mean("numeric")) is None


class TestCheckResultsAsJson:
    """`VerificationResultTest` "getCheckResultsAsJson" scenarios."""

    COLUMNS = {
        "check", "check_level", "check_status", "constraint",
        "constraint_status", "constraint_message",
    }

    def test_success_report_shape(self, data):
        result = _suite(
            data,
            Check(CheckLevel.ERROR, "group-1").has_size(lambda n: n == 4),
        )
        rows = json.loads(result.check_results_as_json())
        assert len(rows) == 1
        assert set(rows[0]) == self.COLUMNS
        assert rows[0]["check"] == "group-1"
        assert rows[0]["check_level"] == "Error"
        assert rows[0]["check_status"] == "Success"
        assert rows[0]["constraint_status"] == "Success"
        assert rows[0]["constraint_message"] == ""

    def test_failing_constraint_carries_message(self, data):
        result = _suite(
            data,
            Check(CheckLevel.ERROR, "group-2-E")
            .has_completeness("att1", lambda v: v > 2.0),  # unsatisfiable
        )
        rows = json.loads(result.check_results_as_json())
        assert rows[0]["check_status"] == "Error"
        assert rows[0]["constraint_status"] == "Failure"
        assert rows[0]["constraint_message"] != ""

    def test_status_precedence_in_reports(self, data):
        """Reference precedence: a failing WARNING check yields Warning,
        any failing ERROR check dominates to Error, all-passing is
        Success — both on the overall status and per-row in the report."""
        passing = Check(CheckLevel.ERROR, "ok").has_size(lambda n: n == 4)
        warning = Check(CheckLevel.WARNING, "warn").has_size(lambda n: n == 0)
        failing = Check(CheckLevel.ERROR, "bad").has_size(lambda n: n == 0)

        only_pass = _suite(data, passing)
        assert only_pass.status == CheckStatus.SUCCESS

        warn = _suite(data, passing, warning)
        assert warn.status == CheckStatus.WARNING
        rows = {r["check"]: r for r in json.loads(warn.check_results_as_json())}
        assert rows["ok"]["check_status"] == "Success"
        assert rows["warn"]["check_status"] == "Warning"
        assert rows["warn"]["check_level"] == "Warning"

        err = _suite(data, passing, warning, failing)
        assert err.status == CheckStatus.ERROR
        rows = {r["check"]: r for r in json.loads(err.check_results_as_json())}
        assert rows["bad"]["check_status"] == "Error"
        assert rows["warn"]["check_status"] == "Warning"
        assert rows["ok"]["check_status"] == "Success"

    def test_dataframe_and_json_agree(self, data):
        result = _suite(
            data,
            Check(CheckLevel.WARNING, "w").is_complete("att1"),
        )
        df = result.check_results_as_data_frame()
        rows = json.loads(result.check_results_as_json())
        assert df.to_dict(orient="records") == rows


class TestCostByAnalyzerRoundTrip:
    """ISSUE 5: the new cost table rides VerificationResult and
    round-trips through JSON."""

    def test_populated_and_round_trips(self, data):
        result = _suite(
            data,
            Check(CheckLevel.ERROR, "costed")
            .is_complete("att1")
            .has_mean("numeric", lambda m: 5 < m < 15)
            .has_min("numeric", lambda v: v < 100)
            .has_max("numeric", lambda v: v > -100),
        )
        assert result.cost_by_analyzer
        for key in (
            repr(Completeness("att1")), repr(Mean("numeric")),
            repr(Minimum("numeric")), repr(Maximum("numeric")),
        ):
            assert key in result.cost_by_analyzer
            assert result.cost_by_analyzer[key] >= 0.0
        rows = json.loads(result.cost_by_analyzer_as_json())
        assert all(set(r) == {"analyzer", "seconds"} for r in rows)
        # sorted most-expensive first
        seconds = [r["seconds"] for r in rows]
        assert seconds == sorted(seconds, reverse=True)
        # lossless round trip
        assert {r["analyzer"]: r["seconds"] for r in rows} == (
            result.cost_by_analyzer
        )

    def test_state_only_run_has_empty_table(self, data):
        from deequ_tpu.analyzers.state_provider import InMemoryStateProvider

        sp = InMemoryStateProvider()
        check = Check(CheckLevel.ERROR, "c").has_mean(
            "numeric", lambda m: 5 < m < 15
        )
        VerificationSuite.on_data(data).add_check(check).save_states_with(
            sp
        ).run()
        result = VerificationSuite.run_on_aggregated_states(
            data.schema, [check], [sp]
        )
        assert isinstance(result, VerificationResult)
        assert result.cost_by_analyzer == {}
        assert json.loads(result.cost_by_analyzer_as_json()) == []
