"""All-null column handling across every analyzer — the
`analyzers/NullHandlingTests.scala` analog: aggregates over columns whose
values are ALL null must produce empty-state failure metrics (never crashes,
never fake zeros), with the documented exceptions (Size, Completeness,
DataType, CountDistinct, ApproxCountDistinct)."""

import numpy as np
import pyarrow as pa
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    MutualInformation,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import EmptyStateException
from deequ_tpu.runners import AnalysisRunner


@pytest.fixture(scope="module", params=["device", "host"])
def ctx(request):
    """8 rows; stringCol / numericCol / numericCol2 all null, numericCol3
    populated (reference `NullHandlingTests.dataWithNullColumns`), computed
    through both ingest tiers."""
    n = 8
    data = Dataset.from_arrow(
        pa.table(
            {
                "stringCol": pa.array([None] * n, type=pa.string()),
                "numericCol": pa.array([None] * n, type=pa.float64()),
                "numericCol2": pa.array([None] * n, type=pa.float64()),
                "numericCol3": pa.array([float(i + 1) for i in range(n)]),
            }
        )
    )
    battery = [
        Size(),
        Completeness("stringCol"),
        Mean("numericCol"),
        StandardDeviation("numericCol"),
        Minimum("numericCol"),
        Maximum("numericCol"),
        MinLength("stringCol"),
        MaxLength("stringCol"),
        DataType("stringCol"),
        Sum("numericCol"),
        ApproxQuantile("numericCol", 0.5),
        CountDistinct(["stringCol"]),
        ApproxCountDistinct("stringCol"),
        Entropy("stringCol"),
        Uniqueness(["stringCol"]),
        Distinctness(["stringCol"]),
        MutualInformation(["numericCol", "numericCol2"]),
        Correlation("numericCol", "numericCol2"),
        Correlation("numericCol", "numericCol3"),
    ]
    return AnalysisRunner.do_analysis_run(data, battery, placement=request.param)


def _assert_empty_state(metric):
    assert metric.value.is_failure, metric
    assert isinstance(metric.value.exception, EmptyStateException), metric


class TestNullColumnsProduceCorrectMetrics:
    def test_size_counts_all_rows(self, ctx):
        assert ctx.metric(Size()).value.get() == 8.0

    def test_completeness_is_zero(self, ctx):
        assert ctx.metric(Completeness("stringCol")).value.get() == 0.0

    @pytest.mark.parametrize(
        "analyzer",
        [
            Mean("numericCol"),
            StandardDeviation("numericCol"),
            Minimum("numericCol"),
            Maximum("numericCol"),
            MinLength("stringCol"),
            MaxLength("stringCol"),
            Sum("numericCol"),
            ApproxQuantile("numericCol", 0.5),
        ],
        ids=lambda a: a.name,
    )
    def test_aggregates_fail_with_empty_state(self, ctx, analyzer):
        _assert_empty_state(ctx.metric(analyzer))

    def test_datatype_is_all_unknown(self, ctx):
        dist = ctx.metric(DataType("stringCol")).value.get()
        assert dist.values["Unknown"].ratio == 1.0

    def test_count_distinct_is_zero(self, ctx):
        assert ctx.metric(CountDistinct(["stringCol"])).value.get() == 0.0

    def test_approx_count_distinct_is_zero(self, ctx):
        assert ctx.metric(ApproxCountDistinct("stringCol")).value.get() == 0.0

    @pytest.mark.parametrize(
        "analyzer",
        [
            Entropy("stringCol"),
            Uniqueness(["stringCol"]),
            Distinctness(["stringCol"]),
            MutualInformation(["numericCol", "numericCol2"]),
            Correlation("numericCol", "numericCol2"),
            Correlation("numericCol", "numericCol3"),
        ],
        ids=lambda a: f"{a.name}-{a.instance}",
    )
    def test_frequency_and_pair_aggregates_fail_with_empty_state(self, ctx, analyzer):
        _assert_empty_state(ctx.metric(analyzer))

    def test_empty_state_message_names_the_analyzer(self, ctx):
        message = str(ctx.metric(Mean("numericCol")).value.exception)
        # reference wording: "Empty state for analyzer Mean(numericCol,None),
        # all input values were NULL."
        assert "Mean" in message
        assert "numericCol" in message
        assert "all input values were NULL." in message
