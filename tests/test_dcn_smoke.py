"""Two-process jax.distributed DCN smoke (VERDICT r5 ask #8), as a test.

Runs ``python -m tools.dcn_smoke``: two OS processes, one CPU device each,
joined into a single global mesh over the gloo cross-process backend;
``sharded_ingest_fold`` + ``collective_merge_states`` must equal the
single-process host-tier fold. Marked slow (spawns 3 jax processes); skips
cleanly where the environment cannot run multi-process CPU collectives.
"""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_two_process_fold_matches_single_process():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dcn_smoke"],
        cwd=repo, env=env, capture_output=True, timeout=600,
    )
    assert proc.stdout, proc.stderr.decode()[-500:]
    report = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    if report.get("skipped"):
        pytest.skip(f"multi-process CPU collectives unavailable: "
                    f"{report.get('reason', '')[:200]}")
    assert proc.returncode == 0, report
    assert report["ok"], report
    assert report["processes"] == 2


@pytest.mark.slow
@pytest.mark.mesh
def test_kill_one_process_survivor_salvages_bit_exact():
    """The process-loss leg of the elastic mesh contract (ISSUE 7): the
    parent SIGKILLs one of the two jax.distributed processes mid-fold; the
    survivor detects the dead peer, salvages its own shard's folded state,
    replays the dead shard's batch slices from its local data copy, and
    completes the fold equal to the single-process oracle."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dcn_smoke", "--drill", "kill-one"],
        cwd=repo, env=env, capture_output=True, timeout=600,
    )
    assert proc.stdout, proc.stderr.decode()[-500:]
    report = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    if report.get("skipped"):
        pytest.skip(f"multi-process CPU collectives unavailable: "
                    f"{report.get('reason', '')[:200]}")
    assert proc.returncode == 0, report
    assert report["ok"], report
    assert report["drill"] == "kill-one"
    # the survivor must have taken the SALVAGE path (its peer is dead);
    # environments where the dead peer goes unnoticed report salvaged=False
    # and still pass parity, but the interesting assertion is the replay
    if report.get("salvaged"):
        assert report["replayed_batches"] > 0
