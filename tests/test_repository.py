"""Metrics repository tests: serde round-trips for every metric/analyzer
type, key semantics, tag/time/analyzer-filtered loads, scheduler reuse —
the analog of the reference `repository/*Test.scala`."""

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    KLLParameters,
    KLLSketch,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.data import Dataset
from deequ_tpu.repository import (
    FileSystemMetricsRepository,
    InMemoryMetricsRepository,
    ResultKey,
)
from deequ_tpu.repository.serde import (
    deserialize_analyzer,
    serialize_analyzer,
)
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor

ALL_ANALYZERS = [
    Size(),
    Size(where="x > 2"),
    Completeness("item"),
    Completeness("item", "x > 1"),
    Compliance("rule", "x > 0"),
    PatternMatch("item", r"\d+"),
    Mean("x"),
    Sum("x"),
    Minimum("x"),
    Maximum("x"),
    MinLength("item"),
    MaxLength("item"),
    StandardDeviation("x"),
    Correlation("x", "y"),
    DataType("item"),
    ApproxCountDistinct("item"),
    ApproxQuantile("x", 0.5),
    ApproxQuantiles("x", (0.25, 0.75)),
    KLLSketch("x", KLLParameters(128, 0.64, 5)),
    KLLSketch("x"),
    Uniqueness(("item",)),
    Distinctness(("item",)),
    UniqueValueRatio(("item",)),
    CountDistinct(("item",)),
    Entropy("item"),
    MutualInformation(("item", "other")),
    Histogram("item"),
]


class TestAnalyzerSerde:
    @pytest.mark.parametrize("analyzer", ALL_ANALYZERS, ids=lambda a: repr(a)[:50])
    def test_roundtrip(self, analyzer):
        assert deserialize_analyzer(serialize_analyzer(analyzer)) == analyzer


@pytest.fixture
def small_data():
    return Dataset.from_dict(
        {
            "item": ["a", "b", "c", "a"],
            "other": ["x", "x", "y", "y"],
            "x": [1.0, 2.0, 3.0, 4.0],
            "y": [2.0, 4.0, 6.0, 8.0],
        }
    )


def full_context(small_data):
    analyzers = [
        Size(),
        Mean("x"),
        ApproxQuantiles("x", (0.5,)),
        KLLSketch("x", KLLParameters(128, 0.64, 4)),
        Histogram("item"),
        DataType("item"),
    ]
    return AnalysisRunner.do_analysis_run(small_data, analyzers)


class TestRepositories:
    @pytest.mark.parametrize("repo_kind", ["memory", "fs"])
    def test_save_load_roundtrip(self, small_data, tmp_path, repo_kind):
        repo = (
            InMemoryMetricsRepository()
            if repo_kind == "memory"
            else FileSystemMetricsRepository(str(tmp_path / "metrics.json"))
        )
        context = full_context(small_data)
        key = ResultKey(1000, {"tag": "a"})
        repo.save(key, context)
        loaded = repo.load_by_key(key)
        assert loaded is not None
        assert set(loaded.metric_map.keys()) == set(context.metric_map.keys())
        for a, m in context.metric_map.items():
            got = loaded.metric_map[a]
            assert got.value.is_success
            if a == Mean("x"):
                assert got.value.get() == m.value.get() == 2.5
        # KLL metric percentile re-derivation survives the round trip
        kll = loaded.metric_map[KLLSketch("x", KLLParameters(128, 0.64, 4))]
        pcts = kll.value.get().compute_percentiles()
        assert pcts[-1] == 4.0

    def test_save_replaces_key(self, small_data):
        repo = InMemoryMetricsRepository()
        key = ResultKey(1)
        ctx1 = AnalysisRunner.do_analysis_run(small_data, [Size()])
        ctx2 = AnalysisRunner.do_analysis_run(small_data, [Mean("x")])
        repo.save(key, ctx1)
        repo.save(key, ctx2)
        loaded = repo.load_by_key(key)
        assert Size() not in loaded.metric_map
        assert Mean("x") in loaded.metric_map

    def test_loader_filters(self, small_data):
        repo = InMemoryMetricsRepository()
        ctx = AnalysisRunner.do_analysis_run(small_data, [Size(), Mean("x")])
        repo.save(ResultKey(100, {"env": "prod"}), ctx)
        repo.save(ResultKey(200, {"env": "test"}), ctx)
        repo.save(ResultKey(300, {"env": "prod"}), ctx)

        assert len(repo.load().get()) == 3
        assert len(repo.load().with_tag_values({"env": "prod"}).get()) == 2
        assert len(repo.load().after(150).get()) == 2
        assert len(repo.load().before(150).get()) == 1
        assert len(repo.load().after(150).before(250).get()) == 1
        only_size = repo.load().for_analyzers([Size()]).get()
        assert all(set(r.analyzer_context.metric_map) == {Size()} for r in only_size)

    def test_loader_dataframe(self, small_data):
        repo = InMemoryMetricsRepository()
        ctx = AnalysisRunner.do_analysis_run(small_data, [Size()])
        repo.save(ResultKey(100, {"env": "prod"}), ctx)
        df = repo.load().get_success_metrics_as_data_frame(with_tags=["env"])
        assert list(df["env"]) == ["prod"]
        assert list(df["value"]) == [4.0]

    def test_scheduler_reuse_skips_pass(self, small_data):
        """Repository reuse eliminates the data pass entirely — the analog of
        the reference job-count assertion (`AnalysisRunnerTests.scala:120-150`)."""
        repo = InMemoryMetricsRepository()
        key = ResultKey(1)
        mon1 = RunMonitor()
        AnalysisRunner.do_analysis_run(
            small_data,
            [Size(), Mean("x")],
            metrics_repository=repo,
            save_or_append_results_with_key=key,
            monitor=mon1,
        )
        assert mon1.passes == 1
        mon2 = RunMonitor()
        ctx = AnalysisRunner.do_analysis_run(
            small_data,
            [Size(), Mean("x")],
            metrics_repository=repo,
            reuse_existing_results_for_key=key,
            monitor=mon2,
        )
        assert mon2.passes == 0  # everything served from the repository
        assert ctx.metric(Size()).value.get() == 4.0

    def test_fail_if_results_missing(self, small_data):
        from deequ_tpu.runners.exceptions import MetricCalculationException

        repo = InMemoryMetricsRepository()
        key = ResultKey(1)
        AnalysisRunner.do_analysis_run(
            small_data, [Size()], metrics_repository=repo,
            save_or_append_results_with_key=key,
        )
        with pytest.raises(MetricCalculationException):
            AnalysisRunner.do_analysis_run(
                small_data,
                [Size(), Mean("x")],
                metrics_repository=repo,
                reuse_existing_results_for_key=key,
                fail_if_results_missing=True,
            )

    def test_append_semantics(self, small_data):
        repo = InMemoryMetricsRepository()
        key = ResultKey(7)
        AnalysisRunner.do_analysis_run(
            small_data, [Size()], metrics_repository=repo,
            save_or_append_results_with_key=key,
        )
        AnalysisRunner.do_analysis_run(
            small_data, [Mean("x")], metrics_repository=repo,
            save_or_append_results_with_key=key,
        )
        loaded = repo.load_by_key(key)
        assert Size() in loaded.metric_map and Mean("x") in loaded.metric_map

    def test_fs_repo_survives_reopen(self, small_data, tmp_path):
        path = str(tmp_path / "history.json")
        repo = FileSystemMetricsRepository(path)
        ctx = AnalysisRunner.do_analysis_run(small_data, [Size()])
        repo.save(ResultKey(1), ctx)
        reopened = FileSystemMetricsRepository(path)
        assert reopened.load_by_key(ResultKey(1)).metric_map[Size()].value.get() == 4.0


def test_kll_where_roundtrip():
    a = KLLSketch("x", KLLParameters(128, 0.64, 4), where="x > 0")
    assert deserialize_analyzer(serialize_analyzer(a)) == a
