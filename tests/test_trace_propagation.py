"""Cross-process trace propagation (ISSUE 20 tentpole).

The acceptance contract this file pins:

- the ``X-Deequ-Trace`` wire format round-trips through
  ``inject``/``extract``: a remote child lands under the producer's
  trace_id with the producer's span_id as its parent;
- the suppression shape (``;;0``) crosses the wire: an unsampled trace
  keeps NO spans on the remote side either (half a trace is worse than
  none), and malformed headers degrade to a fresh root, never an error;
- the sampling verdict is a pure function of the trace_id, so two
  PROCESSES reading the same ``DEEQU_TPU_TRACE`` fraction reach the same
  per-trace decision (satellite 3);
- the HTTP ingest endpoint extracts the header and parents its request
  span — and the folds under it — into the remote trace;
- a cluster worker's protocol spans (``worker_open``/``worker_ingest``/
  ``worker_flush``) join the front tier's trace via ``trace_ctx``
  (satellite 1);
- per-host span journals land as line-buffered JSONL with a header line,
  and ``merge_journals`` stitches them onto ONE timeline with one pid
  track per host;
- ``tools/trace_summarize.py`` accounts roots vs ORPHANED spans and
  warns when a hop dropped its context (satellite 2).
"""

import io
import json
import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pytest

from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.cluster import LocalWorker
from deequ_tpu.ingest import encode_ipc_stream
from deequ_tpu.observability import export as obs_export
from deequ_tpu.observability import trace
from deequ_tpu.observability.recorder import (
    TRACE_HOST_ENV,
    TRACE_JOURNAL_ENV,
    recorder,
)
from deequ_tpu.service import VerificationService
from tools import trace_summarize

pytestmark = pytest.mark.trace

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_recorder():
    recorder().clear()
    yield
    recorder().clear()


def _checks():
    return [
        Check(CheckLevel.ERROR, "wire battery")
        .has_size(lambda n: n > 0)
        .is_complete("x")
    ]


def _payload(rows=512, seed=3):
    rng = np.random.default_rng(seed)
    table = pa.table({
        "x": rng.normal(size=rows),
        "y": rng.normal(10.0, 2.0, size=rows),
    })
    return encode_ipc_stream(table)


# ---------------------------------------------------------------------------
# wire format: inject / extract
# ---------------------------------------------------------------------------


class TestWireFormat:
    def test_inject_extract_round_trip(self):
        with trace.span("origin", kind="rpc") as origin:
            header = trace.inject()
            assert header == f"{origin.trace_id};{origin.span_id};1"
        ctx = trace.extract(header)
        assert isinstance(ctx, trace.TraceContext)
        assert ctx.to_header() == header
        child = trace.start_span("remote_side", kind="rpc", parent=ctx)
        assert child.trace_id == origin.trace_id
        assert child.parent_id == origin.span_id
        child.finish()

    def test_inject_without_context_sends_no_header(self):
        assert trace.inject() is None

    def test_explicit_span_injects_its_own_identity(self):
        with trace.span("outer", kind="rpc") as outer:
            with trace.span("inner", kind="rpc") as inner:
                assert trace.inject(outer) == (
                    f"{outer.trace_id};{outer.span_id};1"
                )
                assert trace.inject() == (
                    f"{inner.trace_id};{inner.span_id};1"
                )

    def test_suppression_crosses_the_wire(self, monkeypatch):
        monkeypatch.setenv(trace.TRACE_ENV, "0")
        root = trace.start_span("off", kind="rpc", parent=None)
        assert root is trace.NULL
        header = trace.inject(root)
        assert header == ";;0"
        remote_parent = trace.extract(header)
        assert remote_parent is trace.NULL
        # the remote side must not start a fresh root for half a trace
        assert trace.start_span(
            "remote", kind="rpc", parent=remote_parent
        ) is trace.NULL

    @pytest.mark.parametrize(
        "bad",
        [None, "", "tid;sid", "a;b;c;d", "tid;;1", ";sid;1", "tid;sid;2"],
    )
    def test_malformed_headers_degrade_to_fresh_root(self, bad):
        assert trace.extract(bad) is None


# ---------------------------------------------------------------------------
# deterministic fractional sampling (satellite 3)
# ---------------------------------------------------------------------------


class TestDeterministicSampling:
    def test_verdict_is_pure_function_of_trace_id(self):
        ids = [f"t-{i}" for i in range(256)]
        verdicts = [trace.sampled_trace(t, 0.5) for t in ids]
        assert verdicts == [trace.sampled_trace(t, 0.5) for t in ids]
        # a hash sampler at 0.5 over 256 ids keeps some and drops some
        assert any(verdicts) and not all(verdicts)

    def test_rate_bounds(self):
        assert trace.sampled_trace("anything", 1.0) is True
        assert trace.sampled_trace("anything", 0.0) is False

    def test_two_processes_agree_per_trace_id(self):
        """Satellite 3: a SECOND python process reading the same
        ``DEEQU_TPU_TRACE`` fraction reaches the same keep/drop verdict
        for every trace_id — the decision travels with the id, not with
        any per-process RNG."""
        ids = [f"cross-{i}" for i in range(64)]
        program = (
            "import json, sys\n"
            "from deequ_tpu.observability.trace import sampled_trace\n"
            "ids = json.load(sys.stdin)\n"
            "print(json.dumps([sampled_trace(t) for t in ids]))\n"
        )
        env = dict(os.environ, DEEQU_TPU_TRACE="0.5", JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", program],
            input=json.dumps(ids), capture_output=True, text=True,
            env=env, cwd=_REPO_ROOT, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr
        remote = json.loads(proc.stdout.strip().splitlines()[-1])
        local = [trace.sampled_trace(t, 0.5) for t in ids]
        assert remote == local
        assert any(local) and not all(local)


# ---------------------------------------------------------------------------
# the Arrow ingest wire: X-Deequ-Trace through the HTTP endpoint
# ---------------------------------------------------------------------------


@pytest.fixture()
def service():
    with VerificationService(
        workers=2, max_queue_depth=64, background_warm=False
    ) as svc:
        yield svc


def _post(endpoint, body, header=None):
    headers = {"Content-Length": str(len(body))}
    if header is not None:
        headers[trace.TRACE_HEADER] = header
    return endpoint.handle_post("/ingest/v1/t/d", headers, io.BytesIO(body))


class TestEndpointPropagation:
    def test_header_joins_remote_trace(self, service):
        from deequ_tpu.ingest.endpoint import IngestEndpoint

        service.session("t", "d", _checks())
        endpoint = IngestEndpoint(service)
        status, resp = _post(
            endpoint, _payload(), header="t-producer;s-producer;1"
        )
        assert status == 200, resp
        requests = [
            s for s in recorder().spans() if s.name == "ingest_request"
        ]
        assert len(requests) == 1
        assert requests[0].trace_id == "t-producer"
        assert requests[0].parent_id == "s-producer"
        assert requests[0].status == "ok"
        # the fold under the request rides the REMOTE trace too: one
        # trace_id end to end is the whole point of the wire header
        joined = [
            s for s in recorder().spans() if s.trace_id == "t-producer"
        ]
        assert len(joined) >= 2

    def test_no_header_starts_fresh_root(self, service):
        from deequ_tpu.ingest.endpoint import IngestEndpoint

        service.session("t", "d", _checks())
        endpoint = IngestEndpoint(service)
        status, _ = _post(endpoint, _payload())
        assert status == 200
        requests = [
            s for s in recorder().spans() if s.name == "ingest_request"
        ]
        assert len(requests) == 1
        assert requests[0].parent_id is None
        assert requests[0].trace_id

    def test_suppressed_header_keeps_no_spans(self, service):
        from deequ_tpu.ingest.endpoint import IngestEndpoint

        service.session("t", "d", _checks())
        endpoint = IngestEndpoint(service)
        recorder().clear()
        status, _ = _post(endpoint, _payload(), header=";;0")
        assert status == 200  # suppression never affects the fold itself
        assert [
            s for s in recorder().spans() if s.name == "ingest_request"
        ] == []

    def test_error_status_marks_span(self, service):
        from deequ_tpu.ingest.endpoint import IngestEndpoint

        endpoint = IngestEndpoint(service)
        body = _payload()
        status, resp = _post(endpoint, body, header="t-err;s-err;1")
        assert status == 404  # session never created
        requests = [
            s for s in recorder().spans() if s.name == "ingest_request"
        ]
        assert len(requests) == 1
        assert requests[0].status == "error"
        assert requests[0].trace_id == "t-err"


# ---------------------------------------------------------------------------
# worker protocol spans join the front's trace (satellite 1)
# ---------------------------------------------------------------------------


class TestWorkerSpans:
    def _batch(self, i=0, rows=16):
        base = float(i * rows)
        return {
            "x": np.arange(base, base + rows, dtype=np.float64),
            "y": np.ones(rows, dtype=np.float64),
        }

    def test_protocol_spans_join_remote_trace(self, tmp_path):
        svc = VerificationService(
            workers=1, background_warm=False,
            partition_store=str(tmp_path / "store"),
        )
        worker = LocalWorker("w0", svc)
        try:
            worker.open_session(
                "t", "d", _checks(), trace_ctx="t-front;s-open;1"
            )
            worker.ingest(
                "t", "d", self._batch(), trace_ctx="t-front;s-ingest;1"
            )
            worker.flush("t", "d", trace_ctx="t-front;s-flush;1")
        finally:
            worker.close()
        by_name = {s.name: s for s in recorder().spans()}
        for name, parent in (
            ("worker_open", "s-open"),
            ("worker_ingest", "s-ingest"),
            ("worker_flush", "s-flush"),
        ):
            sp = by_name[name]
            assert sp.trace_id == "t-front"
            assert sp.parent_id == parent
            assert sp.kind == "cluster"
            assert sp.attrs["host"] == "w0"

    def test_without_ctx_worker_starts_its_own_root(self):
        svc = VerificationService(workers=1, background_warm=False)
        worker = LocalWorker("w1", svc)
        try:
            worker.open_session("t", "d", _checks())
            worker.ingest("t", "d", self._batch())
        finally:
            worker.close()
        ingest = [
            s for s in recorder().spans() if s.name == "worker_ingest"
        ]
        assert len(ingest) == 1
        assert ingest[0].parent_id is None


# ---------------------------------------------------------------------------
# per-host span journals + the multi-host merge
# ---------------------------------------------------------------------------


class TestSpanJournal:
    def test_journal_header_and_line_per_span(self, tmp_path, monkeypatch):
        monkeypatch.setenv(TRACE_JOURNAL_ENV, str(tmp_path))
        monkeypatch.setenv(TRACE_HOST_ENV, "alpha")
        recorder().clear()  # re-probe the journal env
        with trace.span("unit_alpha", kind="span"):
            pass
        path = tmp_path / "spans-alpha.jsonl"
        # line-buffered: readable without closing (the SIGKILL contract)
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert lines[0]["journal_header"] is True
        assert lines[0]["host"] == "alpha"
        assert "epoch_anchor_s" in lines[0]
        assert lines[1]["name"] == "unit_alpha"
        assert lines[1]["span_id"]

    def _write_host_journals(self, tmp_path, monkeypatch):
        for host in ("alpha", "beta"):
            monkeypatch.setenv(TRACE_JOURNAL_ENV, str(tmp_path))
            monkeypatch.setenv(TRACE_HOST_ENV, host)
            recorder().clear()
            with trace.span(f"work_{host}", kind="span"):
                pass
        recorder().clear()
        journals = sorted(str(p) for p in tmp_path.glob("spans-*.jsonl"))
        assert len(journals) == 2
        return journals

    def test_merge_journals_one_timeline(self, tmp_path, monkeypatch):
        journals = self._write_host_journals(tmp_path, monkeypatch)
        out = tmp_path / "merged.trace.json"
        doc = obs_export.merge_journals(journals, out_path=str(out))
        hosts = {
            e["args"]["name"]
            for e in doc["traceEvents"] if e.get("ph") == "M"
        }
        assert hosts == {"alpha", "beta"}
        pids = {
            e["pid"] for e in doc["traceEvents"] if e.get("ph") == "X"
        }
        assert len(pids) == 2  # one track per journal
        assert len(doc["otherData"]["journals"]) == 2
        # the written artifact round-trips through the summarizer loader
        spans = trace_summarize.load_spans(str(out))
        assert {s["name"] for s in spans} == {"work_alpha", "work_beta"}

    def test_summarizer_reads_a_journal_directory(
        self, tmp_path, monkeypatch
    ):
        self._write_host_journals(tmp_path, monkeypatch)
        spans = trace_summarize.load_spans(str(tmp_path))
        assert {s["name"] for s in spans} == {"work_alpha", "work_beta"}
        text = trace_summarize.summarize(str(tmp_path))
        assert "2 distinct trace_ids" in text

    def test_torn_final_line_is_skipped_not_fatal(self, tmp_path):
        path = tmp_path / "spans-torn.jsonl"
        good = {
            "trace_id": "t1", "span_id": "a", "parent_id": None,
            "name": "ok", "kind": "span", "start_ns": 0, "end_ns": 5,
            "status": "ok", "thread": 0, "attrs": {}, "events": [],
        }
        path.write_text(
            json.dumps({"journal_header": True, "host": "torn", "pid": 1,
                        "epoch_anchor_s": 0.0}) + "\n"
            + json.dumps(good) + "\n"
            + '{"trace_id": "t1", "span_id": "b", "star'  # SIGKILL tear
        )
        header, spans, skipped = obs_export.load_journal(str(path))
        assert header["host"] == "torn"
        assert [s["span_id"] for s in spans] == ["a"]
        assert skipped == 1


# ---------------------------------------------------------------------------
# orphan accounting in the summarizer (satellite 2)
# ---------------------------------------------------------------------------


class TestOrphanAccounting:
    def _spans(self, with_orphan=True):
        base = {
            "kind": "span", "status": "ok", "thread": 0,
            "attrs": {}, "events": [],
        }
        spans = [
            dict(base, trace_id="t1", span_id="a", parent_id=None,
                 name="root", start_ns=0, end_ns=10),
            dict(base, trace_id="t1", span_id="b", parent_id="a",
                 name="child", start_ns=1, end_ns=5),
        ]
        if with_orphan:
            spans.append(
                dict(base, trace_id="t1", span_id="c",
                     parent_id="missing", name="lost", start_ns=2,
                     end_ns=4)
            )
        return spans

    def test_span_accounting_counts(self):
        acct = trace_summarize.span_accounting(self._spans())
        assert acct == {
            "total": 3, "roots": 1, "orphans": 1, "trace_ids": 1,
        }

    def test_summarize_warns_on_orphans(self, tmp_path):
        path = tmp_path / "spans.jsonl"
        path.write_text(
            "".join(json.dumps(s) + "\n" for s in self._spans())
        )
        text = trace_summarize.summarize(str(path))
        assert "1 orphaned" in text
        assert "WARNING: orphaned spans" in text

    def test_clean_artifact_has_no_warning(self, tmp_path):
        path = tmp_path / "spans.jsonl"
        path.write_text(
            "".join(
                json.dumps(s) + "\n"
                for s in self._spans(with_orphan=False)
            )
        )
        text = trace_summarize.summarize(str(path))
        assert "0 orphaned" in text
        assert "WARNING" not in text
