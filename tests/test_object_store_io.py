"""Object-store IO round-trips through the registered in-memory fsspec
filesystem (VERDICT r3 missing #1: the reference reads/writes state blobs
and metric histories on HDFS/S3 via Hadoop FileSystem,
`io/DfsUtils.scala:24-85`, `analyzers/StateProvider.scala:73-312`)."""

import numpy as np
import pyarrow as pa
import pytest

from deequ_tpu.analyzers import ApproxCountDistinct, KLLSketch, Mean, Uniqueness
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner


@pytest.fixture(autouse=True)
def clean_memory_fs():
    from fsspec.implementations.memory import MemoryFileSystem

    MemoryFileSystem.store.clear()
    yield
    MemoryFileSystem.store.clear()


@pytest.fixture
def data():
    rng = np.random.default_rng(11)
    return Dataset.from_arrow(
        pa.table(
            {
                "x": pa.array(rng.normal(size=1000)),
                "s": pa.array(rng.choice(["a", "b", "c"], 1000)),
            }
        )
    )


class TestParquetIngest:
    def test_from_parquet_memory_uri(self, data):
        from deequ_tpu import io as dio

        dio.write_parquet_table(data.arrow, "memory://bucket/data.parquet")
        back = Dataset.from_parquet("memory://bucket/data.parquet")
        assert back.num_rows == 1000
        a = Mean("x")
        ctx = AnalysisRunner.do_analysis_run(back, [a])
        want = data.arrow["x"].to_numpy().mean()
        assert ctx.metric(a).value.get() == pytest.approx(want)


class TestMultiFileRemoteRead:
    def test_from_parquet_list_of_memory_uris(self, data):
        from deequ_tpu import io as dio

        tbl = data.arrow
        dio.write_parquet_table(tbl.slice(0, 600), "memory://bkt/part0.parquet")
        dio.write_parquet_table(tbl.slice(600), "memory://bkt/part1.parquet")
        back = Dataset.from_parquet(
            ["memory://bkt/part0.parquet", "memory://bkt/part1.parquet"]
        )
        assert back.num_rows == 1000


class TestStateProviderObjectStore:
    def test_scan_and_sketch_states_roundtrip(self, data):
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        battery = [Mean("x"), ApproxCountDistinct("s"), KLLSketch("x")]
        sp = FileSystemStateProvider("memory://bucket/states")
        ctx = AnalysisRunner.do_analysis_run(data, battery, save_states_with=sp)
        merged = AnalysisRunner.run_on_aggregated_states(data.schema, battery, [sp])
        for a in battery:
            got = merged.metric(a).value
            want = ctx.metric(a).value
            assert got.is_success, a
            if isinstance(want.get(), float):
                assert got.get() == pytest.approx(want.get()), a

    def test_frequency_state_roundtrip(self, data):
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        a = Uniqueness("s")
        sp = FileSystemStateProvider("memory://bucket/freq")
        ctx = AnalysisRunner.do_analysis_run(data, [a], save_states_with=sp)
        merged = AnalysisRunner.run_on_aggregated_states(data.schema, [a], [sp])
        assert merged.metric(a).value.get() == ctx.metric(a).value.get()

    def test_incremental_two_partitions_equal_full(self, data):
        """The multi-host pod use case: two day partitions persist states to
        shared storage; merging them equals one full run."""
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        tbl = data.arrow
        day1, day2 = Dataset(tbl.slice(0, 600)), Dataset(tbl.slice(600))
        battery = [Mean("x"), Uniqueness("s")]
        providers = []
        for i, day in enumerate((day1, day2)):
            sp = FileSystemStateProvider(f"memory://bucket/day{i}")
            AnalysisRunner.do_analysis_run(day, battery, save_states_with=sp)
            providers.append(sp)
        merged = AnalysisRunner.run_on_aggregated_states(data.schema, battery, providers)
        full = AnalysisRunner.do_analysis_run(data, battery)
        for a in battery:
            assert merged.metric(a).value.get() == pytest.approx(
                full.metric(a).value.get()
            ), a


class TestMetricsRepositoryObjectStore:
    def test_history_roundtrip_and_query(self, data):
        from deequ_tpu.repository import ResultKey
        from deequ_tpu.repository.fs import FileSystemMetricsRepository

        repo = FileSystemMetricsRepository("memory://bucket/metrics.json")
        a = Mean("x")
        for ts in (1000, 2000, 3000):
            AnalysisRunner.do_analysis_run(
                data, [a], metrics_repository=repo,
                save_or_append_results_with_key=ResultKey(ts, {"env": "t"}),
            )
        loaded = repo.load().after(1500).get()
        assert len(loaded) == 2
        ctx = repo.load_by_key(ResultKey(2000, {"env": "t"}))
        assert ctx is not None
        assert ctx.metric(a).value.is_success

    def test_save_replaces_key(self, data):
        from deequ_tpu.repository import ResultKey
        from deequ_tpu.repository.fs import FileSystemMetricsRepository

        repo = FileSystemMetricsRepository("memory://bucket/metrics.json")
        key = ResultKey(1, {})
        a = Mean("x")
        repo.save(key, AnalysisRunner.do_analysis_run(data, [a]))
        repo.save(key, AnalysisRunner.do_analysis_run(data, [a]))
        assert len(repo.load().get()) == 1


class TestLocalPathsUnchanged:
    def test_local_still_works(self, tmp_path, data):
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider
        from deequ_tpu.repository import ResultKey
        from deequ_tpu.repository.fs import FileSystemMetricsRepository

        a = Mean("x")
        sp = FileSystemStateProvider(str(tmp_path / "states"))
        repo = FileSystemMetricsRepository(str(tmp_path / "m.json"))
        AnalysisRunner.do_analysis_run(
            data, [a], save_states_with=sp, metrics_repository=repo,
            save_or_append_results_with_key=ResultKey(1, {}),
        )
        assert sp.load(a) is not None
        assert repo.load_by_key(ResultKey(1, {})) is not None


class TestJsonSinksObjectStore:
    def test_verification_sinks_accept_uris(self, data):
        from deequ_tpu import io as dio
        from deequ_tpu.checks import Check, CheckLevel
        from deequ_tpu.verification import VerificationSuite

        (
            VerificationSuite.on_data(data)
            .add_check(Check(CheckLevel.ERROR, "c").has_size(lambda n: n == 1000))
            .save_check_results_json_to_path("memory://out/checks.json")
            .save_success_metrics_json_to_path("memory://out/metrics.json")
            .run()
        )
        import json as _json

        with dio.open_file("memory://out/checks.json", "r") as f:
            assert _json.loads(f.read())
        with dio.open_file("memory://out/metrics.json", "r") as f:
            assert _json.loads(f.read())

    def test_profile_and_suggestion_sinks_accept_uris(self, data):
        from deequ_tpu import io as dio
        from deequ_tpu.profiles import ColumnProfilerRunner
        from deequ_tpu.suggestions import ConstraintSuggestionRunner, Rules

        ColumnProfilerRunner.on_data(data).save_column_profiles_json_to_path(
            "memory://out/profiles.json"
        ).run()
        (
            ConstraintSuggestionRunner.on_data(data)
            .add_constraint_rules(Rules.DEFAULT)
            .save_constraint_suggestions_json_to_path("memory://out/sugg.json")
            .run()
        )
        import json as _json

        for p in ("memory://out/profiles.json", "memory://out/sugg.json"):
            with dio.open_file(p, "r") as f:
                assert _json.loads(f.read()), p
