"""Ingestion plane: columnar coercion, Arrow IPC frontend, HTTP endpoint,
prefetch pipeline, bounded-admission backpressure (PR 9)."""

import json
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.exceptions import (
    FeedDisconnectError,
    MalformedFrameError,
    SchemaDriftError,
)
from deequ_tpu.ingest import (
    CHECKSUM_HEADER,
    PrefetchingBatchIterator,
    as_dataset,
    encode_ipc_stream,
    fold_stream,
    iter_frames,
)
from deequ_tpu.integrity import checksum_bytes
from deequ_tpu.reliability import FaultSpec, inject
from deequ_tpu.service import ServiceOverloaded, VerificationService

pytestmark = pytest.mark.ingest


def _checks():
    return [
        Check(CheckLevel.ERROR, "ingest")
        .has_size(lambda n: n > 0)
        .is_complete("x")
        .has_mean("y", lambda m: 0.0 < m < 20.0),
    ]


def _table(rows=2000, seed=0, nulls=True):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=rows)
    y = rng.normal(10.0, 2.0, size=rows)
    y_mask = (rng.random(rows) < 0.05) if nulls else np.zeros(rows, bool)
    cats = np.array(["alpha", "beta", "gamma", "delta"])
    c = cats[rng.integers(0, len(cats), rows)].astype(object)
    if nulls:
        c[rng.random(rows) < 0.03] = None
    return pa.table({
        "x": pa.array(x),
        "y": pa.array(y, mask=y_mask),
        "c": pa.array(c).dictionary_encode(),
    })


def _success_metrics(result):
    return {
        (a.name, a.instance): m.value.get()
        for a, m in result.metrics.items() if m.value.is_success
    }


@pytest.fixture
def service():
    with VerificationService(
        workers=2, max_queue_depth=64, background_warm=False
    ) as svc:
        yield svc


class TestAsDataset:
    def test_dataset_passthrough_is_identity(self):
        ds = Dataset.from_dict({"a": [1, 2, 3]})
        assert as_dataset(ds) is ds

    def test_table_and_record_batch(self):
        t = _table(100)
        assert as_dataset(t).num_rows == 100
        rb = t.to_batches()[0]
        ds = as_dataset(rb)
        assert ds.num_rows == len(rb)
        assert set(ds.schema.names) == {"x", "y", "c"}

    def test_dict_of_numpy_no_pandas(self):
        ds = as_dataset({
            "x": np.arange(5, dtype=np.float64),
            "n": np.array([1, 2, 3, 4, 5], dtype=np.int32),
        })
        assert ds.num_rows == 5
        assert ds.arrow["x"].to_pylist() == [0.0, 1.0, 2.0, 3.0, 4.0]

    def test_rejects_unknown_type(self):
        with pytest.raises(TypeError, match="cannot ingest"):
            as_dataset(42)

    def test_session_ingest_accepts_dict(self, service):
        rng = np.random.default_rng(1)
        session = service.session("t", "dict", _checks())
        result = session.ingest({
            "x": rng.normal(size=512), "y": rng.normal(10, 1, 512),
        })
        assert result.status == CheckStatus.SUCCESS
        assert session.batches_ingested == 1
        assert session.bytes_ingested > 0


class TestRoundTrip:
    def test_encode_decode_frames(self):
        t = _table(3000)
        payload = encode_ipc_stream(t, max_chunksize=1000)
        frames = list(iter_frames(payload))
        assert [i for i, _ in frames] == [0, 1, 2]
        got = pa.Table.from_batches([b for _, b in frames])
        assert got.num_rows == 3000

    def test_fold_stream_counts_and_checksum(self, service):
        t = _table(2000)
        payload = encode_ipc_stream(t, max_chunksize=1000)
        session = service.session("t", "rt", _checks())
        report = fold_stream(
            session, payload, checksum=checksum_bytes(payload), source="test"
        )
        assert report.frames == 2
        assert report.rows == 2000
        assert report.bytes == len(payload)
        assert session.batches_ingested == 2
        m = service.metrics
        labels = dict(tenant="t", dataset="rt")
        assert m.counter_value(
            "deequ_service_ingest_batches_total", **labels) == 2
        assert m.counter_value(
            "deequ_service_ingest_bytes_total", **labels) == len(payload)
        assert m.counter_value(
            "deequ_service_ingest_sessions_total", **labels) == 1


class TestParity:
    """Bit-exact metric parity between Arrow-fed, dict-fed and pandas-fed
    sessions — dictionary-encoded and null-bearing columns included."""

    def _battery(self):
        from deequ_tpu.analyzers import (
            ApproxCountDistinct,
            Completeness,
            Mean,
            StandardDeviation,
        )

        return [
            Completeness("x"), Completeness("y"), Completeness("c"),
            Mean("y"), StandardDeviation("y"), ApproxCountDistinct("c"),
        ]

    def test_three_feeds_bit_exact(self, service):
        t = _table(4000, seed=3)
        required = self._battery()

        arrow_s = service.session("p", "arrow", (),
                                  required_analyzers=required)
        fold_stream(arrow_s, encode_ipc_stream(t, max_chunksize=2000),
                    source="parity")

        dict_s = service.session("p", "dict", (), required_analyzers=required)
        for lo in (0, 2000):
            sl = t.slice(lo, 2000)
            dict_s.ingest({
                "x": sl["x"].to_numpy(),
                # null-bearing float column: NaN marks the nulls
                "y": sl["y"].to_numpy(zero_copy_only=False),
                "c": sl["c"].to_pylist(),
            })

        pandas_s = service.session("p", "pandas", (),
                                   required_analyzers=required)
        df = t.to_pandas()
        for lo in (0, 2000):
            pandas_s.ingest(Dataset.from_pandas(df.iloc[lo:lo + 2000]))

        ma = _success_metrics(arrow_s.current())
        md = _success_metrics(dict_s.current())
        mp = _success_metrics(pandas_s.current())
        assert len(ma) == len(required)
        assert ma == md == mp  # bit-exact, not approx

    def test_dictionary_and_null_frames_match_direct_run(self, service):
        from deequ_tpu.verification import VerificationSuite

        t = _table(3000, seed=9)
        session = service.session("p", "direct", _checks())
        fold_stream(session, encode_ipc_stream(t, max_chunksize=1000),
                    source="parity")
        direct = VerificationSuite.on_data(Dataset(t)).add_checks(
            _checks()
        ).run()
        streamed = _success_metrics(session.current())
        oracle = _success_metrics(direct)
        assert set(streamed) == set(oracle)
        # the streamed run folded 3 frames (different summation order than
        # the one-pass oracle): counts are exact, float aggregates agree
        # to 1e-12 relative
        for k, want in oracle.items():
            assert streamed[k] == pytest.approx(want, rel=1e-12, abs=1e-12)


class TestDriftGuard:
    """Drift policies fire identically on the Arrow path."""

    def test_retyped_column_rejected_typed(self, service):
        session = service.session("d", "reject", _checks())
        fold_stream(session, encode_ipc_stream(_table(1000)), source="drift")
        assert session.batches_ingested == 1
        drifted = pa.table({
            "x": pa.array(np.zeros(100)),
            "y": pa.array(["oops"] * 100),  # float -> string retype
            "c": pa.array(["alpha"] * 100).dictionary_encode(),
        })
        with pytest.raises(SchemaDriftError):
            fold_stream(session, encode_ipc_stream(drifted), source="drift")
        assert session.batches_ingested == 1  # states untouched

    def test_widening_coerces_on_arrow_path(self, service):
        rng = np.random.default_rng(4)
        first = pa.table({
            "x": pa.array(rng.normal(size=500)),
            "y": pa.array(rng.normal(10, 1, 500)),
        })
        session = service.session("d", "widen", _checks())
        fold_stream(session, encode_ipc_stream(first), source="drift")
        # float32 arriving where float64 was promised: same-family
        # widening — coerced and counted, never rejected
        narrow = pa.table({
            "x": pa.array(rng.normal(size=500).astype(np.float32)),
            "y": pa.array(rng.normal(10, 1, 500).astype(np.float32)),
        })
        fold_stream(session, encode_ipc_stream(narrow), source="drift")
        assert session.batches_ingested == 2
        assert session.drift_coercions >= 1

    def test_degrade_policy_folds_surviving_columns(self, service):
        session = service.session(
            "d", "degrade", _checks(), drift_policy="degrade"
        )
        fold_stream(session, encode_ipc_stream(_table(1000)), source="drift")
        drifted = pa.table({
            "x": pa.array(np.zeros(200)),
            "y": pa.array(["oops"] * 200),
            "c": pa.array(["alpha"] * 200).dictionary_encode(),
        })
        fold_stream(session, encode_ipc_stream(drifted), source="drift")
        assert session.batches_ingested == 2
        assert session.drift_degraded_batches == 1


class TestMalformedAndDisconnect:
    def test_garbage_nothing_folds(self, service):
        session = service.session("m", "garbage", _checks())
        with pytest.raises(MalformedFrameError):
            fold_stream(session, b"definitely not an arrow stream",
                        source="test")
        assert session.batches_ingested == 0
        assert service.metrics.counter_value(
            "deequ_service_ingest_malformed_total",
            tenant="m", dataset="garbage",
        ) == 1

    def test_checksum_mismatch_nothing_folds(self, service):
        payload = encode_ipc_stream(_table(1000))
        bad = bytearray(payload)
        bad[len(bad) // 2] ^= 0xFF  # silent under IPC decode...
        session = service.session("m", "sum", _checks())
        with pytest.raises(MalformedFrameError, match="checksum"):
            fold_stream(session, bytes(bad),
                        checksum=checksum_bytes(payload), source="test")
        assert session.batches_ingested == 0

    def test_truncated_stream_commits_leading_frames(self, service):
        import io

        tables = [_table(800, seed=s) for s in (1, 2, 3)]
        sink = io.BytesIO()
        bounds = []
        with pa.ipc.new_stream(sink, tables[0].schema) as w:
            for t in tables:
                for b in t.to_batches():
                    w.write_batch(b)
                bounds.append(sink.tell())
        payload = sink.getvalue()
        cut = bounds[1] + (bounds[2] - bounds[1]) // 2
        session = service.session("m", "torn", _checks())
        with pytest.raises(FeedDisconnectError) as exc_info:
            fold_stream(session, payload[:cut], complete=False, source="t")
        assert exc_info.value.frames_decoded == 2
        assert session.batches_ingested == 2
        assert service.metrics.counter_value(
            "deequ_service_ingest_disconnects_total",
            tenant="m", dataset="torn",
        ) == 1

    def test_injected_frame_corrupt(self, service):
        session = service.session("m", "inject", _checks())
        payload = encode_ipc_stream(_table(2000), max_chunksize=1000)
        with inject(FaultSpec("frame_decode", "frame_corrupt", at=2)) as inj:
            with pytest.raises(MalformedFrameError):
                fold_stream(session, payload, source="test")
        assert inj.fired == ["frame_decode:1:frame_corrupt"]
        assert session.batches_ingested == 1  # first frame stayed committed


class TestHttpEndpoint:
    def _post(self, exporter, path, body, headers=None):
        import http.client
        import json

        conn = http.client.HTTPConnection(
            exporter.host, exporter.port, timeout=30
        )
        try:
            conn.request("POST", path, body=body, headers=headers or {})
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read().decode())
        finally:
            conn.close()

    def test_post_folds_and_counts(self, service):
        service.session("h", "ok", _checks())
        exporter = service.start_exporter()
        payload = encode_ipc_stream(_table(2000), max_chunksize=1000)
        status, body = self._post(
            exporter, "/ingest/v1/h/ok", payload,
            {CHECKSUM_HEADER: checksum_bytes(payload)},
        )
        assert status == 200
        assert body["frames"] == 2 and body["rows"] == 2000
        assert body["statuses"] == ["Success", "Success"]
        text = service.prometheus_text()
        assert "deequ_service_ingest_batches_total" in text
        assert "# HELP deequ_service_ingest_bytes_total" in text

    def test_unknown_session_is_404_never_autocreated(self, service):
        exporter = service.start_exporter()
        status, body = self._post(
            exporter, "/ingest/v1/nope/nothing",
            encode_ipc_stream(_table(100)),
        )
        assert status == 404 and body["error"] == "unknown_session"
        assert service.get_session("nope", "nothing") is None

    def test_closed_session_is_410_gone(self, service):
        session = service.session("h", "closed", _checks())
        session.close()
        exporter = service.start_exporter()
        status, body = self._post(
            exporter, "/ingest/v1/h/closed", encode_ipc_stream(_table(100))
        )
        # "gone", not "never existed": a producer must not be told to
        # re-register a deliberately closed session
        assert status == 410 and body["error"] == "session_closed"

    def test_malformed_body_is_400(self, service):
        session = service.session("h", "bad", _checks())
        exporter = service.start_exporter()
        status, body = self._post(
            exporter, "/ingest/v1/h/bad", b"garbage not arrow"
        )
        assert status == 400 and body["error"] == "malformed_frame"
        assert session.batches_ingested == 0

    def test_drift_is_409(self, service):
        session = service.session("h", "drift", _checks())
        exporter = service.start_exporter()
        self._post(exporter, "/ingest/v1/h/drift",
                   encode_ipc_stream(_table(500)))
        drifted = pa.table({"x": pa.array(np.zeros(10))})
        status, body = self._post(
            exporter, "/ingest/v1/h/drift", encode_ipc_stream(drifted)
        )
        assert status == 409 and body["error"] == "schema_drift"
        assert session.batches_ingested == 1

    def test_disconnect_mid_body_counts_and_commits_nothing_torn(
        self, service
    ):
        import socket

        session = service.session("h", "torn", _checks())
        exporter = service.start_exporter()
        payload = encode_ipc_stream(_table(2000), max_chunksize=1000)
        sock = socket.create_connection((exporter.host, exporter.port))
        head = (
            f"POST /ingest/v1/h/torn HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n"
        ).encode()
        sock.sendall(head + payload[: len(payload) // 4])
        sock.close()
        deadline = time.time() + 10
        while time.time() < deadline:
            if service.metrics.counter_value(
                "deequ_service_ingest_disconnects_total",
                tenant="h", dataset="torn",
            ) >= 1:
                break
            time.sleep(0.05)
        assert service.metrics.counter_value(
            "deequ_service_ingest_disconnects_total",
            tenant="h", dataset="torn",
        ) == 1
        # bytes of the torn stream never count as ingested
        assert service.metrics.counter_value(
            "deequ_service_ingest_bytes_total", tenant="h", dataset="torn"
        ) == 0

    def test_checksummed_torn_body_folds_nothing(self, service):
        import socket

        session = service.session("h", "csum-torn", _checks())
        exporter = service.start_exporter()
        payload = encode_ipc_stream(_table(2000), max_chunksize=1000)
        digest = checksum_bytes(payload)
        sock = socket.create_connection((exporter.host, exporter.port))
        head = (
            f"POST /ingest/v1/h/csum-torn HTTP/1.1\r\nHost: t\r\n"
            f"{CHECKSUM_HEADER}: {digest}\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n"
        ).encode()
        # ship MOST of the payload (several complete frames' worth), then
        # die: the declared digest can never verify, so NOTHING folds
        sock.sendall(head + payload[: len(payload) - 50])
        sock.close()
        deadline = time.time() + 10
        while time.time() < deadline:
            if service.metrics.counter_value(
                "deequ_service_ingest_disconnects_total",
                tenant="h", dataset="csum-torn",
            ) >= 1:
                break
            time.sleep(0.05)
        assert session.batches_ingested == 0
        assert service.metrics.counter_value(
            "deequ_service_ingest_disconnects_total",
            tenant="h", dataset="csum-torn",
        ) == 1

    def test_overload_is_429(self):
        with VerificationService(
            workers=1, max_queue_depth=1, background_warm=False
        ) as svc:
            session = svc.session("h", "busy", _checks())
            exporter = svc.start_exporter()
            release = threading.Event()
            # wedge the single worker, then fill the single queue slot
            svc.scheduler.submit(lambda ctx: release.wait(10))
            time.sleep(0.1)  # let the worker pick the wedge up
            svc.scheduler.submit(lambda ctx: None)
            payload = encode_ipc_stream(_table(100))
            status, body = self._post(
                exporter, "/ingest/v1/h/busy", payload
            )
            release.set()
            assert status == 429 and body["error"] == "overloaded"
            assert svc.metrics.counter_value(
                "deequ_service_ingest_shed_total",
                tenant="h", dataset="busy",
            ) == 1
            assert session.batches_ingested == 0


class TestPrefetch:
    def test_preserves_order_and_stops(self):
        items = iter(range(10))

        def produce():
            return next(items, None)

        with PrefetchingBatchIterator(produce, depth=2) as it:
            assert list(it) == list(range(10))

    def test_serial_depth_zero_inline(self):
        calls = []
        items = iter(range(4))

        def produce():
            calls.append(threading.current_thread().name)
            return next(items, None)

        with PrefetchingBatchIterator(produce, depth=0) as it:
            got = list(it)
        assert got == list(range(4))
        assert set(calls) == {threading.current_thread().name}

    def test_propagates_producer_exception(self):
        state = {"n": 0}

        def produce():
            state["n"] += 1
            if state["n"] == 3:
                raise RuntimeError("boom")
            return state["n"]

        with PrefetchingBatchIterator(produce, depth=2) as it:
            assert next(it) == 1
            assert next(it) == 2
            with pytest.raises(RuntimeError, match="boom"):
                for _ in it:
                    pass

    def test_close_unblocks_parked_producer(self):
        def produce():
            return "item"  # endless

        it = PrefetchingBatchIterator(produce, depth=1)
        assert next(it) == "item"
        it.close()  # must not hang on the full queue
        assert it._thread is None

    def test_silent_feed_trips_stall_deadline_typed(self):
        from deequ_tpu.exceptions import FeedStallError

        wedge = threading.Event()

        def produce():
            wedge.wait(30)  # a hung transfer: never returns, never raises
            return None

        with PrefetchingBatchIterator(
            produce, depth=1, stall_timeout_s=0.3
        ) as it:
            t0 = time.perf_counter()
            with pytest.raises(FeedStallError):
                next(it)
            assert 0.2 <= time.perf_counter() - t0 < 10.0
        wedge.set()

    def test_env_depth_warn_and_fallback(self, monkeypatch):
        from deequ_tpu.ingest import prefetch as pf

        monkeypatch.setenv(pf.PREFETCH_DEPTH_ENV, "not-a-number")
        assert pf.prefetch_depth() == pf.DEFAULT_PREFETCH_DEPTH
        monkeypatch.setenv(pf.PREFETCH_DEPTH_ENV, "5")
        assert pf.prefetch_depth() == 5
        monkeypatch.setenv(pf.PREFETCH_DEPTH_ENV, "0")
        assert pf.prefetch_depth() == 0

    def test_engine_parity_across_depths(self, monkeypatch):
        from deequ_tpu.analyzers import Completeness, Mean, Sum
        from deequ_tpu.runners import AnalysisRunner

        rng = np.random.default_rng(6)
        data = Dataset.from_dict({"x": rng.normal(size=50_000)})
        analyzers = [Completeness("x"), Mean("x"), Sum("x")]

        def run(depth):
            monkeypatch.setenv("DEEQU_TPU_PREFETCH_DEPTH", str(depth))
            ctx = AnalysisRunner.do_analysis_run(
                data, analyzers, batch_size=8192, placement="device"
            )
            return {
                repr(a): m.value.get()
                for a, m in ctx.metric_map.items() if m.value.is_success
            }

        m0, m1, m3 = run(0), run(1), run(3)
        assert m0 == m1 == m3 and len(m0) == 3  # bit-exact

    def test_feed_stall_fails_over_typed(self):
        from deequ_tpu.analyzers import Completeness, Mean
        from deequ_tpu.runners import AnalysisRunner
        from deequ_tpu.runners.engine import RunMonitor

        rng = np.random.default_rng(7)
        data = Dataset.from_dict({"x": rng.normal(size=40_000)})
        analyzers = [Completeness("x"), Mean("x")]
        clean = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=8192, placement="device"
        )
        mon = RunMonitor()
        with inject(FaultSpec("prefetch", "feed_stall", at=2)) as inj:
            stalled = AnalysisRunner.do_analysis_run(
                data, analyzers, batch_size=8192, placement="device",
                monitor=mon,
            )
        assert inj.fired == ["prefetch:1:feed_stall"]
        assert mon.device_failovers == 1  # typed -> host-tier failover
        for a in analyzers:
            want = clean.metric(a).value.get()
            got = stalled.metric(a).value.get()
            assert got == pytest.approx(want, rel=1e-9)


class TestBackpressure:
    def test_block_s_waits_for_space_instead_of_shedding(self):
        with VerificationService(
            workers=1, max_queue_depth=1, background_warm=False
        ) as svc:
            release = threading.Event()
            svc.scheduler.submit(lambda ctx: release.wait(10))
            time.sleep(0.1)
            filler = svc.scheduler.submit(lambda ctx: "filler")
            # without backpressure: immediate typed shed
            with pytest.raises(ServiceOverloaded):
                svc.scheduler.submit(lambda ctx: "shed")

            def free():
                time.sleep(0.3)
                release.set()

            threading.Thread(target=free, daemon=True).start()
            handle = svc.scheduler.submit(
                lambda ctx: "waited", block_s=10.0
            )
            assert handle.result(timeout=10) == "waited"
            assert filler.result(timeout=10) == "filler"

    def test_block_s_expiry_sheds_typed(self):
        with VerificationService(
            workers=1, max_queue_depth=1, background_warm=False
        ) as svc:
            release = threading.Event()
            try:
                svc.scheduler.submit(lambda ctx: release.wait(10))
                time.sleep(0.1)
                svc.scheduler.submit(lambda ctx: None)
                t0 = time.perf_counter()
                with pytest.raises(ServiceOverloaded):
                    svc.scheduler.submit(lambda ctx: None, block_s=0.3)
                assert 0.2 <= time.perf_counter() - t0 < 5.0
            finally:
                release.set()


class TestSoakSmoke:
    def test_concurrency_soak_completes(self):
        from tools.ingest_soak import run_concurrency_soak

        summary = run_concurrency_soak(
            sessions=12, batches=2, rows=512, workers=4, queue_depth=16,
            block_s=30.0, feeders=4,
        )
        assert summary["ok"]
        assert summary["sessions_completed"] == 12
        assert summary["failed_folds"] == 0

    def test_stream_throughput_parity(self):
        from tools.ingest_soak import run_stream_throughput

        summary = run_stream_throughput(
            target_mb=1.0, rows_per_batch=1 << 14, workers=2
        )
        assert summary["ok"] and summary["parity_ok"]
        assert summary["frames"] >= 1


class TestIncrementalHttpDecode:
    """The unbuffered ingest path: an unchecksummed POST decodes frame by
    frame straight off the socket — one frame in memory, not the body."""

    def _post_chunked(self, exporter, path, payload, chunks, gap_s=0.02):
        import socket

        sock = socket.create_connection((exporter.host, exporter.port))
        head = (
            f"POST {path} HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n"
        ).encode()
        sock.sendall(head)
        step = -(-len(payload) // chunks)
        for i in range(0, len(payload), step):
            sock.sendall(payload[i:i + step])
            time.sleep(gap_s)
        resp = b""
        sock.settimeout(20)
        try:
            while b"\r\n\r\n" not in resp or len(resp) < 10:
                part = sock.recv(65536)
                if not part:
                    break
                resp += part
        except OSError:
            pass
        sock.close()
        status = int(resp.split(b" ", 2)[1])
        body = json.loads(resp.split(b"\r\n\r\n", 1)[1])
        return status, body

    def test_frames_fold_while_body_still_arriving(self, service):
        """Frames committed BEFORE the transport delivered the full body
        prove the decode is incremental, not buffered."""
        session = service.session("inc", "stream", _checks())
        exporter = service.start_exporter()
        table = _table(4000)
        payload = encode_ipc_stream(table, max_chunksize=1000)
        committed_mid_body = []

        orig = type(session)._commit_fold

        def spy(self, result, data, pending_contract, done):
            committed_mid_body.append(time.perf_counter())
            return orig(self, result, data, pending_contract, done)

        import deequ_tpu.service.streaming as streaming_mod

        streaming_mod.StreamingSession._commit_fold = spy
        try:
            t0 = time.perf_counter()
            status, body = self._post_chunked(
                exporter, "/ingest/v1/inc/stream", payload, chunks=8,
                gap_s=0.05,
            )
            last_byte_at = t0 + 7 * 0.05  # the 8th chunk leaves then
        finally:
            streaming_mod.StreamingSession._commit_fold = orig
        assert status == 200 and body["frames"] == 4
        assert session.batches_ingested == 4
        # at least the first frame folded before the final chunk was sent
        assert committed_mid_body[0] < last_byte_at

    def test_incremental_equivalent_to_buffered(self, service):
        """HTTP-fed (incremental) == checksummed HTTP-fed (buffered) ==
        in-process fold_stream, bit-exact."""
        import urllib.request

        table = _table(3000)
        payload = encode_ipc_stream(table, max_chunksize=1000)
        exporter = service.start_exporter()
        for name, headers in (
            ("plain", {}),
            ("csum", {CHECKSUM_HEADER: checksum_bytes(payload)}),
        ):
            service.session(f"eq-{name}", "s", _checks())
            req = urllib.request.Request(
                f"http://{exporter.host}:{exporter.port}"
                f"/ingest/v1/eq-{name}/s",
                data=payload, headers=headers, method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
        direct = service.session("eq-direct", "s", _checks())
        fold_stream(direct, payload, source="direct")
        maps = []
        for name in ("eq-plain", "eq-csum", "eq-direct"):
            s = service.get_session(name, "s")
            cum = s.current()
            maps.append({
                repr(a): m.value.get()
                for a, m in cum.metrics.items() if m.value.is_success
            })
        assert maps[0] == maps[1] == maps[2]

    def test_incremental_malformed_drains_and_400s(self, service):
        import urllib.error
        import urllib.request

        service.session("inc", "bad", _checks())
        exporter = service.start_exporter()
        req = urllib.request.Request(
            f"http://{exporter.host}:{exporter.port}/ingest/v1/inc/bad",
            data=b"definitely not an arrow stream, padded " + b"x" * 500,
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=30)
        assert err.value.code == 400
        assert json.loads(err.value.read())["error"] == "malformed_frame"

    def test_bounded_reader_contract(self):
        import io

        from deequ_tpu.ingest.arrow_stream import BoundedReader

        raw = io.BytesIO(b"abcdefghij")
        r = BoundedReader(raw, 6)
        assert r.read(4) == b"abcd"
        assert r.read(100) == b"ef"  # capped at the declared limit
        assert r.read(1) == b""
        assert r.bytes_read == 6 and not r.short
        short = BoundedReader(io.BytesIO(b"ab"), 10)
        assert short.read(10) == b"ab"
        assert short.short and short.bytes_read == 2
        short.drain()
        assert short.bytes_read == 2
