"""Persistent XLA compilation cache (VERDICT r3 weak #3): fresh processes
must hit the on-disk cache instead of re-paying tens of seconds of XLA
compiles. config.py enables jax_compilation_cache_dir by default
(opt out: DEEQU_TPU_NO_COMPILE_CACHE=1; relocate: DEEQU_TPU_COMPILE_CACHE)."""

import os
import subprocess
import sys

_WORKLOAD = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import deequ_tpu  # noqa: F401  (applies cache config on import)

hits = {"n": 0}
from jax._src import monitoring
def _listener(event, **kw):
    if "compilation_cache/cache_hits" in event:
        hits["n"] += 1
monitoring.register_event_listener(_listener)

from deequ_tpu.analyzers import Completeness, Mean, StandardDeviation
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner

rng = np.random.default_rng(5)
data = Dataset.from_dict({"x": rng.normal(size=50_000)})
ctx = AnalysisRunner.do_analysis_run(
    data, [Mean("x"), StandardDeviation("x"), Completeness("x")]
)
assert ctx.metric(Mean("x")).value.is_success
print("CACHE_HITS", hits["n"])
"""


def _run(cache_dir: str) -> int:
    env = dict(os.environ)
    env["DEEQU_TPU_COMPILE_CACHE"] = cache_dir
    env.pop("DEEQU_TPU_NO_COMPILE_CACHE", None)
    # force every compile to be cache-eligible regardless of compile time
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    out = subprocess.run(
        [sys.executable, "-c", _WORKLOAD],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    for line in out.stdout.splitlines():
        if line.startswith("CACHE_HITS"):
            return int(line.split()[1])
    raise AssertionError(out.stdout)


class TestPersistentCompilationCache:
    def test_populated_then_hit_across_processes(self, tmp_path):
        cache = str(tmp_path / "xla-cache")
        hits_cold = _run(cache)
        entries = os.listdir(cache)
        assert entries, "first process must populate the cache directory"
        hits_warm = _run(cache)
        assert hits_warm > hits_cold, (hits_cold, hits_warm)

    def test_opt_out_env(self, tmp_path):
        env = dict(os.environ)
        env["DEEQU_TPU_NO_COMPILE_CACHE"] = "1"
        env["DEEQU_TPU_COMPILE_CACHE"] = str(tmp_path / "never")
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms','cpu');"
             "import deequ_tpu;"
             "print(repr(jax.config.jax_compilation_cache_dir))"],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "never" not in out.stdout


class TestBoundedLruCaches:
    """VERDICT r4 #9: the bounded program caches evict least-recently-USED,
    so a hot key survives churn that previously (FIFO) evicted it."""

    def test_lru_keeps_hot_key_under_churn(self):
        from deequ_tpu.utils import BoundedLRU

        lru = BoundedLRU(4)
        lru["hot"] = "H"
        for i in range(100):
            lru[f"cold{i}"] = i
            assert lru.get("hot") == "H"  # touch -> stays resident
        assert len(lru) == 4

    def test_fifo_order_without_touches(self):
        from deequ_tpu.utils import BoundedLRU

        lru = BoundedLRU(2)
        lru["a"] = 1
        lru["b"] = 2
        lru["c"] = 3
        assert lru.get("a") is None and lru.get("b") == 2 and lru.get("c") == 3

    def test_merge_fold_cache_hot_key_survives(self):
        import numpy as np

        from deequ_tpu.analyzers import Mean
        from deequ_tpu.analyzers.base import _MERGE_FOLD_CACHE, merge_states_batched
        from deequ_tpu.analyzers.states import MeanState

        def state(v, c):
            return MeanState(np.float64(v), np.int64(c))

        hot = Mean("hot_col")
        merge_states_batched(hot, [state(1, 1), state(2, 1)])
        hot_key = (hot, 2)
        assert hot_key in _MERGE_FOLD_CACHE
        for i in range(_MERGE_FOLD_CACHE.max_size + 5):
            # churn with distinct shard counts; touch the hot key each time
            merge_states_batched(Mean(f"c{i}"), [state(1, 1)] * 3)
            merged = merge_states_batched(hot, [state(1, 1), state(2, 1)])
            assert hot_key in _MERGE_FOLD_CACHE
        assert float(merged.total) == 3.0
