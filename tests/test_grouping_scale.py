"""High-cardinality frequency machinery.

The reference leans on Spark's hash-aggregation shuffle for grouping
(`analyzers/GroupingAnalyzers.scala:53-80`); this build must match that
scalability on one host: amortized run-buffer accumulation (merge work
O(total entries), never O(batches x distinct)), an enforced entry budget,
and a device segment_sum path for dictionary-encoded low-cardinality sets
(SURVEY.md §7 step 6).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from deequ_tpu.analyzers import (
    CountDistinct,
    Distinctness,
    Entropy,
    Histogram,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.analyzers.grouping import (
    FrequenciesAndNumRows,
    MIN_FLUSH_ENTRIES,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor


class TestAmortizedAccumulation:
    def test_merge_work_linear_in_appended(self):
        """100 batches x 10k fresh keys: the old per-batch outer join
        re-touched the full table every batch (~50M entries of merge work);
        the amortized buffer must stay within a small constant of the 1M
        appended entries."""
        state = FrequenciesAndNumRows.empty(["k"])
        before = FrequenciesAndNumRows.merge_work
        per_batch, batches = 10_000, 100
        for i in range(batches):
            run = pd.Series(
                np.ones(per_batch, dtype=np.int64),
                index=pd.RangeIndex(i * per_batch, (i + 1) * per_batch),
            )
            state._append_run(run)
        assert len(state.frequencies) == per_batch * batches
        work = FrequenciesAndNumRows.merge_work - before
        assert work <= 8 * per_batch * batches, work

    def test_small_batches_buffer_below_flush_threshold(self):
        """Low-cardinality accumulation never flushes per batch: many small
        runs buffer until MIN_FLUSH_ENTRIES."""
        state = FrequenciesAndNumRows.empty(["k"])
        before = FrequenciesAndNumRows.merge_work
        for i in range(50):
            state._append_run(pd.Series(np.int64(1), index=pd.Index([f"v{i % 7}"])))
        assert 50 < MIN_FLUSH_ENTRIES
        assert FrequenciesAndNumRows.merge_work == before  # nothing flushed yet
        assert int(state.frequencies.sum()) == 50
        assert len(state.frequencies) == 7

    def test_high_cardinality_run_end_to_end(self):
        """A high-cardinality Uniqueness over many batches: values correct
        and merge work bounded (the quadratic path would blow the bound)."""
        n = 400_000
        rng = np.random.default_rng(5)
        keys = rng.integers(0, n, n)  # ~63% unique under birthday collisions
        data = Dataset.from_dict({"k": keys})
        before = FrequenciesAndNumRows.merge_work
        ctx = AnalysisRunner.do_analysis_run(
            data, [Uniqueness(["k"]), CountDistinct(["k"])], batch_size=8192
        )
        counts = pd.Series(keys).value_counts()
        assert ctx.metric(Uniqueness(["k"])).value.get() == pytest.approx(
            (counts == 1).sum() / n
        )
        assert ctx.metric(CountDistinct(["k"])).value.get() == len(counts)
        work = FrequenciesAndNumRows.merge_work - before
        assert work <= 10 * n, work

    def test_budget_enforced_as_failure_metric_when_spill_disabled(self, monkeypatch):
        # budget semantics belong to the HOST accumulator tier — the device
        # frequency table engine (the default route for this set since
        # ROADMAP item 3 landed) has its own overflow tiering and would
        # compute this exactly without ever touching the budget
        monkeypatch.setenv("DEEQU_TPU_DEVICE_FREQ", "0")
        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "1000")
        monkeypatch.setenv("DEEQU_TPU_FREQUENCY_SPILL", "0")
        data = Dataset.from_dict({"k": np.arange(200_000) % 150_000})
        ctx = AnalysisRunner.do_analysis_run(data, [Uniqueness(["k"])], batch_size=65536)
        value = ctx.metric(Uniqueness(["k"])).value
        assert value.is_failure
        assert "budget" in str(value.exception)

    def test_budget_spills_and_completes_by_default(self, monkeypatch):
        """VERDICT r3 weak #4: over-budget frequency tables spill to disk
        and the run COMPLETES (the Spark shuffle-spill analog) instead of
        raising FrequencyBudgetExceeded."""
        data = Dataset.from_dict({"k": np.arange(200_000) % 150_000})
        battery = [
            Uniqueness(["k"]), Distinctness(["k"]), CountDistinct(["k"]),
            Entropy("k"), UniqueValueRatio(["k"]),
        ]
        want = AnalysisRunner.do_analysis_run(data, battery, batch_size=65536)
        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "1000")
        got = AnalysisRunner.do_analysis_run(data, battery, batch_size=65536)
        for a in battery:
            assert got.metric(a).value.get() == pytest.approx(
                want.metric(a).value.get()
            ), a


def _dict_encoded(values) -> Dataset:
    arr = pa.array(values).dictionary_encode()
    return Dataset.from_arrow(pa.table({"c": arr}))


class TestDeviceFrequencyPath:
    BATTERY = [
        Uniqueness(["c"]),
        Distinctness(["c"]),
        CountDistinct(["c"]),
        Entropy("c"),
    ]

    def test_dictionary_column_matches_plain_column(self):
        rng = np.random.default_rng(11)
        values = [f"g{int(i)}" for i in rng.integers(0, 40, 20_000)]
        values[::97] = [None] * len(values[::97])
        plain = Dataset.from_dict({"c": values})
        encoded = _dict_encoded(values)
        ctx_p = AnalysisRunner.do_analysis_run(plain, self.BATTERY, batch_size=4096)
        ctx_e = AnalysisRunner.do_analysis_run(encoded, self.BATTERY, batch_size=4096)
        for a in self.BATTERY:
            assert ctx_e.metric(a).value.get() == pytest.approx(
                ctx_p.metric(a).value.get()
            ), a

    def test_device_path_does_no_host_frequency_work(self):
        """The dictionary-encoded grouping rides the device scan: zero
        host-side merge work."""
        values = [f"g{i % 30}" for i in range(30_000)]
        encoded = _dict_encoded(values)
        before = FrequenciesAndNumRows.merge_work
        mon = RunMonitor()
        ctx = AnalysisRunner.do_analysis_run(
            encoded, self.BATTERY, batch_size=4096, monitor=mon
        )
        assert mon.passes == 1
        assert FrequenciesAndNumRows.merge_work == before
        assert ctx.metric(CountDistinct(["c"])).value.get() == 30

    def test_numeric_dictionary_column(self):
        values = (np.arange(10_000) % 12).astype(np.int64)
        arr = pa.array(values).dictionary_encode()
        encoded = Dataset.from_arrow(pa.table({"c": arr}))
        ctx = AnalysisRunner.do_analysis_run(encoded, [CountDistinct(["c"]), Entropy("c")])
        assert ctx.metric(CountDistinct(["c"])).value.get() == 12
        assert ctx.metric(Entropy("c")).value.get() == pytest.approx(np.log(12), rel=1e-6)

    def test_histogram_on_dictionary_column(self):
        values = ["a", "b", "a", None, "c", "a"]
        encoded = _dict_encoded(values)
        ctx = AnalysisRunner.do_analysis_run(encoded, [Histogram("c")])
        dist = ctx.metric(Histogram("c")).value.get()
        assert dist.values["a"].absolute == 3
        assert dist.values["NullValue"].absolute == 1

    def test_dictionary_column_ordinary_analyzers(self):
        """Dictionary-encoded columns work for non-grouping analyzers too
        (completeness, distinct sketch) via the decoded values."""
        from deequ_tpu.analyzers import ApproxCountDistinct, Completeness

        values = [f"g{i % 25}" if i % 10 else None for i in range(5_000)]
        encoded = _dict_encoded(values)
        ctx = AnalysisRunner.do_analysis_run(
            encoded, [Completeness("c"), ApproxCountDistinct("c")]
        )
        assert ctx.metric(Completeness("c")).value.get() == pytest.approx(0.9)
        assert ctx.metric(ApproxCountDistinct("c")).value.get() == pytest.approx(25, abs=3)


class TestFrequencySpill:
    """Hash-partitioned spill (the Spark shuffle-spill analog,
    `GroupingAnalyzers.scala:53-80`): over-budget tables keep RAM bounded
    and stream final counts at metric time."""

    def test_resident_table_stays_bounded_and_counts_exact(self, monkeypatch):
        budget = 50_000
        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", str(budget))
        state = FrequenciesAndNumRows.empty(["k"])
        per_run, runs = 40_000, 12
        for i in range(runs):
            run = pd.Series(
                np.ones(per_run, dtype=np.int64),
                index=pd.RangeIndex(i * per_run, (i + 1) * per_run),
            )
            state._append_run(run)
            state._flush()
            assert len(state._merged) <= budget  # resident never over budget
        assert state.spilled
        total = 0
        seen = set()
        for chunk in state.iter_merged_chunks():
            assert (chunk.to_numpy() == 1).all()
            total += len(chunk)
            dup = seen.intersection(chunk.index)
            assert not dup, f"keys duplicated across chunks: {list(dup)[:5]}"
            seen.update(chunk.index)
        assert total == per_run * runs
        assert state.num_distinct() == per_run * runs

    def test_repeated_keys_sum_across_spill_events(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "1000")
        state = FrequenciesAndNumRows.empty(["k"])
        # every run holds the same 5000 keys: counts must sum across runs
        for _ in range(4):
            state._append_run(
                pd.Series(np.ones(5000, dtype=np.int64), index=pd.RangeIndex(5000))
            )
            state._flush()
        assert state.spilled
        chunks = list(state.iter_merged_chunks())
        merged = pd.concat(chunks)
        assert len(merged) == 5000
        assert (merged.to_numpy() == 4).all()

    def test_multicolumn_and_nan_keys_spill(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "100")
        rng = np.random.default_rng(3)
        a = rng.integers(0, 40, 4000).astype(np.float64)
        a[::11] = np.nan  # NaN VALUES form a real group key
        b = rng.choice(["x", "y", "z"], 4000)
        data = Dataset.from_dict({"a": a, "b": b})
        battery = [Uniqueness(["a", "b"]), CountDistinct(["a", "b"])]
        got = AnalysisRunner.do_analysis_run(data, battery, batch_size=512)
        monkeypatch.delenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES")
        want = AnalysisRunner.do_analysis_run(data, battery, batch_size=512)
        for an in battery:
            assert got.metric(an).value.get() == pytest.approx(
                want.metric(an).value.get()
            ), an

    def test_histogram_top_k_under_spill(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "500")
        # zipf-ish: key i appears (i % 97)+1 times; top bins well-defined
        keys = np.repeat(np.arange(5000), (np.arange(5000) % 97) + 1)
        data = Dataset.from_dict({"k": keys.astype(np.int64)})
        h = Histogram("k", max_detail_bins=10)
        got = AnalysisRunner.do_analysis_run(data, [h], batch_size=8192)
        dist = got.metric(h).value.get()
        assert dist.number_of_bins == 5000
        assert len(dist.values) == 10
        assert all(v.absolute == 97 for v in dist.values.values())

    def test_mutual_information_under_spill(self, monkeypatch):
        from deequ_tpu.analyzers import MutualInformation

        rng = np.random.default_rng(9)
        x = rng.integers(0, 200, 20_000)
        y = (x // 2 + rng.integers(0, 3, 20_000)) % 150  # dependent
        data = Dataset.from_dict({"x": x, "y": y})
        mi = MutualInformation(["x", "y"])
        want = AnalysisRunner.do_analysis_run(data, [mi], batch_size=4096)
        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "200")
        got = AnalysisRunner.do_analysis_run(data, [mi], batch_size=4096)
        assert got.metric(mi).value.get() == pytest.approx(
            want.metric(mi).value.get(), rel=1e-9
        )

    def test_spilled_state_persistence_fails_cleanly(self, monkeypatch, tmp_path):
        from deequ_tpu.analyzers.grouping import FrequencyBudgetExceeded
        from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "100")
        state = FrequenciesAndNumRows.empty(["k"])
        state._append_run(
            pd.Series(np.ones(5000, dtype=np.int64), index=pd.RangeIndex(5000))
        )
        state._flush()
        assert state.spilled
        sp = FileSystemStateProvider(str(tmp_path))
        with pytest.raises(FrequencyBudgetExceeded, match="materializ"):
            sp.persist(Uniqueness(["k"]), state)

    def test_spill_files_cleaned_up_on_gc(self, monkeypatch):
        import gc
        import os

        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "100")
        state = FrequenciesAndNumRows.empty(["k"])
        state._append_run(
            pd.Series(np.ones(500, dtype=np.int64), index=pd.RangeIndex(500))
        )
        state._flush()
        spill_dir = state._spill.dir
        assert os.path.isdir(spill_dir)
        del state
        gc.collect()
        assert not os.path.exists(spill_dir)

    def test_spill_with_column_named_count(self, monkeypatch):
        """Spill frames use sentinel column names, so user columns named
        'count' (or anything else) cannot collide."""
        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "100")
        data = Dataset.from_dict({"count": np.arange(5000) % 3000})
        ctx = AnalysisRunner.do_analysis_run(
            data, [Uniqueness(["count"]), CountDistinct(["count"])]
        )
        assert ctx.metric(CountDistinct(["count"])).value.get() == 3000.0
        assert ctx.metric(Uniqueness(["count"])).value.get() == pytest.approx(1000 / 5000)


class TestDictionaryFastPaths:
    """Dictionary-derived feature caches (type codes, lengths, hashes of
    DISTINCT values + per-row gathers) must give metrics identical to the
    plain-column paths, on both ingest tiers."""

    def _battery(self):
        from deequ_tpu.analyzers import (
            ApproxCountDistinct,
            Completeness,
            DataType,
            MaxLength,
            MinLength,
        )

        return [
            Completeness("c"), ApproxCountDistinct("c"), DataType("c"),
            MinLength("c"), MaxLength("c"),
        ]

    @pytest.mark.parametrize("placement", ["host", "device"])
    def test_dictionary_matches_plain(self, placement):
        rng = np.random.default_rng(17)
        pool = [f"value-{i:04d}"[: 4 + i % 7] for i in range(500)] + ["123", "4.5", "true"]
        values = [pool[i] for i in rng.integers(0, len(pool), 30_000)]
        values[::41] = [None] * len(values[::41])
        plain = Dataset.from_dict({"c": values})
        encoded = Dataset.from_arrow(
            pa.table({"c": pa.array(values).dictionary_encode()})
        )
        battery = self._battery()
        ctx_p = AnalysisRunner.do_analysis_run(plain, battery, placement=placement,
                                               batch_size=4096)
        ctx_e = AnalysisRunner.do_analysis_run(encoded, battery, placement=placement,
                                               batch_size=4096)
        for a in battery:
            got, want = ctx_e.metric(a).value.get(), ctx_p.metric(a).value.get()
            if isinstance(want, float):
                assert got == want, a
            else:  # DataType histogram distribution
                assert {k: v.absolute for k, v in got.values.items()} == {
                    k: v.absolute for k, v in want.values.items()
                }, a

    def test_dictionary_decoded_once_per_dataset(self):
        """Dictionary artifacts compute once per dataset, not once per
        batch (aux caches are shared across batches) — and a run whose
        consumers read arrow buffers directly never pays the python-object
        dictionary decode at all ("values" stays absent: lazy contract)."""
        from deequ_tpu.analyzers import DataType

        values = pa.array([f"v{i % 50}" for i in range(20_000)]).dictionary_encode()
        data = Dataset.from_arrow(pa.table({"c": values}))
        AnalysisRunner.do_analysis_run(
            data, [DataType("c")], placement="host", batch_size=1024
        )
        aux = data._dict_aux["c"]
        assert "type_codes" in aux
        assert "values" not in aux, "type inference should not decode objects"
        # the decode happens lazily — and lands in the shared cache — the
        # moment a python-level consumer asks for the dictionary
        for batch in data.batches(1024):
            assert batch.column("c").dictionary is not None
            break
        assert "values" in aux
