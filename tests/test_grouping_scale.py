"""High-cardinality frequency machinery.

The reference leans on Spark's hash-aggregation shuffle for grouping
(`analyzers/GroupingAnalyzers.scala:53-80`); this build must match that
scalability on one host: amortized run-buffer accumulation (merge work
O(total entries), never O(batches x distinct)), an enforced entry budget,
and a device segment_sum path for dictionary-encoded low-cardinality sets
(SURVEY.md §7 step 6).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from deequ_tpu.analyzers import (
    CountDistinct,
    Distinctness,
    Entropy,
    Histogram,
    Uniqueness,
)
from deequ_tpu.analyzers.grouping import (
    FrequenciesAndNumRows,
    MIN_FLUSH_ENTRIES,
)
from deequ_tpu.data import Dataset
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.engine import RunMonitor


class TestAmortizedAccumulation:
    def test_merge_work_linear_in_appended(self):
        """100 batches x 10k fresh keys: the old per-batch outer join
        re-touched the full table every batch (~50M entries of merge work);
        the amortized buffer must stay within a small constant of the 1M
        appended entries."""
        state = FrequenciesAndNumRows.empty(["k"])
        before = FrequenciesAndNumRows.merge_work
        per_batch, batches = 10_000, 100
        for i in range(batches):
            run = pd.Series(
                np.ones(per_batch, dtype=np.int64),
                index=pd.RangeIndex(i * per_batch, (i + 1) * per_batch),
            )
            state._append_run(run)
        assert len(state.frequencies) == per_batch * batches
        work = FrequenciesAndNumRows.merge_work - before
        assert work <= 8 * per_batch * batches, work

    def test_small_batches_buffer_below_flush_threshold(self):
        """Low-cardinality accumulation never flushes per batch: many small
        runs buffer until MIN_FLUSH_ENTRIES."""
        state = FrequenciesAndNumRows.empty(["k"])
        before = FrequenciesAndNumRows.merge_work
        for i in range(50):
            state._append_run(pd.Series(np.int64(1), index=pd.Index([f"v{i % 7}"])))
        assert 50 < MIN_FLUSH_ENTRIES
        assert FrequenciesAndNumRows.merge_work == before  # nothing flushed yet
        assert int(state.frequencies.sum()) == 50
        assert len(state.frequencies) == 7

    def test_high_cardinality_run_end_to_end(self):
        """A high-cardinality Uniqueness over many batches: values correct
        and merge work bounded (the quadratic path would blow the bound)."""
        n = 400_000
        rng = np.random.default_rng(5)
        keys = rng.integers(0, n, n)  # ~63% unique under birthday collisions
        data = Dataset.from_dict({"k": keys})
        before = FrequenciesAndNumRows.merge_work
        ctx = AnalysisRunner.do_analysis_run(
            data, [Uniqueness(["k"]), CountDistinct(["k"])], batch_size=8192
        )
        counts = pd.Series(keys).value_counts()
        assert ctx.metric(Uniqueness(["k"])).value.get() == pytest.approx(
            (counts == 1).sum() / n
        )
        assert ctx.metric(CountDistinct(["k"])).value.get() == len(counts)
        work = FrequenciesAndNumRows.merge_work - before
        assert work <= 10 * n, work

    def test_budget_enforced_as_failure_metric(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_MAX_FREQUENCY_ENTRIES", "1000")
        data = Dataset.from_dict({"k": np.arange(200_000) % 150_000})
        ctx = AnalysisRunner.do_analysis_run(data, [Uniqueness(["k"])], batch_size=65536)
        value = ctx.metric(Uniqueness(["k"])).value
        assert value.is_failure
        assert "budget" in str(value.exception)


def _dict_encoded(values) -> Dataset:
    arr = pa.array(values).dictionary_encode()
    return Dataset.from_arrow(pa.table({"c": arr}))


class TestDeviceFrequencyPath:
    BATTERY = [
        Uniqueness(["c"]),
        Distinctness(["c"]),
        CountDistinct(["c"]),
        Entropy("c"),
    ]

    def test_dictionary_column_matches_plain_column(self):
        rng = np.random.default_rng(11)
        values = [f"g{int(i)}" for i in rng.integers(0, 40, 20_000)]
        values[::97] = [None] * len(values[::97])
        plain = Dataset.from_dict({"c": values})
        encoded = _dict_encoded(values)
        ctx_p = AnalysisRunner.do_analysis_run(plain, self.BATTERY, batch_size=4096)
        ctx_e = AnalysisRunner.do_analysis_run(encoded, self.BATTERY, batch_size=4096)
        for a in self.BATTERY:
            assert ctx_e.metric(a).value.get() == pytest.approx(
                ctx_p.metric(a).value.get()
            ), a

    def test_device_path_does_no_host_frequency_work(self):
        """The dictionary-encoded grouping rides the device scan: zero
        host-side merge work."""
        values = [f"g{i % 30}" for i in range(30_000)]
        encoded = _dict_encoded(values)
        before = FrequenciesAndNumRows.merge_work
        mon = RunMonitor()
        ctx = AnalysisRunner.do_analysis_run(
            encoded, self.BATTERY, batch_size=4096, monitor=mon
        )
        assert mon.passes == 1
        assert FrequenciesAndNumRows.merge_work == before
        assert ctx.metric(CountDistinct(["c"])).value.get() == 30

    def test_numeric_dictionary_column(self):
        values = (np.arange(10_000) % 12).astype(np.int64)
        arr = pa.array(values).dictionary_encode()
        encoded = Dataset.from_arrow(pa.table({"c": arr}))
        ctx = AnalysisRunner.do_analysis_run(encoded, [CountDistinct(["c"]), Entropy("c")])
        assert ctx.metric(CountDistinct(["c"])).value.get() == 12
        assert ctx.metric(Entropy("c")).value.get() == pytest.approx(np.log(12), rel=1e-6)

    def test_histogram_on_dictionary_column(self):
        values = ["a", "b", "a", None, "c", "a"]
        encoded = _dict_encoded(values)
        ctx = AnalysisRunner.do_analysis_run(encoded, [Histogram("c")])
        dist = ctx.metric(Histogram("c")).value.get()
        assert dist.values["a"].absolute == 3
        assert dist.values["NullValue"].absolute == 1

    def test_dictionary_column_ordinary_analyzers(self):
        """Dictionary-encoded columns work for non-grouping analyzers too
        (completeness, distinct sketch) via the decoded values."""
        from deequ_tpu.analyzers import ApproxCountDistinct, Completeness

        values = [f"g{i % 25}" if i % 10 else None for i in range(5_000)]
        encoded = _dict_encoded(values)
        ctx = AnalysisRunner.do_analysis_run(
            encoded, [Completeness("c"), ApproxCountDistinct("c")]
        )
        assert ctx.metric(Completeness("c")).value.get() == pytest.approx(0.9)
        assert ctx.metric(ApproxCountDistinct("c")).value.get() == pytest.approx(25, abs=3)
