"""Constraint suggestion tests — the analog of the reference
`suggestions/*Test.scala` + `ConstraintSuggestionsIntegrationTest.scala`."""

import json

import numpy as np
import pytest

from deequ_tpu.data import Dataset
from deequ_tpu.suggestions import ConstraintSuggestionRunner, Rules
from deequ_tpu.suggestions.rules import (
    CategoricalRangeRule,
    CompleteIfCompleteRule,
    FractionalCategoricalRangeRule,
    NonNegativeNumbersRule,
    RetainCompletenessRule,
    RetainTypeRule,
    UniqueIfApproximatelyUniqueRule,
)


@pytest.fixture
def suggestion_data():
    n = 200
    rng = np.random.default_rng(0)
    import pyarrow as pa

    return Dataset.from_arrow(
        pa.table(
            {
                "id": pa.array([str(i) for i in range(n)]),
                "status": pa.array(
                    [("ACTIVE", "INACTIVE", "DELETED")[i % 3] for i in range(n)]
                ),
                "mostly_complete": pa.array(
                    [float(i) if i % 10 else None for i in range(n)]
                ),
                "count_str": pa.array([str(i % 50) for i in range(n)]),
                "views": pa.array(rng.integers(0, 100, n)),
            }
        )
    )


class TestRules:
    def test_default_set(self):
        names = [type(r).__name__ for r in Rules.DEFAULT]
        assert names == [
            "CompleteIfCompleteRule",
            "RetainCompletenessRule",
            "RetainTypeRule",
            "CategoricalRangeRule",
            "FractionalCategoricalRangeRule",
            "NonNegativeNumbersRule",
        ]

    def test_end_to_end_suggestions(self, suggestion_data):
        result = (
            ConstraintSuggestionRunner.on_data(suggestion_data)
            .add_constraint_rules(Rules.DEFAULT)
            .run()
        )
        assert result.num_records == 200
        by_col = result.constraint_suggestions
        # complete columns -> isComplete
        codes = [s.code_for_constraint for s in by_col.get("id", [])]
        assert any("is_complete" in c for c in codes)
        # categorical string column -> is_contained_in
        status_codes = [s.code_for_constraint for s in by_col.get("status", [])]
        assert any("is_contained_in" in c for c in status_codes)
        # incomplete column -> has_completeness with lower bound
        mc = [s.code_for_constraint for s in by_col.get("mostly_complete", [])]
        assert any("has_completeness" in c for c in mc)
        # numeric string column -> type constraint
        cs = [s.code_for_constraint for s in by_col.get("count_str", [])]
        assert any("has_data_type" in c for c in cs)
        # non-negative ints
        vw = [s.code_for_constraint for s in by_col.get("views", [])]
        assert any("is_non_negative" in c for c in vw)

    def test_suggested_constraints_evaluate_cleanly(self, suggestion_data):
        """Applying the suggested constraints to the SAME data must succeed
        (suggestions describe the data)."""
        from deequ_tpu.checks import Check, CheckLevel
        from deequ_tpu.constraints import ConstraintStatus
        from deequ_tpu.verification import VerificationSuite

        result = (
            ConstraintSuggestionRunner.on_data(suggestion_data)
            .add_constraint_rules(Rules.DEFAULT)
            .run()
        )
        check = Check(CheckLevel.ERROR, "suggested")
        for s in result.all_suggestions:
            check = check.add_constraint(s.constraint)
        verification = VerificationSuite.on_data(suggestion_data).add_check(check).run()
        failures = [
            (str(cr.constraint), cr.message)
            for r in verification.check_results.values()
            for cr in r.constraint_results
            if cr.status == ConstraintStatus.FAILURE
        ]
        # known reference wart carried over: NonNegativeNumbersRule emits
        # `col >= 0` whose compliance counts nulls as non-compliant, so it
        # fails on incomplete columns (reference
        # `rules/NonNegativeNumbersRule.scala` has the same behavior)
        unexpected = [f for f in failures if "mostly_complete" not in f[0]]
        assert unexpected == []
        assert len(failures) <= 1

    def test_train_test_split_evaluation(self, suggestion_data, tmp_path):
        eval_path = str(tmp_path / "eval.json")
        sugg_path = str(tmp_path / "suggestions.json")
        result = (
            ConstraintSuggestionRunner.on_data(suggestion_data)
            .add_constraint_rules(Rules.DEFAULT)
            .use_train_test_split_with_testset_ratio(0.3, testset_split_random_seed=7)
            .save_constraint_suggestions_json_to_path(sugg_path)
            .save_evaluation_results_json_to_path(eval_path)
            .run()
        )
        assert result.verification_result is not None
        payload = json.loads(open(eval_path).read())
        assert len(payload["constraint_suggestions"]) == len(result.all_suggestions)
        sugg = json.loads(open(sugg_path).read())
        assert {s["column_name"] for s in sugg["constraint_suggestions"]}

    def test_invalid_testset_ratio(self, suggestion_data):
        with pytest.raises(ValueError):
            ConstraintSuggestionRunner.on_data(
                suggestion_data
            ).add_constraint_rules(Rules.DEFAULT).use_train_test_split_with_testset_ratio(
                1.5
            ).run()


class TestIndividualRules:
    def _profile(self, **kwargs):
        from deequ_tpu.profiles import NumericColumnProfile, StandardColumnProfile

        numeric = kwargs.pop("numeric", False)
        defaults = dict(
            column="col",
            completeness=1.0,
            approximate_num_distinct_values=10,
            data_type="String",
            is_data_type_inferred=True,
        )
        defaults.update(kwargs)
        cls = NumericColumnProfile if numeric else StandardColumnProfile
        return cls(**defaults)

    def test_complete_if_complete(self):
        rule = CompleteIfCompleteRule()
        assert rule.should_be_applied(self._profile(completeness=1.0), 100)
        assert not rule.should_be_applied(self._profile(completeness=0.99), 100)

    def test_retain_completeness_bounds(self):
        rule = RetainCompletenessRule()
        assert rule.should_be_applied(self._profile(completeness=0.5), 100)
        assert not rule.should_be_applied(self._profile(completeness=0.1), 100)
        assert not rule.should_be_applied(self._profile(completeness=1.0), 100)
        s = rule.candidate(self._profile(completeness=0.5), 100)
        # evaluate the generated assertion: target = 0.5 - 1.96*sqrt(0.25/100)
        target = 0.40  # rounded down to 2 decimals
        assert f"{target}" in s.code_for_constraint

    def test_retain_type(self):
        rule = RetainTypeRule()
        assert rule.should_be_applied(
            self._profile(data_type="Integral", is_data_type_inferred=True), 10
        )
        assert not rule.should_be_applied(
            self._profile(data_type="Integral", is_data_type_inferred=False), 10
        )
        assert not rule.should_be_applied(
            self._profile(data_type="String", is_data_type_inferred=True), 10
        )

    def test_categorical_range_rule(self):
        from deequ_tpu.metrics import Distribution, DistributionValue

        hist = Distribution(
            {"a": DistributionValue(50, 0.5), "b": DistributionValue(50, 0.5)}, 2
        )
        rule = CategoricalRangeRule()
        assert rule.should_be_applied(self._profile(histogram=hist), 100)
        # mostly-unique histogram -> not applied
        unique_hist = Distribution(
            {str(i): DistributionValue(1, 0.01) for i in range(100)}, 100
        )
        assert not rule.should_be_applied(self._profile(histogram=unique_hist), 100)

    def test_non_negative_rule(self):
        rule = NonNegativeNumbersRule()
        assert rule.should_be_applied(
            self._profile(numeric=True, data_type="Integral", minimum=0.0), 10
        )
        assert not rule.should_be_applied(
            self._profile(numeric=True, data_type="Integral", minimum=-1.0), 10
        )
        assert not rule.should_be_applied(self._profile(), 10)

    def test_unique_if_approximately_unique(self):
        rule = UniqueIfApproximatelyUniqueRule()
        assert rule.should_be_applied(
            self._profile(approximate_num_distinct_values=97), 100
        )
        assert not rule.should_be_applied(
            self._profile(approximate_num_distinct_values=50), 100
        )
        assert not rule.should_be_applied(
            self._profile(approximate_num_distinct_values=97, completeness=0.9), 100
        )
