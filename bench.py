"""Benchmark: fused single-pass analyzer scan throughput on the real device.

Measures the BASELINE.json north-star proxy — analyzer-engine rows/sec/chip
on a representative battery (completeness, moments, min/max, HLL distinct,
KLL quantile sketch over multiple columns) — and compares against a
single-core pandas/numpy oracle computing the same metrics on the same data
(the stand-in for the reference's Spark-local per-core throughput; the
reference publishes no numbers, BASELINE.md).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_data(rows: int):
    import pyarrow as pa

    rng = np.random.default_rng(42)
    cols = {}
    for i in range(4):
        vals = rng.normal(100 * i, 10, rows)
        nulls = rng.random(rows) < 0.05
        cols[f"x{i}"] = pa.array(vals, mask=nulls)
    cols["cat"] = pa.array(rng.integers(0, 100_000, rows))
    return pa.table(cols)


def analyzer_battery():
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        KLLParameters,
        KLLSketch,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
        Sum,
    )

    analyzers = []
    for i in range(4):
        c = f"x{i}"
        analyzers += [
            Completeness(c), Mean(c), Sum(c), Minimum(c), Maximum(c),
            StandardDeviation(c),
        ]
    analyzers.append(ApproxCountDistinct("cat"))
    analyzers += [KLLSketch("x0", KLLParameters(2048, 0.64, 100)),
                  KLLSketch("x1", KLLParameters(2048, 0.64, 100))]
    return analyzers


def run_tpu(table, batch_size: int) -> tuple[float, dict]:
    import jax

    from deequ_tpu.data import Dataset
    from deequ_tpu.runners import AnalysisRunner
    from deequ_tpu.runners.engine import RunMonitor, probe_feed_bandwidth

    data = Dataset.from_arrow(table)
    analyzers = analyzer_battery()
    log(f"devices: {jax.devices()}")
    log(f"feed-link probe: {probe_feed_bandwidth():.0f} MB/s")

    # warmup: compile the programs on one batch (placement-stable: the
    # ingest fold has a fixed chunk shape, so this hits every program)
    warm = Dataset.from_arrow(table.slice(0, batch_size))
    AnalysisRunner.do_analysis_run(warm, analyzers, batch_size=batch_size)

    mon = RunMonitor()
    t0 = time.perf_counter()
    ctx = AnalysisRunner.do_analysis_run(
        data, analyzers, batch_size=batch_size, monitor=mon
    )
    elapsed = time.perf_counter() - t0
    assert mon.passes == 1
    values = {}
    for a, m in ctx.metric_map.items():
        if m.value.is_success and a.name in ("Completeness", "Mean", "Sum"):
            values[f"{a.name}:{a.instance}"] = m.value.get()
    return elapsed, values


def run_pandas_baseline(table, rows: int) -> tuple[float, dict]:
    """Same metrics, single-core pandas/numpy on the full data."""
    df = table.to_pandas()
    t0 = time.perf_counter()
    values = {}
    for i in range(4):
        c = f"x{i}"
        s = df[c]
        values[f"Completeness:{c}"] = s.notna().mean()
        values[f"Mean:{c}"] = s.mean()
        values[f"Sum:{c}"] = s.sum()
        s.min(); s.max(); s.std(ddof=0)
    df["cat"].nunique()
    np.nanquantile(df["x0"].to_numpy(), np.linspace(0.01, 1, 100))
    np.nanquantile(df["x1"].to_numpy(), np.linspace(0.01, 1, 100))
    elapsed = time.perf_counter() - t0
    return elapsed, values


def main() -> None:
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000_000
    batch_size = 1 << 20
    log(f"building {rows:,}-row table")
    table = build_data(rows)

    tpu_s, tpu_vals = run_tpu(table, batch_size)
    log(f"tpu pass: {tpu_s:.2f}s ({rows / tpu_s / 1e6:.2f}M rows/s)")
    base_s, base_vals = run_pandas_baseline(table, rows)
    log(f"measured single-core pandas baseline: {base_s:.2f}s")

    # metric parity guard: same answers as the oracle (±1e-6 relative)
    for k, v in base_vals.items():
        tv = tpu_vals[k]
        if abs(tv - v) > 1e-6 * max(1.0, abs(v)):
            log(f"PARITY MISMATCH {k}: tpu={tv} oracle={v}")
            sys.exit(1)

    rows_per_sec = rows / tpu_s
    print(
        json.dumps(
            {
                "metric": "analyzer_scan_rows_per_sec_per_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / (rows / base_s), 2),
            }
        )
    )


if __name__ == "__main__":
    main()
