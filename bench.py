"""Benchmarks on the real device, mirroring the BASELINE.json configs.

1. **Scan battery** (BASELINE config 2 shape): fused single-pass analyzer
   scan over a 50M-row table — completeness, moments, min/max, HLL distinct,
   KLL quantile sketches.
2. **Column profiler** (BASELINE config 3 shape, the north-star metric):
   `ColumnProfilerRunner` full profile over a wide mixed-type table
   (numeric + string + categorical columns), reporting rows/sec/chip.

Each stage compares against a single-core pandas/numpy oracle computing the
same statistics on the same data (the stand-in for the reference's
Spark-local per-core throughput; the reference publishes no numbers,
BASELINE.md). Prints ONE json line with the north-star profiler metric;
the scan-battery numbers land in the stderr tail.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# stage 1: scan battery (BASELINE config 2)
# ---------------------------------------------------------------------------


def build_scan_data(rows: int):
    import pyarrow as pa

    rng = np.random.default_rng(42)
    cols = {}
    for i in range(4):
        vals = rng.normal(100 * i, 10, rows)
        nulls = rng.random(rows) < 0.05
        cols[f"x{i}"] = pa.array(vals, mask=nulls)
    cols["cat"] = pa.array(rng.integers(0, 100_000, rows))
    return pa.table(cols)


def scan_battery():
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        KLLParameters,
        KLLSketch,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
        Sum,
    )

    analyzers = []
    for i in range(4):
        c = f"x{i}"
        analyzers += [
            Completeness(c), Mean(c), Sum(c), Minimum(c), Maximum(c),
            StandardDeviation(c),
        ]
    analyzers.append(ApproxCountDistinct("cat"))
    analyzers += [KLLSketch("x0", KLLParameters(2048, 0.64, 100)),
                  KLLSketch("x1", KLLParameters(2048, 0.64, 100))]
    return analyzers


def run_scan_stage(rows: int, batch_size: int) -> dict:
    import pyarrow as pa

    from deequ_tpu.data import Dataset
    from deequ_tpu.runners import AnalysisRunner
    from deequ_tpu.runners.engine import RunMonitor

    log(f"[scan] building {rows:,}-row table")
    table = build_scan_data(rows)
    data = Dataset.from_arrow(table)
    analyzers = scan_battery()

    warm = Dataset.from_arrow(table.slice(0, batch_size))
    AnalysisRunner.do_analysis_run(warm, analyzers, batch_size=batch_size)

    mon = RunMonitor()
    t0 = time.perf_counter()
    ctx = AnalysisRunner.do_analysis_run(
        data, analyzers, batch_size=batch_size, monitor=mon
    )
    elapsed = time.perf_counter() - t0
    assert mon.passes == 1
    tpu_vals = {}
    for a, m in ctx.metric_map.items():
        if m.value.is_success and a.name in ("Completeness", "Mean", "Sum"):
            tpu_vals[f"{a.name}:{a.instance}"] = m.value.get()

    df = table.to_pandas()
    t0 = time.perf_counter()
    base_vals = {}
    for i in range(4):
        c = f"x{i}"
        s = df[c]
        base_vals[f"Completeness:{c}"] = s.notna().mean()
        base_vals[f"Mean:{c}"] = s.mean()
        base_vals[f"Sum:{c}"] = s.sum()
        s.min(); s.max(); s.std(ddof=0)
    df["cat"].nunique()
    np.nanquantile(df["x0"].to_numpy(), np.linspace(0.01, 1, 100))
    np.nanquantile(df["x1"].to_numpy(), np.linspace(0.01, 1, 100))
    base_s = time.perf_counter() - t0

    for k, v in base_vals.items():
        tv = tpu_vals[k]
        if abs(tv - v) > 1e-6 * max(1.0, abs(v)):
            log(f"PARITY MISMATCH {k}: tpu={tv} oracle={v}")
            sys.exit(1)
    rate = rows / elapsed
    phases = ", ".join(f"{k}={v:.2f}s" for k, v in sorted(mon.phase_seconds.items()))
    log(
        f"[scan] {rows:,} rows x {len(analyzers)} analyzers: {elapsed:.2f}s "
        f"({rate/1e6:.2f}M rows/s/chip), single-core pandas {base_s:.2f}s "
        f"-> {rate/(rows/base_s):.1f}x"
    )
    log(f"[scan] placement={mon.placement} phases: {phases}")
    return {"rows_per_sec": rate, "vs_single_core": rate / (rows / base_s)}


# ---------------------------------------------------------------------------
# stage 2: column profiler on a wide mixed table (BASELINE config 3)
# ---------------------------------------------------------------------------

N_NUMERIC = 16
N_STRING = 4
N_CAT = 4


def build_wide_data(rows: int):
    import pyarrow as pa

    rng = np.random.default_rng(7)
    cols = {}
    for i in range(N_NUMERIC):
        vals = rng.normal(10 * i, 1 + i, rows)
        if i % 3 == 0:
            cols[f"n{i}"] = pa.array(vals, mask=rng.random(rows) < 0.02)
        else:
            cols[f"n{i}"] = pa.array(vals)
    base = np.array([f"id_{i:07d}" for i in range(100_000)])
    for i in range(N_STRING):
        cols[f"s{i}"] = pa.array(base[rng.integers(0, len(base), rows)])
    for i in range(N_CAT):
        card = 20 * (i + 1)
        cats = np.array([f"c{j}" for j in range(card)])
        cols[f"c{i}"] = pa.array(cats[rng.integers(0, card, rows)])
    return pa.table(cols)


def run_profile_stage(rows: int) -> dict:
    from deequ_tpu.data import Dataset
    from deequ_tpu.profiles import ColumnProfilerRunner
    from deequ_tpu.runners.engine import RunMonitor

    n_cols = N_NUMERIC + N_STRING + N_CAT
    log(f"[profile] building {rows:,}-row x {n_cols}-col mixed table")
    table = build_wide_data(rows)
    data = Dataset.from_arrow(table)

    # warmup on a slice: compile every program shape the profile needs
    warm = Dataset.from_arrow(table.slice(0, 1 << 18))
    ColumnProfilerRunner.on_data(warm).run()

    mon = RunMonitor()
    t0 = time.perf_counter()
    profiles = ColumnProfilerRunner.on_data(data).with_monitor(mon).run()
    elapsed = time.perf_counter() - t0
    rate = rows / elapsed
    phases = ", ".join(f"{k}={v:.2f}s" for k, v in sorted(mon.phase_seconds.items()))
    log(f"[profile] passes={mon.passes} placement={mon.placement} phases: {phases}")

    # single-core pandas oracle: the same per-column statistics
    df = table.to_pandas()
    t0 = time.perf_counter()
    base_vals = {}
    for name in df.columns:
        s = df[name]
        s.notna().mean()
        nunique = s.nunique()
        if s.dtype.kind == "f":
            base_vals[name] = (s.mean(), s.min(), s.max(), s.std(ddof=0), s.sum())
            np.nanquantile(s.to_numpy(), np.linspace(0.01, 1, 100))
        if nunique <= 120:
            s.value_counts()
    base_s = time.perf_counter() - t0

    # parity guard on the numeric profiles
    for name, (mean, mn, mx, std, total) in base_vals.items():
        p = profiles.profiles[name]
        for got, want in ((p.mean, mean), (p.minimum, mn), (p.maximum, mx),
                          (p.std_dev, std), (p.sum, total)):
            if abs(got - want) > 1e-6 * max(1.0, abs(want)):
                log(f"PARITY MISMATCH {name}: got={got} want={want}")
                sys.exit(1)
    complete = len(profiles.profiles)
    log(
        f"[profile] {rows:,} rows x {n_cols} cols ({complete} profiled): "
        f"{elapsed:.2f}s ({rate/1e6:.2f}M rows/s/chip), single-core pandas "
        f"{base_s:.2f}s -> {rate/(rows/base_s):.1f}x"
    )
    return {"rows_per_sec": rate, "vs_single_core": rate / (rows / base_s)}


# ---------------------------------------------------------------------------
# stage 3: incremental/stateful partitions + sketch-state merge (BASELINE
# config 4: partition states persisted, table metrics refreshed from merged
# states WITHOUT rescanning data, anomaly check on the history)
# ---------------------------------------------------------------------------


def run_incremental_stage(rows_per_partition: int, n_partitions: int = 8) -> dict:
    import jax

    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        KLLSketch,
        Mean,
        Size,
    )
    from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
    from deequ_tpu.data import Dataset
    from deequ_tpu.runners import AnalysisRunner

    analyzers = [Size(), Completeness("x0"), Mean("x0"),
                 ApproxCountDistinct("cat"), KLLSketch("x0")]
    log(f"[incremental] {n_partitions} partitions x {rows_per_partition:,} rows")
    providers = []
    table = build_scan_data(rows_per_partition * n_partitions)
    for p in range(n_partitions):
        part = Dataset.from_arrow(
            table.slice(p * rows_per_partition, rows_per_partition)
        )
        sp = InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(part, analyzers, save_states_with=sp)
        providers.append(sp)
    schema = Dataset.from_arrow(table.slice(0, 1)).schema

    # warm the merge programs, then time the state-only refresh
    AnalysisRunner.run_on_aggregated_states(schema, analyzers, providers)
    state_bytes = 0
    for sp in providers:
        for a in analyzers:
            state = sp.load(a)
            leaves = jax.tree_util.tree_leaves(state)
            state_bytes += sum(np.asarray(x).nbytes for x in leaves)
    t0 = time.perf_counter()
    ctx = AnalysisRunner.run_on_aggregated_states(schema, analyzers, providers)
    merge_s = time.perf_counter() - t0
    total_rows = rows_per_partition * n_partitions
    assert ctx.metric(Size()).value.get() == float(total_rows)
    log(
        f"[incremental] table metrics refreshed from {n_partitions} partition "
        f"states in {merge_s*1e3:.0f}ms — no data rescan "
        f"({state_bytes/1e6:.1f}MB of sketch states, "
        f"{state_bytes/merge_s/1e9:.2f}GB/s merge)"
    )
    return {"merge_seconds": merge_s, "state_bytes": state_bytes}


# ---------------------------------------------------------------------------
# stage 4: constraint suggestion on the wide mixed table (BASELINE config 5
# shape: profile + rule application + held-out evaluation of the suggested
# constraints)
# ---------------------------------------------------------------------------


def run_suggestion_stage(rows: int) -> dict:
    from deequ_tpu.data import Dataset
    from deequ_tpu.suggestions import ConstraintSuggestionRunner, Rules

    n_cols = N_NUMERIC + N_STRING + N_CAT
    log(f"[suggest] {rows:,}-row x {n_cols}-col constraint suggestion run")
    table = build_wide_data(rows)
    data = Dataset.from_arrow(table)

    def run_once() -> tuple:
        t0 = time.perf_counter()
        result = (
            ConstraintSuggestionRunner.on_data(data)
            .add_constraint_rules(Rules.DEFAULT)
            .use_train_test_split_with_testset_ratio(0.25, testset_split_random_seed=0)
            .run()
        )
        return time.perf_counter() - t0, result

    # the held-out evaluation's constraint battery is data-dependent, so its
    # fused fold program compiles on first use; report cold (incl. compile)
    # and warm (program-cache hit) separately like the other stages' warmups
    cold_s, result = run_once()
    warm_s, result = run_once()
    n_suggestions = len(result.all_suggestions)
    evaluated = result.verification_result is not None
    log(
        f"[suggest] {n_suggestions} suggestions over {len(result.column_profiles)} "
        f"columns: cold {cold_s:.2f}s (incl. compiles), warm {warm_s:.2f}s "
        f"({rows/warm_s/1e6:.2f}M rows/s, held-out evaluation="
        f"{'yes' if evaluated else 'no'})"
    )
    return {"seconds": warm_s, "suggestions": n_suggestions}


def main() -> None:
    import jax

    from deequ_tpu.runners.engine import probe_feed_bandwidth

    scan_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000_000
    profile_rows = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000_000
    log(f"devices: {jax.devices()}")
    log(f"feed-link probe: {probe_feed_bandwidth():.0f} MB/s")

    scan = run_scan_stage(scan_rows, batch_size=1 << 20)
    profile = run_profile_stage(profile_rows)
    incremental = run_incremental_stage(max(scan_rows // 50, 100_000))
    suggest = run_suggestion_stage(max(profile_rows // 5, 100_000))

    print(
        json.dumps(
            {
                "metric": "column_profiler_rows_per_sec_per_chip",
                "value": round(profile["rows_per_sec"], 1),
                "unit": "rows/s",
                "vs_baseline": round(profile["vs_single_core"], 2),
                "scan_rows_per_sec_per_chip": round(scan["rows_per_sec"], 1),
                "scan_vs_baseline": round(scan["vs_single_core"], 2),
                "state_merge_seconds": round(incremental["merge_seconds"], 3),
                "state_merge_bytes": incremental["state_bytes"],
                "suggest_seconds": round(suggest["seconds"], 2),
                "suggestions": suggest["suggestions"],
            }
        )
    )


if __name__ == "__main__":
    main()
